"""Scaling-projection tool: HLO comm-byte extraction + end-to-end run.

The virtual CPU mesh cannot measure scaling efficiency (all devices share
one host core); `tools/scaling_projection.py` provides the relative signal
instead — comm bytes and FLOPs from the COMPILED step, rolled into the ring
roofline. These tests pin the extraction against ground truth (gradient
bytes == 4 B x param count for the fp32-gradient DP step)."""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, os.path.join(_REPO, "tools"))
from scaling_projection import comm_bytes_from_hlo  # noqa: E402


def test_comm_bytes_extraction():
    hlo = """
  %ar0 = f32[1000,512] all-reduce(f32[1000,512] %p0), replica_groups={}
  %ar1 = bf16[256] all-reduce(bf16[256] %p1), replica_groups={}
  %t = (f32[10], s32[4]) all-reduce(%a, %b)
  %ag = f32[64,8] all-gather(f32[8,8] %p2), dimensions={0}
  %cp = bf16[4,128] collective-permute(bf16[4,128] %p3), source_target_pairs={{0,1}}
  %a2a = f32[16,2] all-to-all(f32[16,2] %p4), dimensions={0}
  %other = f32[999] add(f32[999] %x, f32[999] %y)
"""
    want = (1000 * 512 * 4 + 256 * 2 + (10 * 4 + 4 * 4) + 64 * 8 * 4
            + 4 * 128 * 2 + 16 * 2 * 4)
    assert comm_bytes_from_hlo(hlo) == want


def test_comm_bytes_async_pairs_counted_once():
    hlo = """
  %s = f32[100] all-reduce-start(f32[100] %p0)
  %d = f32[100] all-reduce-done(f32[100] %s)
  %cs = bf16[8] collective-permute-start(bf16[8] %p1)
  %cd = bf16[8] collective-permute-done(bf16[8] %cs)
  %ags = (f32[8,8], f32[64,8]) all-gather-start(f32[8,8] %p2), dimensions={0}
  %agd = f32[64,8] all-gather-done(%ags)
"""
    # tuple-shaped -start ops count only the result (largest) element
    assert comm_bytes_from_hlo(hlo) == 100 * 4 + 8 * 2 + 64 * 8 * 4


def test_comm_time_model():
    from scaling_projection import comm_ops_from_hlo, comm_time_s

    hlo = """
  %ar = f32[100] all-reduce(f32[100] %a), replica_groups={{0,1,2,3},{4,5,6,7}}
  %cp = f32[50] collective-permute(f32[50] %b), source_target_pairs={{0,1}}
  %ag = f32[80] all-gather(f32[20] %c), replica_groups=[2,4]<=[8], dimensions={0}
"""
    ops = comm_ops_from_hlo(hlo)
    assert [(o, g) for o, _, g in ops] == [
        ("all-reduce", 4), ("collective-permute", 0), ("all-gather", 4)]
    bw = 1e9
    t = comm_time_s(ops, bw, default_group=8)
    want = (2 * 3 / 4 * 400 + 50 * 4 + 3 / 4 * 320) / bw
    assert abs(t - want) < 1e-12


def test_zero1_sync_byte_model():
    """RS+AG decomposition (ZeRO-1 sharded optimizer): the reduce-scatter
    leg moves exactly half the allreduce's gradient bytes, and the total
    (RS + update all-gather) is ring-equal at full precision."""
    from scaling_projection import zero1_sync_bytes

    B = 4 * 25_600_000  # fp32 ResNet-50-ish gradient volume
    n = 8
    m = zero1_sync_bytes(B, n)
    ring = (n - 1) / n
    assert m["allreduce"] == 2 * ring * B
    assert m["rs"] == ring * B == m["allreduce"] / 2
    assert m["ag"] == ring * B
    assert m["sharded_total"] == m["allreduce"]
    # fp16-compressed wire: RS rides 2-byte gradients, AG full fp32 updates
    c = zero1_sync_bytes(B, n, wire_bytes=B // 2)
    assert c["allreduce"] == ring * B
    assert c["rs"] == ring * B / 2
    assert c["sharded_total"] == ring * (B // 2 + B)
    # degenerate single rank: nothing moves
    z = zero1_sync_bytes(B, 1)
    assert z["allreduce"] == z["sharded_total"] == 0.0


def test_zero1_hlo_rs_ag_priced_like_allreduce():
    """An HLO carrying the sharded step's reduce-scatter + all-gather pair
    must price the same wire time as one ring allreduce of the gradient
    volume: RS outputs the 1/g shard costed (g-1)·B_shard, AG outputs the
    full buffer costed (g-1)/g·B — their sum is the allreduce's 2(g-1)/g·B."""
    from scaling_projection import comm_ops_from_hlo, comm_time_s

    ar = """
  %ar = f32[80] all-reduce(f32[80] %g), replica_groups={{0,1,2,3,4,5,6,7}}
"""
    rsag = """
  %rs = f32[10] reduce-scatter(f32[80] %g), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
  %ag = f32[80] all-gather(f32[10] %u), replica_groups={{0,1,2,3,4,5,6,7}}, dimensions={0}
"""
    bw = 1e9
    t_ar = comm_time_s(comm_ops_from_hlo(ar), bw, default_group=8)
    t_rsag = comm_time_s(comm_ops_from_hlo(rsag), bw, default_group=8)
    assert abs(t_ar - t_rsag) < 1e-15
    # and the RS leg alone is half the allreduce
    rs_only = comm_time_s(comm_ops_from_hlo(rsag)[:1], bw, default_group=8)
    assert abs(rs_only - t_ar / 2) < 1e-15


@pytest.mark.compression
def test_int8_sync_byte_model():
    """Blockwise int8 byte model: per-leaf 1 byte/element + one bf16 scale
    per 256-block for leaves above the min-quantize floor, dense fp32
    below it; ring factors as allreduce/RS."""
    from scaling_projection import int8_sync_bytes

    shapes = [(784, 512), (512,), (512, 512), (512,), (512, 10), (10,)]
    m = int8_sync_bytes(shapes, 8)

    def size(s):
        return s[0] * (s[1] if len(s) == 2 else 1)

    elems = sum(size(s) for s in shapes)
    wire = sum(
        size(s) + -(-size(s) // 256) * 2 if size(s) >= 1024
        else 4 * size(s)
        for s in shapes
    )
    ring = 7 / 8
    assert m["wire_bytes"] == wire
    assert m["allreduce"] == pytest.approx(2 * ring * wire)
    assert m["rs"] == pytest.approx(ring * wire)
    assert m["fp32_allreduce"] == pytest.approx(2 * ring * 4 * elems)
    assert 0.25 < m["ratio_vs_fp32"] < 0.26  # ~25.8% incl. scale overhead
    # int shorthand: one flat leaf; a sub-floor leaf is billed dense
    assert int8_sync_bytes(2048, 8)["wire_bytes"] == 2048 + 8 * 2
    assert int8_sync_bytes(256, 8)["wire_bytes"] == 256 * 4


@pytest.mark.compression
def test_powersgd_sync_byte_model():
    from scaling_projection import powersgd_sync_bytes

    shapes = [(64, 192), (64, 64), (2048,)]
    m = powersgd_sync_bytes(shapes, 4, 8)
    factor = (64 + 192) * 4 * 4 + (64 + 64) * 4 * 4
    fb = 2048 + 8 * 2  # 1-D int8 fallback: bytes + scales
    assert m["factor_bytes"] == factor
    assert m["int8_fallback_bytes"] == fb
    assert m["wire_bytes"] == factor + fb
    # a sub-floor 1-D leaf rides (and bills) dense
    assert powersgd_sync_bytes([(192,)], 4, 8)["int8_fallback_bytes"] == 768
    # a tiny 2-D leaf fails the (d0+m)*r < d0*m crossover: factors would
    # cost MORE than the dense leaf, so it falls back (and bills dense)
    tiny = powersgd_sync_bytes([(2, 3)], 4, 8)
    assert tiny["factor_bytes"] == 0
    assert tiny["int8_fallback_bytes"] == 6 * 4


@pytest.mark.compression
def test_int8_model_matches_live_gauge():
    """The analytic model must equal the grad_sync_bytes_per_step gauge the
    instrumented optimizer reports — same hook, zero drift."""
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.compression import Compression
    from scaling_projection import int8_sync_bytes, powersgd_sync_bytes

    hvd.init()
    try:
        hvd.metrics.reset()
        n = hvd.size()
        params = {"w": jnp.ones((64, 48), jnp.float32),
                  "b": jnp.ones((29,), jnp.float32)}
        shapes = [(29,), (64, 48)]  # tree_leaves order: b, w
        g = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 0.5), params)

        tx = hvd.DistributedOptimizer(
            optax.sgd(0.1), compression=Compression.int8,
            error_feedback=True)
        s = tx.init(params)
        tx.update(g, s, params)
        gauge = hvd.metrics.value("grad_sync_bytes_per_step",
                                  mode="allreduce")
        assert gauge == pytest.approx(int8_sync_bytes(shapes, n)["allreduce"])

        tx = hvd.DistributedOptimizer(
            optax.sgd(0.1), compression=Compression.powersgd(4),
            error_feedback=True)
        s = tx.init(params)
        tx.update(g, s, params)
        gauge = hvd.metrics.value("grad_sync_bytes_per_step",
                                  mode="allreduce")
        assert gauge == pytest.approx(
            powersgd_sync_bytes(shapes, 4, n)["allreduce"])
    finally:
        hvd.shutdown()


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["sp", "tp", "ep", "pp"])
def test_lm_comm_fraction_modes(mode):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "scaling_projection.py"),
         "--parallelism", mode, "--dim", "64", "--depth", "1",
         "--heads", "4", "--seq-len", "256", "--vocab", "512"],
        capture_output=True, text=True, timeout=900, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == f"{mode}_comm_fraction"
    assert rec["comm_bytes_per_step"] > 0
    assert 0.0 < rec["comm_fraction_serial"] < 1.0
    assert 0.0 < rec["efficiency_overlapped"] <= 1.0


@pytest.mark.slow
def test_projection_end_to_end():
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "scaling_projection.py"),
         "--model", "resnet50", "--image-size", "64", "--batch-per-chip", "2",
         "--chips", "8"],
        capture_output=True, text=True, timeout=900, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    # the DP step allreduces every fp32 gradient exactly once: comm bytes
    # must equal 4 B x params to within a few % (loss/batch-stat scalars)
    assert abs(rec["comm_bytes_per_step"] - 4 * rec["params"]) \
        < 0.05 * 4 * rec["params"], rec
    eff = rec["projection"]["8"]
    assert 0.0 < eff["efficiency_serial"] <= 1.0
    assert eff["efficiency_overlapped"] >= eff["efficiency_serial"]


@pytest.mark.slow
def test_hier_projection_end_to_end():
    """hier mode: the compiled step must decompose the gradient allreduce
    into local reduce-scatter + cross all-reduce on the 1/local shard +
    local all-gather (reference NCCLHierarchicalAllreduce,
    nccl_operations.cc:162-354), with each fabric's byte count pinned to
    the gradient volume."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "scaling_projection.py"),
         "--parallelism", "hier", "--image-size", "64",
         "--batch-per-chip", "2"],
        capture_output=True, text=True, timeout=900, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "hier_comm_fraction"
    grad = 4 * rec["params"]
    local = rec["mesh"]["local"]
    tol = 0.06
    # DCN carries ONLY the 1/local cross shard — the whole point
    assert abs(rec["comm_bytes_by_fabric"]["dcn"] - grad / local) \
        < tol * grad, rec["comm_bytes_by_fabric"]
    # ICI carries the local reduce-scatter output (grad/local) plus the
    # local all-gather output (grad)
    assert abs(rec["comm_bytes_by_fabric"]["ici"] - (grad + grad / local)) \
        < tol * grad, rec["comm_bytes_by_fabric"]
    for cfg in rec["multi_host_projection"].values():
        assert cfg["hier_speedup"] > 1.0
