"""Pin the Spark barrier-context fake against the real pyspark 3.x API.

VERDICT r3 item 6: ``horovod_tpu.spark``'s barrier dispatch is exercised only
through ``FakeBarrierCtx`` because pyspark is not installable here. This file
bounds the drift risk two ways:

1. A WRITTEN contract (``PYSPARK3_BARRIER_CONTRACT``) of the
   ``pyspark.BarrierTaskContext`` surface the dispatch relies on, transcribed
   from the pyspark 3.x docs/source (``python/pyspark/taskcontext.py``):
   the fake must satisfy it, so a fake edit that diverges from real Spark
   fails here first.
2. Auto-skipped real-pyspark tests that light up the moment the image gains
   pyspark: the real class must satisfy the same contract, and a local
   barrier job must produce the rank grouping the fake-driven test pins.

Reference behavior under test: ``/root/reference/horovod/spark/runner.py:131-237``.
"""

import inspect

import pytest

# ---------------------------------------------------------------------------
# The contract: method name -> (positional arg names after self, notes).
# pyspark 3.x (3.0 through 3.5) BarrierTaskContext:
#   - get() classmethod -> BarrierTaskContext (executor-side accessor)
#   - partitionId() -> int                      (inherited from TaskContext)
#   - allGather(message: str = "") -> list[str] (3.0+; blocking, global order
#                                                by partition? NO — order is
#                                                by task attempt; our slot
#                                                code therefore parses the
#                                                partition id OUT of the
#                                                message rather than relying
#                                                on list order)
#   - barrier() -> None                         (3.0+)
PYSPARK3_BARRIER_CONTRACT = {
    "partitionId": ([], "returns int partition id"),
    "allGather": (["message"], "message str, returns list[str]"),
    "barrier": ([], "global sync, returns None"),
}


def _check_surface(cls_or_obj, *, allow_extra_defaults: bool = True):
    for name, (arg_names, _note) in PYSPARK3_BARRIER_CONTRACT.items():
        fn = getattr(cls_or_obj, name, None)
        assert fn is not None, f"missing method {name}"
        sig = inspect.signature(fn)
        params = [
            p.name for p in sig.parameters.values()
            if p.name not in ("self", "cls")
        ]
        # every contract arg must be acceptable positionally
        assert params[: len(arg_names)] == arg_names, (
            f"{name}: expected leading args {arg_names}, got {params}"
        )


def test_fake_matches_pyspark3_contract():
    from tests.test_estimator import FakeBarrierCtx

    fake = FakeBarrierCtx(idx=0)
    # the fake covers the subset the dispatch uses (barrier() is real surface
    # but unused by _run_barrier_slot, so the fake intentionally omits it);
    # what it does implement must match the real signatures exactly
    for name in ("partitionId", "allGather"):
        fn = getattr(fake, name)
        want_args = PYSPARK3_BARRIER_CONTRACT[name][0]
        params = [p.name for p in inspect.signature(fn).parameters.values()]
        assert params[: len(want_args)] == want_args, (name, params)


def test_dispatch_uses_only_contract_methods():
    """_run_barrier_slot must not call anything outside the pinned surface —
    a new ctx.* call site widens the drift risk and must extend the
    contract first."""
    import ast
    import textwrap

    import horovod_tpu.spark as sp

    src = textwrap.dedent(inspect.getsource(sp._run_barrier_slot))
    tree = ast.parse(src)
    used = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "ctx"
        ):
            used.add(node.func.attr)
    assert used <= set(PYSPARK3_BARRIER_CONTRACT), (
        f"dispatch calls {used - set(PYSPARK3_BARRIER_CONTRACT)} outside "
        "the pinned pyspark contract"
    )
    assert "partitionId" in used and "allGather" in used


# ---------------------------------------------------------------------------
# auto-skipped: light up when the image gains pyspark


def test_real_barrier_context_matches_contract():
    pyspark = pytest.importorskip("pyspark")
    from pyspark import BarrierTaskContext

    _check_surface(BarrierTaskContext)
    assert hasattr(BarrierTaskContext, "get")
    major = int(pyspark.__version__.split(".")[0])
    assert major >= 3, "contract written against pyspark 3.x"


@pytest.mark.slow
def test_real_spark_barrier_run():
    pytest.importorskip("pyspark")
    from pyspark.sql import SparkSession

    import horovod_tpu.spark as sp

    spark = (
        SparkSession.builder.master("local[2]")
        .appName("hvd-contract")
        .getOrCreate()
    )
    try:
        def fn():
            import os

            return int(os.environ["HOROVOD_RANK"])

        res = sp.run(fn, np=2, spark=spark)
        assert sorted(res) == [0, 1]
    finally:
        spark.stop()
