"""Long-context stack tests: flash attention (scan + pallas-interpret paths)
and sequence-parallel ring/Ulysses attention on the 8-device CPU mesh,
validated against dense reference attention."""

import functools

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as shard_map_fn
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as shard_map_fn

from horovod_tpu.ops.flash_attention import flash_attention
from horovod_tpu.parallel import (
    SEQUENCE_AXIS, build_mesh, ring_attention, ulysses_attention,
)


def dense_attention(q, k, v, causal=False):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        t_q, t_k = q.shape[1], k.shape[1]
        mask = np.tril(np.ones((t_q, t_k), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def qkv(b=2, t=64, h=4, d=16, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(b, t, h, d).astype(np.float32)
    return jnp.asarray(mk()), jnp.asarray(mk()), jnp.asarray(mk())


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_scan_matches_dense(causal):
    q, k, v = qkv()
    out = flash_attention(q, k, v, causal=causal, use_pallas=False,
                          block_k=16)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_pallas_interpret_matches_dense(causal):
    q, k, v = qkv(b=1, t=32, h=2, d=8)
    out = flash_attention(q, k, v, causal=causal, use_pallas=True,
                          interpret=True, block_q=16, block_k=16)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_grad_matches_dense():
    q, k, v = qkv(b=1, t=32, h=2, d=8)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=True,
                                use_pallas=False, block_k=8) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal=True) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def _seq_sharded(mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P(None, SEQUENCE_AXIS)))


def _run_sp(fn, mesh, q, k, v):
    spec = P(None, SEQUENCE_AXIS, None, None)
    wrapped = shard_map_fn(
        fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    sh = NamedSharding(mesh, spec)
    return jax.jit(wrapped)(
        jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    mesh = build_mesh({SEQUENCE_AXIS: 8})
    q, k, v = qkv(b=2, t=64, h=2, d=16)
    out = _run_sp(
        functools.partial(ring_attention, causal=causal, block_k=8),
        mesh, q, k, v,
    )
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_grad_matches_dense():
    mesh = build_mesh({SEQUENCE_AXIS: 4}, devices=jax.devices()[:4])
    q, k, v = qkv(b=1, t=32, h=2, d=8, seed=3)
    spec = P(None, SEQUENCE_AXIS, None, None)
    sh = NamedSharding(mesh, spec)

    ring = shard_map_fn(
        functools.partial(ring_attention, causal=True, block_k=8),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )

    def loss_ring(q, k, v):
        return (ring(q, k, v) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal=True) ** 2).sum()

    g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(
        jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh))
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(causal):
    mesh = build_mesh({SEQUENCE_AXIS: 4}, devices=jax.devices()[:4])
    q, k, v = qkv(b=2, t=32, h=4, d=8, seed=1)  # heads divisible by 4
    out = _run_sp(
        functools.partial(
            ulysses_attention, causal=causal,
            attention_fn=functools.partial(flash_attention,
                                           use_pallas=False)),
        mesh, q, k, v,
    )
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_heads_not_divisible_raises():
    mesh = build_mesh({SEQUENCE_AXIS: 8})
    q, k, v = qkv(b=1, t=32, h=3, d=8)
    with pytest.raises(Exception, match="divisible"):
        _run_sp(ulysses_attention, mesh, q, k, v)


def test_ring_attention_long_context_many_blocks():
    # more k-blocks per shard than one: exercises the inner scan x ring loop
    mesh = build_mesh({SEQUENCE_AXIS: 8})
    q, k, v = qkv(b=1, t=128, h=2, d=8, seed=2)
    out = _run_sp(
        functools.partial(ring_attention, causal=True, block_k=4),
        mesh, q, k, v,
    )
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_pallas_grad_matches_dense():
    # pallas forward (interpret) supplies lse for the blockwise backward
    q, k, v = qkv(b=1, t=32, h=2, d=8, seed=4)

    def loss_pallas(q, k, v):
        return (flash_attention(q, k, v, causal=True, use_pallas=True,
                                interpret=True, block_q=16,
                                block_k=16) ** 2).sum()

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal=True) ** 2).sum()

    g1 = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


# --------------------------------------------------------------- zigzag ring


from horovod_tpu.parallel import zigzag_permutation, zigzag_ring_attention


def test_zigzag_permutation_layout():
    perm = zigzag_permutation(16, 4)
    # device 0 holds chunks 0 and 7, device 1 chunks 1 and 6, ...
    assert perm.tolist() == [
        0, 1, 14, 15, 2, 3, 12, 13, 4, 5, 10, 11, 6, 7, 8, 9
    ]
    assert sorted(perm.tolist()) == list(range(16))
    with pytest.raises(ValueError, match="divisible"):
        zigzag_permutation(12, 8)


@pytest.mark.parametrize("n,t", [(4, 64), (8, 64), (2, 32)])
def test_zigzag_ring_attention_matches_dense(n, t):
    mesh = build_mesh({SEQUENCE_AXIS: n}, devices=jax.devices()[:n])
    q, k, v = qkv(b=2, t=t, h=2, d=16, seed=5)
    perm = zigzag_permutation(t, n)
    inv = np.argsort(perm)
    out_zz = _run_sp(
        functools.partial(zigzag_ring_attention, block_k=8),
        mesh, q[:, perm], k[:, perm], v[:, perm],
    )
    out = np.asarray(out_zz)[:, inv]
    ref = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(out, np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_zigzag_ring_attention_grad_matches_dense():
    n, t = 4, 32
    mesh = build_mesh({SEQUENCE_AXIS: n}, devices=jax.devices()[:n])
    q, k, v = qkv(b=1, t=t, h=2, d=8, seed=7)
    perm = zigzag_permutation(t, n)
    inv = np.argsort(perm)
    spec = P(None, SEQUENCE_AXIS, None, None)
    sh = NamedSharding(mesh, spec)

    zz = shard_map_fn(
        functools.partial(zigzag_ring_attention, block_k=8),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )

    def loss_zz(qp, kp, vp):
        return (zz(qp, kp, vp) ** 2).sum()  # sum is permutation-invariant

    def loss_dense(q, k, v):
        return (dense_attention(q, k, v, causal=True) ** 2).sum()

    g1 = jax.jit(jax.grad(loss_zz, argnums=(0, 1, 2)))(
        jax.device_put(q[:, perm], sh), jax.device_put(k[:, perm], sh),
        jax.device_put(v[:, perm], sh))
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a)[:, inv], np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_zigzag_rejects_odd_local_length():
    mesh = build_mesh({SEQUENCE_AXIS: 8})
    q, k, v = qkv(b=1, t=8, h=2, d=8)  # local length 1 per device
    with pytest.raises(Exception, match="2\\*Tc|odd local"):
        _run_sp(
            functools.partial(zigzag_ring_attention, block_k=8),
            mesh, q, k, v,
        )


# -------------------------------------------------------------------- GQA


def test_flash_attention_gqa_matches_repeated_dense():
    """Grouped-query attention: H_kv < H kv heads broadcast over query
    groups; result must equal dense attention with explicitly repeated
    heads, and gradients must flow."""
    rng = np.random.RandomState(9)
    b, t, h, h_kv, d = 2, 32, 8, 2, 8
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
    out = flash_attention(q, k, v, causal=True, use_pallas=False, block_k=8)
    ref = dense_attention(
        q, jnp.repeat(k, h // h_kv, axis=2), jnp.repeat(v, h // h_kv, axis=2),
        causal=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # mqa (single kv head) + gradient flow
    k1 = jnp.asarray(rng.randn(b, t, 1, d).astype(np.float32))
    v1 = jnp.asarray(rng.randn(b, t, 1, d).astype(np.float32))
    g = jax.grad(
        lambda kk: (flash_attention(q, kk, v1, causal=False,
                                    use_pallas=False, block_k=8) ** 2).sum()
    )(k1)
    assert g.shape == k1.shape
    assert np.isfinite(np.asarray(g)).all()
    with pytest.raises(ValueError, match="multiple of kv heads"):
        flash_attention(q, jnp.asarray(rng.randn(b, t, 3, d),), 
                        jnp.asarray(rng.randn(b, t, 3, d)), use_pallas=False)


def test_ring_and_ulysses_gqa_match_dense():
    n = 4
    mesh = build_mesh({SEQUENCE_AXIS: n}, devices=jax.devices()[:n])
    rng = np.random.RandomState(10)
    b, t, h, h_kv, d = 1, 32, 4, 2, 8
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
    ref = dense_attention(
        q, jnp.repeat(k, h // h_kv, axis=2),
        jnp.repeat(v, h // h_kv, axis=2), causal=True,
    )
    out_ring = _run_sp(
        functools.partial(ring_attention, causal=True, block_k=8),
        mesh, q, k, v,
    )
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    out_uly = _run_sp(
        functools.partial(ulysses_attention, causal=True),
        mesh, q, k, v,
    )
    np.testing.assert_allclose(np.asarray(out_uly), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("h,h_kv,n", [
    (8, 4, 4),   # h_kv % n == 0: the SMALL-bundle a2a branch
    (8, 2, 4),   # h_kv % n != 0: lcm fallback (repeat to 4 heads, not 8)
    (4, 1, 4),   # MQA: lcm fallback repeats to n heads
])
def test_ulysses_gqa_branches_match_dense(h, h_kv, n):
    """Both Ulysses GQA exchange strategies — small-bundle a2a and the
    lcm-bounded repeat fallback — against dense attention with repeated
    heads (pins the post-a2a head-group alignment)."""
    mesh = build_mesh({SEQUENCE_AXIS: n}, devices=jax.devices()[:n])
    rng = np.random.RandomState(13)
    b, t, d = 1, 32, 8
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
    out = _run_sp(
        functools.partial(ulysses_attention, causal=True),
        mesh, q, k, v,
    )
    ref = dense_attention(
        q, jnp.repeat(k, h // h_kv, axis=2),
        jnp.repeat(v, h // h_kv, axis=2), causal=True,
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_gqa_ring_and_zigzag_grads_match_dense():
    """GQA gradients through the ring passes: the rotating dk/dv bundles
    stay H_kv-wide (group contributions reduced per fold) and must match
    dense attention on explicitly repeated heads, reduced over groups."""
    n, t = 4, 32
    mesh = build_mesh({SEQUENCE_AXIS: n}, devices=jax.devices()[:n])
    rng = np.random.RandomState(11)
    b, h, h_kv, d = 1, 4, 2, 8
    grp = h // h_kv
    q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
    k = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
    v = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
    spec = P(None, SEQUENCE_AXIS, None, None)
    sh = NamedSharding(mesh, spec)

    def loss_dense(q_, k_, v_):
        return (dense_attention(
            q_, jnp.repeat(k_, grp, axis=2), jnp.repeat(v_, grp, axis=2),
            causal=True) ** 2).sum()

    ref_g = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)

    ring = shard_map_fn(
        functools.partial(ring_attention, causal=True, block_k=8),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    g_ring = jax.jit(jax.grad(
        lambda a, b_, c: (ring(a, b_, c) ** 2).sum(), argnums=(0, 1, 2)
    ))(jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh))
    for a, b_ in zip(g_ring, ref_g):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)

    perm = zigzag_permutation(t, n)
    inv = np.argsort(perm)
    zz = shard_map_fn(
        functools.partial(zigzag_ring_attention, block_k=8),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    g_zz = jax.jit(jax.grad(
        lambda a, b_, c: (zz(a, b_, c) ** 2).sum(), argnums=(0, 1, 2)
    ))(jax.device_put(q[:, perm], sh), jax.device_put(k[:, perm], sh),
       jax.device_put(v[:, perm], sh))
    for a, b_ in zip(g_zz, ref_g):
        np.testing.assert_allclose(np.asarray(a)[:, inv], np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_flash_attention_pallas_gqa_interpret_matches_dense():
    """The Pallas kernel's GQA kv index map (grid row -> kv head) against
    dense attention with repeated heads — interpret mode, both causal
    flavors, including MQA."""
    rng = np.random.RandomState(12)
    b, t, d = 1, 32, 8
    for h, h_kv in [(4, 2), (4, 1)]:
        q = jnp.asarray(rng.randn(b, t, h, d).astype(np.float32))
        k = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
        v = jnp.asarray(rng.randn(b, t, h_kv, d).astype(np.float32))
        for causal in (False, True):
            out = flash_attention(q, k, v, causal=causal, use_pallas=True,
                                  interpret=True, block_q=16, block_k=16)
            ref = dense_attention(
                q, jnp.repeat(k, h // h_kv, axis=2),
                jnp.repeat(v, h // h_kv, axis=2), causal=causal)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-5, atol=2e-5)
