"""Model + training-step tests (reference analog: examples used as smoke
tests in CI, ``.buildkite/gen-pipeline.sh:145-192``)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax


@pytest.fixture()
def mnist_setup(hvd):
    from horovod_tpu.models import MnistCNN
    from horovod_tpu.training import init_model, replicate

    model = MnistCNN()
    params, batch_stats = init_model(
        model, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
    )
    return model, replicate(params), batch_stats


def _batch(hvd, n_per_rank=2):
    from horovod_tpu.training import shard_batch

    n = hvd.size() * n_per_rank
    rng = np.random.RandomState(0)
    x = shard_batch(rng.rand(n, 28, 28, 1).astype(np.float32))
    y = shard_batch(rng.randint(0, 10, n))
    return x, y


def test_resnet_tiny_forward(hvd):
    from horovod_tpu.models import ResNet18

    model = ResNet18(num_classes=10, num_filters=8, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_jit_and_shardmap_steps_agree(hvd, mnist_setup):
    """The pjit-style and explicit-collective steps must produce the same
    parameters from the same state (the two execution modes are semantically
    one framework)."""
    from horovod_tpu.training import (
        make_jit_train_step,
        make_shardmap_train_step,
        replicate,
    )

    model, params, batch_stats = mnist_setup
    x, y = _batch(hvd)
    tx_jit = __import__("horovod_tpu").DistributedOptimizer(optax.sgd(0.1))
    tx_sm = optax.sgd(0.1)

    s1 = make_jit_train_step(model, tx_jit, donate=False)
    s2 = make_shardmap_train_step(model, tx_sm, donate=False)

    opt_state = replicate(tx_sm.init(params))
    p1, _, _, l1 = s1(params, batch_stats, opt_state, x, y)
    p2, _, _, l2 = s2(params, batch_stats, opt_state, x, y)

    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for k in ("Dense_0", "Conv_0"):
        np.testing.assert_allclose(
            np.asarray(p1[k]["kernel"]),
            np.asarray(p2[k]["kernel"]),
            rtol=1e-4,
            atol=1e-6,
        )


def test_training_reduces_loss(hvd, mnist_setup):
    from horovod_tpu.training import make_jit_train_step, replicate

    model, params, batch_stats = mnist_setup
    x, y = _batch(hvd, n_per_rank=4)
    tx = __import__("horovod_tpu").DistributedOptimizer(optax.sgd(0.05))
    step = make_jit_train_step(model, tx, donate=False)
    opt_state = replicate(tx.init(params))
    losses = []
    for _ in range(10):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, x, y
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_graft_entry_dryrun(hvd):
    """The driver's multichip dryrun must work on the 8-device CPU mesh."""
    import sys, pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    import __graft_entry__ as g

    g.dryrun_multichip(8)
