"""Model + training-step tests (reference analog: examples used as smoke
tests in CI, ``.buildkite/gen-pipeline.sh:145-192``)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax


@pytest.fixture()
def mnist_setup(hvd):
    from horovod_tpu.models import MnistCNN
    from horovod_tpu.training import init_model, replicate

    model = MnistCNN()
    params, batch_stats = init_model(
        model, jax.random.PRNGKey(0), jnp.zeros((1, 28, 28, 1))
    )
    return model, replicate(params), batch_stats


def _batch(hvd, n_per_rank=2):
    from horovod_tpu.training import shard_batch

    n = hvd.size() * n_per_rank
    rng = np.random.RandomState(0)
    x = shard_batch(rng.rand(n, 28, 28, 1).astype(np.float32))
    y = shard_batch(rng.randint(0, 10, n))
    return x, y


def test_resnet_tiny_forward(hvd):
    from horovod_tpu.models import ResNet18

    model = ResNet18(num_classes=10, num_filters=8, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


def test_jit_and_shardmap_steps_agree(hvd, mnist_setup):
    """The pjit-style and explicit-collective steps must produce the same
    parameters from the same state (the two execution modes are semantically
    one framework)."""
    from horovod_tpu.training import (
        make_jit_train_step,
        make_shardmap_train_step,
        replicate,
    )

    model, params, batch_stats = mnist_setup
    x, y = _batch(hvd)
    tx_jit = __import__("horovod_tpu").DistributedOptimizer(optax.sgd(0.1))
    tx_sm = optax.sgd(0.1)

    s1 = make_jit_train_step(model, tx_jit, donate=False)
    s2 = make_shardmap_train_step(model, tx_sm, donate=False)

    opt_state = replicate(tx_sm.init(params))
    p1, _, _, l1 = s1(params, batch_stats, opt_state, x, y)
    p2, _, _, l2 = s2(params, batch_stats, opt_state, x, y)

    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    for k in ("Dense_0", "Conv_0"):
        np.testing.assert_allclose(
            np.asarray(p1[k]["kernel"]),
            np.asarray(p2[k]["kernel"]),
            rtol=1e-4,
            atol=1e-6,
        )


def test_training_reduces_loss(hvd, mnist_setup):
    from horovod_tpu.training import make_jit_train_step, replicate

    model, params, batch_stats = mnist_setup
    x, y = _batch(hvd, n_per_rank=4)
    tx = __import__("horovod_tpu").DistributedOptimizer(optax.sgd(0.05))
    step = make_jit_train_step(model, tx, donate=False)
    opt_state = replicate(tx.init(params))
    losses = []
    for _ in range(10):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, x, y
        )
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def _sharded_paths(tree, ax):
    """Leaf paths whose dim-0 sharding uses axis `ax`."""
    return {
        jax.tree_util.keystr(path)
        for path, l in jax.tree_util.tree_flatten_with_path(tree)[0]
        if getattr(l.sharding, "spec", None) and l.sharding.spec[0] == ax
    }


def test_zero_sharded_opt_state_matches_replicated(hvd):
    """ZeRO-1 layout: optimizer state sharded over the data axis must train
    bit-for-bit like the replicated layout (sharding is layout, not math)
    and the moment leaves must STAY sharded across donated steps (the HBM
    win persists, it isn't re-replicated by the compiler). MLP rather than
    the CNN: the layout logic is identical and the two extra jit compiles
    stay cheap."""
    import jax

    from horovod_tpu.models import MLP
    from horovod_tpu.training import (
        init_model, make_jit_train_step, replicate, shard_batch,
        zero_shard_opt_state,
    )

    model = MLP(features=(64, 10))
    rng = np.random.RandomState(0)
    params, batch_stats = init_model(
        model, jax.random.PRNGKey(0), jnp.zeros((1, 16))
    )
    params = replicate(params)
    n = hvd.size() * 2
    x = shard_batch(rng.rand(n, 16).astype(np.float32))
    y = shard_batch(rng.randint(0, 10, n))
    tx = __import__("horovod_tpu").DistributedOptimizer(
        optax.adam(0.01)  # adam: real moment tensors to shard
    )
    step_r = make_jit_train_step(model, tx, donate=False)
    step_z = make_jit_train_step(model, tx, donate=True)

    opt_r = replicate(tx.init(params))
    opt_z = zero_shard_opt_state(tx.init(params))

    # at least one big leaf actually sharded over 'data'
    ax = hvd.data_axis()
    sharded_paths = lambda tree: _sharded_paths(tree, ax)

    before = sharded_paths(opt_z)
    assert before, "no optimizer-state leaf got the data-axis layout"

    pr, pz = params, params
    br, bz = batch_stats, batch_stats
    for _ in range(3):
        pr, br, opt_r, lr = step_r(pr, br, opt_r, x, y)
        pz, bz, opt_z, lz = step_z(pz, bz, opt_z, x, y)
        # sharded layouts reduce in a different order -> fp32-level deltas
        np.testing.assert_allclose(float(lr), float(lz), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(pr), jax.tree_util.tree_leaves(pz)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5
        )
    # the SAME leaves stay sharded through the donated steps (a count-only
    # check would miss the compiler re-replicating one leaf while another
    # happened to pick up the axis)
    assert sharded_paths(opt_z) == before, "compiler changed the layout"


def test_zero_shard_preserves_model_axis_layout():
    """On a dp x tp mesh, moments of TP-sharded params already carry a
    model-axis layout; the ZeRO placement must MERGE the data axis in, not
    clobber the spec (re-replicating the model dim would inflate HBM)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd_mod
    from horovod_tpu.training import zero_shard_opt_state

    hvd_mod.shutdown()
    hvd_mod.init(axes={"data": 2, "model": 4})
    try:
        mesh = hvd_mod.mesh()
        mu_tp = jax.device_put(  # moment of a TP-sharded weight
            jnp.zeros((8, 8)), NamedSharding(mesh, P(None, "model"))
        )
        mu_plain = jnp.zeros((8, 4))
        mu_odd = jnp.zeros((3,))  # indivisible dim 0
        out = zero_shard_opt_state(
            {"tp": mu_tp, "plain": mu_plain, "odd": mu_odd}
        )
        assert out["tp"].sharding.spec == P("data", "model")
        spec = out["plain"].sharding.spec
        assert spec[0] == "data" and all(e is None for e in spec[1:])
        assert all(e is None for e in tuple(out["odd"].sharding.spec))
        # a leaf whose dim 0 already uses the data axis is left untouched
        pre = jax.device_put(
            jnp.zeros((8, 8)), NamedSharding(mesh, P("data", None))
        )
        out2 = zero_shard_opt_state({"pre": pre})
        assert out2["pre"].sharding.spec == P("data", None)
    finally:
        hvd_mod.shutdown()


@pytest.mark.slow  # ~16 s big-model forward; the same builder/step machinery runs tier-1 on resnet_tiny
def test_vgg16_forward_and_train_step(hvd):
    """VGG-16 (the reference's allreduce-bandwidth stress workload,
    ``docs/benchmarks.rst:10-14``) is stateless by default (no BN): forward
    shape/dtype, empty batch_stats, and one DP train step."""
    from horovod_tpu.models import VGG16
    from horovod_tpu.training import (
        init_model, make_jit_train_step, replicate, shard_batch,
    )

    model = VGG16(num_classes=10, hidden_dim=32, dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    params, batch_stats = init_model(model, jax.random.PRNGKey(0), x)
    assert batch_stats == {}
    logits = model.apply({"params": params}, x, train=False)
    assert logits.shape == (2, 10) and logits.dtype == jnp.float32

    tx = hvd.DistributedOptimizer(optax.sgd(0.01))
    step = make_jit_train_step(model, tx, donate=False)
    n = hvd.size() * 2
    rng = np.random.RandomState(0)
    images = shard_batch(rng.rand(n, 32, 32, 3).astype(np.float32))
    labels = shard_batch(rng.randint(0, 10, n))
    params = replicate(params)
    opt_state = replicate(tx.init(params))
    _, _, _, loss = step(params, batch_stats, opt_state, images, labels)
    assert np.isfinite(float(loss))


def test_vgg_bn_variant_has_batch_stats(hvd):
    from horovod_tpu.models import VGG
    from horovod_tpu.training import init_model

    model = VGG(stages=((4,), (8,)), num_classes=10, hidden_dim=16,
                dtype=jnp.float32, use_bn=True)
    x = jnp.zeros((2, 16, 16, 3))
    _, batch_stats = init_model(model, jax.random.PRNGKey(0), x)
    assert batch_stats  # BN running stats present


@pytest.mark.slow  # ~26 s big-model forward; stem/shape coverage duplicated by resnet_tiny tier-1
def test_inception_v3_forward(hvd):
    """Inception V3 (reference scaling workload #2). 128x128 input — the
    network is fully convolutional up to the head, so any size surviving
    the stem works; canonical 299 is exercised on hardware by bench.py.
    Forward-only: the train-step plumbing for the new families is already
    proven by the VGG test, and V3's backward compile alone costs ~40 s of
    suite time for no additional coverage."""
    from horovod_tpu.models import InceptionV3
    from horovod_tpu.training import init_model

    model = InceptionV3(num_classes=10, dtype=jnp.float32)
    x = jnp.zeros((1, 128, 128, 3))
    params, batch_stats = init_model(model, jax.random.PRNGKey(0), x)
    assert batch_stats  # BN everywhere
    logits = model.apply(
        {"params": params, "batch_stats": batch_stats}, x, train=False
    )
    assert logits.shape == (1, 10) and logits.dtype == jnp.float32


def test_bench_model_table_resolves():
    """Every bench.py --model choice maps to a real models attr."""
    import sys, pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    import bench
    import horovod_tpu.models as models

    for name, (attr, image_size, has_baseline) in bench._MODELS.items():
        assert hasattr(models, attr), name
        assert image_size in (224, 299)
        assert isinstance(has_baseline, bool)


def test_graft_entry_dryrun(hvd):
    """The driver's multichip dryrun must work on the 8-device CPU mesh."""
    import sys, pathlib

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_fsdp_sharded_params_match_replicated(hvd):
    """FSDP/ZeRO-3 layout: params sharded over the data axis on dim 0 must
    train to the same result as the replicated layout, and the param leaves
    must STAY sharded across donated steps (per-chip param HBM win
    persists). XLA inserts the gather/reduce-scatter pattern itself."""
    import jax

    from horovod_tpu.models import MLP
    from horovod_tpu.training import (
        fsdp_shard_params, init_model, make_jit_train_step, replicate,
        shard_batch, zero_shard_opt_state,
    )

    model = MLP(features=(64, 10))
    rng = np.random.RandomState(0)
    params, batch_stats = init_model(
        model, jax.random.PRNGKey(0), jnp.zeros((1, 16))
    )
    n = hvd.size() * 2
    x = shard_batch(rng.rand(n, 16).astype(np.float32))
    y = shard_batch(rng.randint(0, 10, n))
    tx = __import__("horovod_tpu").DistributedOptimizer(optax.adam(0.01))
    step_r = make_jit_train_step(model, tx, donate=False)
    step_f = make_jit_train_step(model, tx, donate=True)

    p_r = replicate(params)
    opt_r = replicate(tx.init(params))
    p_f = fsdp_shard_params(params)
    opt_f = zero_shard_opt_state(tx.init(p_f))

    ax = hvd.data_axis()
    sharded_paths = lambda tree: _sharded_paths(tree, ax)

    before = sharded_paths(p_f)
    assert before, "no param leaf got the data-axis layout"

    br, bf = batch_stats, batch_stats
    for _ in range(3):
        p_r, br, opt_r, lr = step_r(p_r, br, opt_r, x, y)
        p_f, bf, opt_f, lf = step_f(p_f, bf, opt_f, x, y)
        np.testing.assert_allclose(float(lr), float(lf), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(p_r), jax.tree_util.tree_leaves(p_f)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5
        )
    assert sharded_paths(p_f) == before, "compiler changed the param layout"
