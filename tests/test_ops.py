"""Collective-op correctness tests, modeled on the reference's pattern of
computing the collective and comparing with local arithmetic
(``test/test_tensorflow.py:60-300``, ``test/test_torch.py``)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def stacked(hvd, x):
    """Place a [size, ...] per-rank array sharded over the data axis."""
    return jax.device_put(
        x, NamedSharding(hvd.mesh(), P(hvd.data_axis()))
    )


# --------------------------------------------------------------------- eager


def test_allreduce_sum_stacked(hvd):
    n = hvd.size()
    x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    out = hvd.allreduce(stacked(hvd, x), op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out), x.sum(axis=0))


def test_allreduce_average_stacked(hvd):
    n = hvd.size()
    x = np.random.RandomState(0).randn(n, 3, 5).astype(np.float32)
    out = hvd.allreduce(stacked(hvd, x))  # default Average
    np.testing.assert_allclose(np.asarray(out), x.mean(axis=0), rtol=1e-6)


def test_allreduce_replicated(hvd):
    # replicated input == every rank holds the same tensor
    x = np.ones((3,), dtype=np.float32)
    out = hvd.allreduce(jnp.asarray(x), op=hvd.Sum)
    np.testing.assert_allclose(np.asarray(out), x * hvd.size())
    out = hvd.allreduce(jnp.asarray(x), op=hvd.Average)
    np.testing.assert_allclose(np.asarray(out), x)


def test_allreduce_int_dtypes(hvd):
    n = hvd.size()
    # int64 follows jax's x64 config (downcast by default), so test 32-bit
    for dtype in (np.int32, np.uint32):
        x = np.arange(n * 2, dtype=dtype).reshape(n, 2)
        out = hvd.allreduce(stacked(hvd, x), op=hvd.Sum)
        assert np.asarray(out).dtype == dtype
        np.testing.assert_array_equal(np.asarray(out), x.sum(axis=0))


def test_allreduce_prescale_postscale(hvd):
    n = hvd.size()
    x = np.ones((n, 2), dtype=np.float32)
    out = hvd.allreduce(
        stacked(hvd, x), op=hvd.Sum, prescale_factor=2.0, postscale_factor=0.5
    )
    np.testing.assert_allclose(np.asarray(out), np.ones(2) * n)


def test_grouped_allreduce(hvd):
    n = hvd.size()
    xs = [
        np.random.RandomState(i).randn(n, 3).astype(np.float32) for i in range(4)
    ]
    outs = hvd.grouped_allreduce([stacked(hvd, x) for x in xs], op=hvd.Sum)
    for o, x in zip(outs, xs):
        np.testing.assert_allclose(np.asarray(o), x.sum(axis=0), rtol=1e-6)


def test_allgather_stacked(hvd):
    n = hvd.size()
    x = np.arange(n * 2 * 3, dtype=np.float32).reshape(n, 2, 3)
    out = hvd.allgather(stacked(hvd, x))
    np.testing.assert_array_equal(np.asarray(out), x.reshape(n * 2, 3))


def test_allgather_replicated(hvd):
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    out = hvd.allgather(jnp.asarray(x))
    np.testing.assert_array_equal(
        np.asarray(out), np.tile(x, (hvd.size(), 1)).reshape(-1, 3)
    )


def test_broadcast(hvd):
    n = hvd.size()
    x = np.stack([np.full((4,), r, dtype=np.float32) for r in range(n)])
    for root in (0, 3, n - 1):
        out = hvd.broadcast(stacked(hvd, x), root_rank=root)
        np.testing.assert_array_equal(np.asarray(out), np.full((4,), root))


def test_broadcast_bool_and_int(hvd):
    n = hvd.size()
    xb = np.stack([np.asarray([r % 2 == 0, True]) for r in range(n)])
    out = hvd.broadcast(stacked(hvd, xb), root_rank=1)
    assert np.asarray(out).dtype == np.bool_
    np.testing.assert_array_equal(np.asarray(out), xb[1])
    xi = np.stack([np.full((3,), r, dtype=np.int32) for r in range(n)])
    out = hvd.broadcast(stacked(hvd, xi), root_rank=5)
    np.testing.assert_array_equal(np.asarray(out), xi[5])


def test_alltoall(hvd):
    n = hvd.size()
    # rank r sends value 100*r + destination
    x = np.stack(
        [np.repeat(np.arange(n), 1) + 100 * r for r in range(n)]
    ).astype(np.int32)
    out = hvd.alltoall(stacked(hvd, x))
    expect = np.stack([100 * np.arange(n) + r for r in range(n)]).astype(np.int32)
    np.testing.assert_array_equal(np.asarray(out), expect)


def test_reducescatter(hvd):
    n = hvd.size()
    x = np.random.RandomState(0).randn(n, n * 2).astype(np.float32)
    out = hvd.reducescatter(stacked(hvd, x), op=hvd.Sum)
    # stacked output [n, 2]: rank r holds rows r*2:(r+1)*2 of the sum
    s = x.sum(axis=0).reshape(n, 2)
    np.testing.assert_allclose(np.asarray(out), s, rtol=1e-5)


def test_async_handles(hvd):
    n = hvd.size()
    x = np.ones((n, 4), dtype=np.float32)
    h = hvd.allreduce_async(stacked(hvd, x), op=hvd.Sum, name="g0")
    out = hvd.synchronize(h)
    assert hvd.poll(h)
    np.testing.assert_allclose(np.asarray(out), np.full((4,), n))


def test_handle_wait_timeout_warns_on_xla_path(hvd):
    # The pure-XLA Handle cannot interrupt block_until_ready, so a
    # timeout request must not be silently dropped (ADVICE r3).
    n = hvd.size()
    x = stacked(hvd, np.ones((n, 2), dtype=np.float32))
    h = hvd.allreduce_async(x, name="warn0")
    with pytest.warns(RuntimeWarning, match="not enforced on the XLA path"):
        h.wait(timeout=5)


def test_duplicate_name_rejected(hvd):
    n = hvd.size()
    x = stacked(hvd, np.ones((n, 2), dtype=np.float32))
    h1 = hvd.allreduce_async(x, name="dup")
    with pytest.raises(ValueError, match="Duplicate tensor name"):
        hvd.allreduce_async(x, name="dup")
    hvd.synchronize(h1)
    h2 = hvd.allreduce_async(x, name="dup")  # ok after synchronize
    hvd.synchronize(h2)


def test_broadcast_object_and_allgather_object(hvd):
    obj = {"a": 1, "b": [1, 2, 3]}
    assert hvd.broadcast_object(obj) == obj
    gathered = hvd.allgather_object(obj)
    assert len(gathered) == hvd.size()
    assert all(g == obj for g in gathered)


def test_join(hvd):
    assert hvd.join() == hvd.rank()


# ------------------------------------------------------------------- in-jit


def test_injit_allreduce_shard_map(hvd):
    from jax import shard_map

    n = hvd.size()
    ax = hvd.data_axis()

    def step(x):
        return hvd.allreduce(x, op=hvd.Sum, axis=ax)

    x = np.arange(n * 2, dtype=np.float32).reshape(n, 2)
    f = jax.jit(
        shard_map(
            step, mesh=hvd.mesh(), in_specs=(P(ax),), out_specs=P(ax)
        )
    )
    out = f(stacked(hvd, x))
    np.testing.assert_allclose(
        np.asarray(out), np.tile(x.sum(axis=0, keepdims=True), (n, 1))
    )


def test_injit_broadcast_and_allgather(hvd):
    from jax import shard_map

    n = hvd.size()
    ax = hvd.data_axis()
    x = np.stack([np.full((2,), r, dtype=np.float32) for r in range(n)])

    def step(v):
        v = jnp.squeeze(v, 0)
        b = hvd.broadcast(v, root_rank=2, axis=ax)
        g = hvd.allgather(v, axis=ax)
        return b[None], g[None]

    f = jax.jit(
        shard_map(
            step,
            mesh=hvd.mesh(),
            in_specs=(P(ax),),
            out_specs=(P(ax), P(ax)),
        )
    )
    b, g = f(stacked(hvd, x))
    np.testing.assert_array_equal(np.asarray(b)[0], np.full((2,), 2.0))
    np.testing.assert_array_equal(np.asarray(g)[0], x.reshape(-1))


def test_adasum_two_equal_tensors_halves_sum(hvd):
    # adasum(a, a) = a: with identical vectors dot = |a|^2 = |b|^2 so each
    # coefficient is 1/2 (reference adasum.h math).
    n = hvd.size()
    x = np.tile(np.arange(4, dtype=np.float32), (n, 1))
    out = hvd.allreduce(stacked(hvd, x), op=hvd.Adasum)
    np.testing.assert_allclose(np.asarray(out), x[0], rtol=1e-5)


def test_adasum_orthogonal_adds(hvd):
    # orthogonal vectors: dot = 0 so adasum = plain sum
    n = hvd.size()
    x = np.eye(n, dtype=np.float32) * np.arange(1, n + 1)[:, None]
    out = hvd.allreduce(stacked(hvd, x), op=hvd.Adasum)
    np.testing.assert_allclose(
        np.asarray(out), x.sum(axis=0), rtol=1e-5, atol=1e-6
    )


# ---------------------------------------------------- review-found regressions


def test_async_name_released_on_failure(hvd):
    # a failing async op must not poison its name
    import jax.numpy as jnp

    with pytest.raises(Exception):
        hvd.allreduce_async(jnp.ones(3), axis="nonexistent", name="poison")
    h = hvd.allreduce_async(jnp.ones(3), name="poison")  # must not raise
    hvd.synchronize(h)


def test_grouped_allreduce_adasum(hvd):
    n = hvd.size()
    x = np.tile(np.arange(1.0, 5.0, dtype=np.float32), (n, 1))
    (out,) = hvd.grouped_allreduce([stacked(hvd, x)], op=hvd.Adasum)
    # identical tensors: adasum is identity, NOT n*x
    np.testing.assert_allclose(np.asarray(out), x[0], rtol=1e-5)


def test_adasum_with_compression_and_scale(hvd):
    n = hvd.size()
    x = np.tile(np.arange(4, dtype=np.float32), (n, 1))
    from horovod_tpu.compression import Compression

    out = hvd.allreduce(
        stacked(hvd, x),
        op=hvd.Adasum,
        compression=Compression.fp16,
        postscale_factor=2.0,
    )
    np.testing.assert_allclose(np.asarray(out), 2.0 * x[0], rtol=1e-2)


# ------------------------------------------------------- Adasum VHDD oracle


def _vhdd_oracle(vectors):
    """NumPy reference of the VHDD recursion (reference ``adasum.h:194-398``):
    at level l rank i pairs with i^l and combines
    a' = (1 - dot/(2|a|^2)) a + (1 - dot/(2|b|^2)) b. The combine is
    symmetric in (a, b), so pair ordering does not matter."""
    n = len(vectors)
    v = [np.asarray(x, np.float64) for x in vectors]
    level = 1
    while level < n:
        nxt = [None] * n
        for i in range(n):
            a, b = v[i], v[i ^ level]
            dot = float(a @ b)
            na = float(a @ a)
            nb = float(b @ b)
            ca = 0.0 if na == 0 else 1.0 - dot / (2.0 * na)
            cb = 0.0 if nb == 0 else 1.0 - dot / (2.0 * nb)
            nxt[i] = ca * a + cb * b
        v = nxt
        level *= 2
    return v[0]


def test_adasum_matches_vhdd_oracle_n8(hvd):
    n = hvd.size()
    rng = np.random.RandomState(42)
    x = rng.randn(n, 16).astype(np.float32)
    out = hvd.allreduce(stacked(hvd, x), op=hvd.Adasum)
    np.testing.assert_allclose(
        np.asarray(out), _vhdd_oracle(list(x)), rtol=1e-4, atol=1e-5
    )


def test_adasum_matches_vhdd_oracle_n4():
    import jax

    import horovod_tpu as hvd

    hvd.shutdown()
    hvd.init(devices=jax.devices()[:4])
    try:
        rng = np.random.RandomState(7)
        x = rng.randn(4, 8).astype(np.float32)
        out = hvd.allreduce(stacked(hvd, x), op=hvd.Adasum)
        np.testing.assert_allclose(
            np.asarray(out), _vhdd_oracle(list(x)), rtol=1e-4, atol=1e-5
        )
    finally:
        hvd.shutdown()


def test_grouped_adasum_matches_per_tensor_oracle(hvd):
    """Fused Adasum (one butterfly for the whole group, per-tensor scalars
    via segment reductions) must agree with the per-tensor VHDD oracle on a
    group of mixed shapes/dtypes."""
    import jax.numpy as jnp

    n = hvd.size()
    rng = np.random.RandomState(11)
    xs = [
        rng.randn(n, 16).astype(np.float32),
        rng.randn(n, 3, 4).astype(np.float32),
        rng.randn(n, 8).astype(np.float32),
    ]
    stacked_xs = [stacked(hvd, x) for x in xs[:2]] + [
        stacked(hvd, xs[2]).astype(jnp.bfloat16)
    ]
    outs = hvd.grouped_allreduce(stacked_xs, op=hvd.Adasum)
    assert outs[2].dtype == jnp.bfloat16  # dtype round-trips
    for x, out, tol in zip(xs, outs, (1e-4, 1e-4, 5e-2)):
        flat = [x[i].reshape(-1) for i in range(n)]
        np.testing.assert_allclose(
            np.asarray(out, np.float32).reshape(-1),
            _vhdd_oracle(flat),
            rtol=tol,
            atol=tol,
        )


def test_grouped_adasum_collective_count(hvd):
    """An N-tensor fused Adasum issues log2(n) collective-permutes total —
    NOT N*log2(n) (reference adasum.h:194-398 fuses the same way)."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.ops.adasum import _grouped_butterfly

    n = hvd.size()
    mesh = hvd.mesh()
    ax = hvd.data_axis()
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    n_tensors = 5
    sizes = [7, 3, 12, 5, 9]
    seg = np.repeat(np.arange(n_tensors), sizes)

    def fn(v):
        return _grouped_butterfly(v, jnp.asarray(seg), n_tensors, ax, n)

    sm = jax.jit(shard_map(
        fn, mesh=mesh, in_specs=P(), out_specs=P(), check_vma=False
    ))
    text = sm.lower(jnp.ones((sum(sizes),), jnp.float32)).as_text()
    n_permutes = text.count("collective_permute")
    import math

    assert n_permutes == int(math.log2(n)), text[:2000]


def test_adasum_zero_contribution_is_identity(hvd):
    # a join()ed rank contributes zeros; adasum(a, 0) must return a
    # (core.py::_execute_backfilled relies on this)
    n = hvd.size()
    rng = np.random.RandomState(3)
    x = np.zeros((n, 8), np.float32)
    x[0] = rng.randn(8)
    out = hvd.allreduce(stacked(hvd, x), op=hvd.Adasum)
    np.testing.assert_allclose(
        np.asarray(out), _vhdd_oracle(list(x)), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(np.asarray(out), x[0], rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("dtype", [
    np.float32, np.float16, "bfloat16", np.int32, np.int8, np.uint8,
])
@pytest.mark.parametrize("shape", [(), (5,), (2, 3, 4)])
def test_allreduce_dtype_shape_matrix(hvd, dtype, shape):
    """Reference pattern: per-dtype x per-rank-count sweeps comparing the
    collective against local arithmetic (test_tensorflow.py:149-227,
    test_torch.py:48-210). Stacked per-rank values so each rank contributes
    rank-dependent data."""
    if dtype == "bfloat16":
        dtype = jnp.bfloat16
    n = hvd.size()
    rng = np.random.RandomState(0)
    base = (rng.rand(n, *shape) * 4).astype(dtype)
    out = hvd.allreduce(stacked(hvd, base), op=hvd.Sum)
    expect = base.sum(axis=0).astype(dtype)
    got = np.asarray(out)
    assert got.dtype == np.dtype(dtype) and got.shape == tuple(shape)
    if np.dtype(dtype).kind in "iu":
        np.testing.assert_array_equal(got, expect)
    else:
        np.testing.assert_allclose(
            got.astype(np.float32), expect.astype(np.float32),
            rtol=2e-2 if np.dtype(dtype).itemsize < 4 else 1e-6)


@pytest.mark.parametrize("dtype", [np.float32, np.float16, np.int32, np.uint8])
def test_allgather_broadcast_dtype_matrix(hvd, dtype):
    n = hvd.size()
    per_rank = np.stack(
        [np.full((3,), r + 1, dtype=dtype) for r in range(n)])
    gathered = np.asarray(hvd.allgather(stacked(hvd, per_rank)))
    assert gathered.dtype == np.dtype(dtype)
    assert gathered.shape == (n * 3,)  # dim-0 concat contract
    np.testing.assert_array_equal(gathered.reshape(n, 3), per_rank)

    out = np.asarray(hvd.broadcast(stacked(hvd, per_rank), root_rank=1))
    np.testing.assert_array_equal(out, per_rank[1])


def test_reducescatter_rejects_adasum(hvd):
    x = np.ones((hvd.size() * 2,), np.float32)
    with pytest.raises(ValueError, match="Average/Sum"):
        hvd.reducescatter(x, op=hvd.Adasum)
    with pytest.raises(ValueError, match="Average/Sum"):
        hvd.reducescatter_async(x, op=hvd.Adasum)


# -------------------------------------------- flat fusion / ZeRO satellites


def test_mixed_dtype_flat_fusion_roundtrip(hvd):
    """Interleaved f32/bf16/i32 tensors through the fused flat buffer: the
    per-dtype concat/split must restore ordering, shapes and dtypes (the
    signature ordering bug class the flat buffer could hide)."""
    n = hvd.size()
    tensors = [
        jnp.arange(6, dtype=jnp.float32).reshape(2, 3),       # f32 #1
        jnp.full((4,), 1.5, jnp.bfloat16),                    # bf16 #1
        jnp.arange(5, dtype=jnp.int32),                       # i32 #1
        jnp.linspace(-1.0, 1.0, 7).astype(jnp.float32),       # f32 #2
        jnp.full((2, 2), -2.0, jnp.bfloat16),                 # bf16 #2
        jnp.full((3,), 7, jnp.int32),                         # i32 #2
        jnp.full((1,), 0.25, jnp.float32),                    # f32 #3
    ]
    outs = hvd.grouped_allreduce(tensors, op=hvd.Sum)
    assert len(outs) == len(tensors)
    for t, o in zip(tensors, outs):
        assert o.dtype == t.dtype and o.shape == t.shape
        expect = np.asarray(t, np.float32) * n  # replicated: sum = n * x
        np.testing.assert_allclose(
            np.asarray(o, np.float32), expect,
            rtol=2e-2 if t.dtype == jnp.bfloat16 else 1e-6)


def test_mixed_dtype_quantized_allreduce_roundtrip(hvd):
    """Interleaved f32/bf16/i32 leaves under Compression.int8: integer and
    already-bf16 tensors pass through uncompressed EXACTLY as fp16 does
    (bit-identical to the uncompressed allreduce), small f32 tensors pass
    through too (below the min-quantize floor the ring's block padding
    would cost more than fp32), and a large f32 tensor rides the quantized
    ring within tolerance — shapes and dtypes preserved throughout."""
    from horovod_tpu.compression import Compression

    n = hvd.size()
    tensors = [
        jnp.arange(6, dtype=jnp.float32).reshape(2, 3),       # f32, tiny
        jnp.full((4,), 1.5, jnp.bfloat16),                    # bf16 #1
        jnp.arange(5, dtype=jnp.int32),                       # i32 #1
        jnp.linspace(-1.0, 1.0, 1200).astype(jnp.float32),    # f32, big
        jnp.full((3,), 7, jnp.int32),                         # i32 #2
    ]
    for t in tensors:
        out = hvd.allreduce(t, op=hvd.Sum, compression=Compression.int8)
        assert out.dtype == t.dtype and out.shape == t.shape
        plain = hvd.allreduce(t, op=hvd.Sum)
        if t.dtype in (jnp.int32, jnp.bfloat16):
            # passthrough: bit-identical to the uncompressed collective
            np.testing.assert_array_equal(
                np.asarray(out), np.asarray(plain))
        else:
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(t, np.float32) * n,
                atol=float(jnp.abs(t).max()) / 127 * n * 1.5)


def test_reducescatter_nondivisible_padding(hvd):
    """Leading dims not divisible by the axis size ride the zero-padding
    path: each rank holds ceil(rows/N) rows, pad rows land as zeros in the
    tail shards."""
    n = hvd.size()
    rows = n + 2  # 10 rows over 8 ranks -> padded to 16, 2 rows/rank
    x = np.random.RandomState(0).randn(n, rows, 3).astype(np.float32)
    out = np.asarray(hvd.reducescatter(stacked(hvd, x), op=hvd.Sum))
    per = -(-rows // n)
    s = x.sum(axis=0)
    expect = np.concatenate(
        [s, np.zeros((per * n - rows, 3), np.float32)]).reshape(n, per, 3)
    np.testing.assert_allclose(out, expect, rtol=1e-5)

    # replicated input too
    y = np.random.RandomState(1).randn(rows, 2).astype(np.float32)
    out = np.asarray(hvd.reducescatter(jnp.asarray(y), op=hvd.Average))
    expect = np.concatenate(
        [y, np.zeros((per * n - rows, 2), np.float32)]).reshape(n, per, 2)
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_donation_does_not_break_guarded_retry(hvd, monkeypatch):
    """HOROVOD_DONATE_FUSED=1 (forced on, even on CPU) + an injected
    transient dispatch failure: the _guarded retry must re-run the donated
    launch successfully — chaos fires *before* the launch consumes its
    buffers, so the re-dispatch sees live inputs."""
    from horovod_tpu.ops import collective as C
    from horovod_tpu.resilience import chaos

    monkeypatch.setenv("HOROVOD_DONATE_FUSED", "1")
    monkeypatch.setattr(C, "_donate_fused", None)
    C._eager_fused_allreduce_fn.cache_clear()
    C._eager_reducescatter_fn.cache_clear()
    n = hvd.size()
    try:
        chaos.configure("collective_fail=1")
        tensors = [jnp.ones((4,), jnp.float32), jnp.full((3,), 2.0)]
        outs = hvd.grouped_allreduce(tensors, op=hvd.Sum)
        np.testing.assert_allclose(np.asarray(outs[0]), np.full((4,), n))
        np.testing.assert_allclose(np.asarray(outs[1]), np.full((3,), 2.0 * n))
        # reduce-scatter's donated jit under a fresh injected failure
        chaos.configure("collective_fail=1")
        x = np.arange(n * n, dtype=np.float32).reshape(n, n)
        out = hvd.reducescatter(stacked(hvd, x.copy()), op=hvd.Sum)
        np.testing.assert_allclose(
            np.asarray(out), x.sum(axis=0).reshape(n, 1))
    finally:
        chaos.configure(None)
        chaos.reset()
        monkeypatch.setattr(C, "_donate_fused", None)
        C._eager_fused_allreduce_fn.cache_clear()
        C._eager_reducescatter_fn.cache_clear()


def test_eager_cache_cap_and_eviction_metric(hvd, monkeypatch):
    """HOROVOD_EAGER_CACHE_SIZE caps the compiled-kernel caches with LRU
    eviction; displacements surface as eager_compile_cache_evictions."""
    from horovod_tpu.ops import collective as C

    hvd.metrics.reset()
    monkeypatch.setenv("HOROVOD_EAGER_CACHE_SIZE", "2")
    # the fused-allreduce cache keys on the (shape, dtype) signature — the
    # shape-polymorphic growth the cap exists to bound
    C._eager_fused_allreduce_fn.cache_clear()  # rebuild with the new cap
    try:
        for rows in (2, 3, 4, 5):  # 4 distinct signatures > cap of 2
            ts = [jnp.ones((rows,), jnp.float32),
                  jnp.ones((rows, 2), jnp.float32)]
            hvd.grouped_allreduce(ts, op=hvd.Sum)
        info = C._eager_fused_allreduce_fn.cache_info()
        assert info.maxsize == 2
        assert info.currsize <= 2
        ev = hvd.metrics.value(
            "eager_compile_cache_evictions", kind="fused_allreduce")
        assert ev and ev >= 2
        # LRU order: re-using the most recent signature is a hit, no evict
        before = ev
        hvd.grouped_allreduce(
            [jnp.ones((5,), jnp.float32), jnp.ones((5, 2), jnp.float32)],
            op=hvd.Sum)
        assert hvd.metrics.value(
            "eager_compile_cache_evictions", kind="fused_allreduce") == before
    finally:
        C._eager_fused_allreduce_fn.cache_clear()
