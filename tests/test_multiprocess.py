"""Real 2-process distributed tests through the launcher: jax.distributed
wire-up + cross-process collectives on host-local values — the reference's
``horovodrun -np 2 pytest`` pattern (SURVEY.md §4) done TPU-native (gloo CPU
collectives stand in for ICI)."""

import os

import numpy as np
import pytest

from horovod_tpu.run import runner

_TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO_ROOT = os.path.dirname(_TESTS_DIR)


def _worker_env():
    """Workers unpickle functions from this module by reference, so both the
    repo root and the tests dir must be importable there."""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [_REPO_ROOT, _TESTS_DIR, env.get("PYTHONPATH", "")]
    )
    return env


def _two_proc_collectives():
    # runs inside each launched worker process
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    results = {}
    results["size"] = hvd.size()
    results["process_size"] = hvd.process_size()
    rank = hvd.process_rank()
    results["rank"] = rank

    # allreduce: each process contributes rank+1 -> sum=3, avg=1.5
    x = np.full((2, 3), float(rank + 1), np.float32)
    results["sum"] = np.asarray(hvd.allreduce(x, hvd.Sum)).tolist()
    results["avg"] = np.asarray(hvd.allreduce(x, hvd.Average)).tolist()

    # allgather: concat per-process rows
    g = np.full((1, 2), float(rank), np.float32)
    results["gathered"] = np.asarray(hvd.allgather(g)).tolist()

    # broadcast from process 1
    b = np.array([float(rank * 10)], np.float32)
    results["bcast"] = np.asarray(hvd.broadcast(b, root_rank=1)).tolist()

    # grouped allreduce rides the same host-local path
    ga = hvd.grouped_allreduce(
        [np.array([float(rank)]), np.array([float(rank * 2)])], hvd.Sum
    )
    results["grouped"] = [np.asarray(t).tolist() for t in ga]

    # object collectives
    results["objs"] = hvd.allgather_object({"r": rank, "msg": "x" * (rank + 1)})
    results["obj_b"] = hvd.broadcast_object({"from": rank}, root_rank=0)

    # alltoall: process r sends row j to process j
    a2a = np.array([[rank, 0.0], [rank, 1.0]], np.float32)
    results["alltoall"] = np.asarray(hvd.alltoall(a2a)).tolist()

    # reducescatter: each gets its reduced shard
    rs = np.arange(4, dtype=np.float32).reshape(4, 1) + rank
    results["rs"] = np.asarray(hvd.reducescatter(rs, hvd.Sum)).tolist()

    # every worker's registry saw its own traffic (ISSUE 1 acceptance:
    # eager multi-process run -> nonzero op counts/bytes + compile-cache
    # accounting, queried through hvd.metrics, not ad hoc probes)
    results["metrics"] = {
        "allreduce_count": hvd.metrics.value("allreduce_count"),
        "allreduce_bytes": hvd.metrics.value("allreduce_bytes"),
        "allgather_count": hvd.metrics.value("allgather_count"),
        "compile_lookups": sum(
            sum(fam["samples"].values())
            for name, fam in hvd.metrics.snapshot().items()
            if name.startswith("eager_compile_cache_")
        ),
    }
    return results


def test_two_process_collectives_end_to_end():
    out = runner.run(
        _two_proc_collectives, np=2, env=_worker_env(), timeout_s=240
    )
    for rank, r in enumerate(out):
        assert r["rank"] == rank
        assert r["size"] == 2  # one CPU device per process
        assert r["process_size"] == 2
        assert r["sum"] == [[3.0] * 3] * 2
        assert r["avg"] == [[1.5] * 3] * 2
        assert r["gathered"] == [[0.0, 0.0], [1.0, 1.0]]
        assert r["bcast"] == [10.0]
        assert r["grouped"] == [[1.0], [2.0]]
        assert r["objs"] == [
            {"r": 0, "msg": "x"},
            {"r": 1, "msg": "xx"},
        ]
        assert r["obj_b"] == {"from": 0}
        # alltoall: row j of every process's tensor lands on process j
        assert r["alltoall"] == [[0.0, float(rank)], [1.0, float(rank)]]
        # reducescatter: sum_p(arange(4)+p) = [1,3,5,7]; rank r gets rows
        # [2r, 2r+2)
        assert r["rs"] == [[4.0 * rank + 1.0], [4.0 * rank + 3.0]]
        m = r["metrics"]
        # 2 allreduce calls + 1 grouped (2 tensors); sizes: 2x3 f32 twice
        # + [1]+[1] f32 grouped
        assert m["allreduce_count"] == 3
        assert m["allreduce_bytes"] == 2 * (2 * 3 * 4) + 2 * 4
        assert m["allgather_count"] >= 1  # object collectives ride it too
        assert m["compile_lookups"] >= 3


def _two_proc_train_step():
    """Full DP train step over the 2-process global mesh (SPMD jit path)."""
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.models import MLP
    from horovod_tpu.training import (
        init_model,
        make_shardmap_train_step,
        replicate,
    )

    hvd.init()
    rank = hvd.process_rank()
    model = MLP(features=(8, 4))
    tx = optax.sgd(0.1)
    rng = jax.random.PRNGKey(0)
    params, batch_stats = init_model(
        model, rng, jnp.zeros((1, 6), jnp.float32)
    )
    params = replicate(params)
    batch_stats = replicate(batch_stats)
    opt_state = replicate(tx.init(params))
    step = make_shardmap_train_step(model, tx)

    mesh = hvd.mesh()
    # per-process local batch -> global [2, 6] array sharded over data
    local_x = np.random.RandomState(rank).rand(1, 6).astype(np.float32)
    local_y = np.array([rank % 4], np.int32)
    gx = multihost_utils.host_local_array_to_global_array(
        local_x, mesh, P("data")
    )
    gy = multihost_utils.host_local_array_to_global_array(
        local_y, mesh, P("data")
    )
    params, batch_stats, opt_state, loss = step(
        params, batch_stats, opt_state, gx, gy
    )
    return float(np.asarray(loss))


def _two_proc_multichip_collectives():
    """2 processes x 2 local chips: exercises the host-local tiling math for
    local_size > 1 (one process per TPU host owning several chips)."""
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    rank = hvd.process_rank()
    results = {
        "size": hvd.size(),
        "local_size": hvd.local_size(),
        "process_size": hvd.process_size(),
    }
    x = np.full((3,), float(rank + 1), np.float32)
    results["sum"] = np.asarray(hvd.allreduce(x, hvd.Sum)).tolist()
    results["avg"] = np.asarray(hvd.allreduce(x, hvd.Average)).tolist()
    g = np.full((1, 2), float(rank), np.float32)
    results["gathered"] = np.asarray(hvd.allgather(g)).tolist()
    b = np.array([float(rank * 10 + 5)], np.float32)
    results["bcast"] = np.asarray(hvd.broadcast(b, root_rank=1)).tolist()
    # alltoall / reducescatter with local_size > 1 (the TPU-native layout):
    # process r sends row j to process j / receives its reduced shard
    a2a = np.array([[rank, 0.0], [rank, 1.0]], np.float32)
    results["alltoall"] = np.asarray(hvd.alltoall(a2a)).tolist()
    # dim0 divisible by the 4 chips -> chip-level tiled exchange path
    # (each chip receives rows elements, not n_chips*rows)
    a2a4 = np.array(
        [[rank, 0.0], [rank, 1.0], [rank, 2.0], [rank, 3.0]], np.float32
    )
    results["alltoall4"] = np.asarray(hvd.alltoall(a2a4)).tolist()
    rs = np.arange(4, dtype=np.float32).reshape(4, 1) + rank
    results["rs_sum"] = np.asarray(hvd.reducescatter(rs, hvd.Sum)).tolist()
    results["rs_avg"] = np.asarray(
        hvd.reducescatter(rs, hvd.Average)
    ).tolist()
    # odd leading dim: not divisible by the 4 chips -> allreduce+slice path
    rs3 = np.full((2, 3), float(rank + 1), np.float32)
    results["rs_odd"] = np.asarray(hvd.reducescatter(rs3, hvd.Sum)).tolist()
    # adasum over host-local values: pair-combine of ones vs twos
    results["adasum"] = np.asarray(
        hvd.allreduce(np.full((4,), float(rank + 1), np.float32), hvd.Adasum)
    ).tolist()
    # grouped (fused) adasum over host-local values: one flat-concat
    # butterfly across processes with PER-TENSOR dot/norm scalars. The
    # second tensor flips sign on rank 1 so its combine coefficients differ
    # from the first's — concat-level (single-segment) scalars would give a
    # different answer, pinning the segmentation.
    sign = 1.0 if rank == 0 else -1.0
    ga, gb = hvd.grouped_allreduce(
        [
            np.full((4,), float(rank + 1), np.float32),
            np.full((2, 3), sign * float(rank + 1), np.float32),
        ],
        op=hvd.Adasum,
    )
    results["adasum_grouped"] = [
        np.asarray(ga).tolist(),
        np.asarray(gb).tolist(),
    ]
    return results


def test_two_process_multichip_collectives():
    out = runner.run(
        _two_proc_multichip_collectives, np=2, env=_worker_env(), timeout_s=240
    )
    for rank, r in enumerate(out):
        assert r["size"] == 4  # 2 processes x 2 chips
        assert r["local_size"] == 2
        assert r["process_size"] == 2
        # process-level semantics: sum over the 2 processes, not the 4 chips
        assert r["sum"] == [3.0, 3.0, 3.0]
        assert r["avg"] == [1.5, 1.5, 1.5]
        assert r["gathered"] == [[0.0, 0.0], [1.0, 1.0]]
        assert r["bcast"] == [15.0]
        # row j of every process's tensor lands on process j
        assert r["alltoall"] == [[0.0, float(rank)], [1.0, float(rank)]]
        # block p of every process's 4-row tensor, in process order
        # (chip-level tiled exchange path: dim0 % n_chips == 0)
        assert r["alltoall4"] == [
            [0.0, float(2 * rank)], [0.0, float(2 * rank + 1)],
            [1.0, float(2 * rank)], [1.0, float(2 * rank + 1)],
        ]
        # sum_p(arange(4)+p) = [1,3,5,7]; process r gets rows [2r, 2r+2)
        assert r["rs_sum"] == [[4.0 * rank + 1.0], [4.0 * rank + 3.0]]
        assert r["rs_avg"] == [
            [2.0 * rank + 0.5], [2.0 * rank + 1.5]
        ]
        # full reduce [2,3] of 1s+2s = 3s; process r gets row r
        assert r["rs_odd"] == [[3.0, 3.0, 3.0]]
        # VHDD combine of a=1s, b=2s (d=4): dot=8, |a|^2=4, |b|^2=16
        # -> ca = 1-8/8 = 0, cb = 1-8/32 = 0.75 -> 1.5s
        assert r["adasum"] == [1.5, 1.5, 1.5, 1.5]
        # per-tensor VHDD scalars: tensor A (1s vs 2s): ca=0, cb=0.75 ->
        # 1.5s; tensor B (1s vs -2s): dot=-12, |a|^2=6, |b|^2=24 -> ca=2,
        # cb=1.25 -> 2*1 + 1.25*(-2) = -0.5. Concat-level scalars would
        # yield 3.3/... instead, so this distinguishes the segmentation.
        ga, gb = r["adasum_grouped"]
        assert ga == [1.5] * 4
        assert gb == [[-0.5] * 3] * 2


def test_two_process_train_step():
    out = runner.run(
        _two_proc_train_step, np=2, env=_worker_env(), timeout_s=240
    )
    assert len(out) == 2
    # identical global loss on both processes
    assert np.isfinite(out[0])
    assert out[0] == pytest.approx(out[1])


def _two_proc_torch_and_checkpoint():
    """Regression coverage for cross-process torch state broadcast and
    checkpoint save/restore: fresh-optimizer broadcast_optimizer_state must
    not deadlock, restore must work when only rank 0 has the files, and a
    writer-side save failure must raise on every rank."""
    import os
    import shutil
    import tempfile

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import torch

    import horovod_tpu.torch as hvd
    from horovod_tpu import checkpoint as ckpt

    hvd.init()
    r = hvd.process_rank()
    results = {}

    # 1. fresh optimizer (no state): dummy step must run on EVERY rank
    torch.manual_seed(3)
    model = torch.nn.Linear(4, 2)
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9),
        named_parameters=model.named_parameters(),
    )
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    hvd.broadcast_optimizer_state(opt, root_rank=0)
    results["opt_lr"] = opt.state_dict()["param_groups"][0]["lr"]

    # 2. checkpoint written by rank 0 into a rank-PRIVATE dir: non-root has
    # no files at all and must restore via broadcast
    d = os.path.join(tempfile.gettempdir(), f"hvdckpt_rank{r}")
    shutil.rmtree(d, ignore_errors=True)
    state = {"w": np.full((3,), float(r + 1), np.float32), "step": 4}
    ckpt.save(d, 4, state)
    out = ckpt.restore(d)
    results["restored_w"] = np.asarray(out["w"]).tolist()
    results["restored_step"] = out["step"]

    # 3. duplicate save without force: FileExistsError on EVERY rank
    try:
        ckpt.save(d, 4, state)
        results["dup_save"] = "no-error"
    except FileExistsError:
        results["dup_save"] = "file-exists"
    except RuntimeError as e:
        results["dup_save"] = (
            "runtime-file-exists"
            if "FileExistsError" in str(e)
            else f"runtime-other: {e}"
        )
    shutil.rmtree(d, ignore_errors=True)
    return results


def test_two_process_torch_and_checkpoint():
    out = runner.run(
        _two_proc_torch_and_checkpoint, np=2, env=_worker_env(), timeout_s=240
    )
    for r, res in enumerate(out):
        assert res["opt_lr"] == pytest.approx(0.1)
        # rank 0's state everywhere (non-root had no checkpoint files)
        assert res["restored_w"] == [1.0, 1.0, 1.0]
        assert res["restored_step"] == 4
    assert out[0]["dup_save"] == "file-exists"
    assert out[1]["dup_save"] == "runtime-file-exists"


def _two_proc_tensorflow():
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import tensorflow as tf

    import horovod_tpu.tensorflow as hvd

    hvd.init()
    r = hvd.process_rank()
    out = {}
    out["avg"] = hvd.allreduce(
        tf.constant([float(r + 1)] * 3), op=hvd.Average).numpy().tolist()
    out["gathered"] = hvd.allgather(
        tf.constant([[float(r)]])).numpy().tolist()
    out["bcast"] = hvd.broadcast(
        tf.constant([float(r + 10)]), root_rank=0).numpy().tolist()
    # variable sync: non-root starts different, ends equal to root
    v = tf.Variable([float(r), 1.0])
    hvd.broadcast_variables([v], root_rank=0)
    out["var"] = v.numpy().tolist()
    return out


def test_two_process_tensorflow_frontend():
    results = runner.run(
        _two_proc_tensorflow, np=2, env=_worker_env(), timeout_s=600.0)
    for r in results:
        np.testing.assert_allclose(r["avg"], [1.5] * 3)
        np.testing.assert_allclose(r["gathered"], [[0.0], [1.0]])
        np.testing.assert_allclose(r["bcast"], [10.0])
        np.testing.assert_allclose(r["var"], [0.0, 1.0])


def _four_proc_collectives():
    """np=4: more ranks than any other e2e — distinct code paths in the
    bitvector AND sync (more proposer patterns), the VHDD butterfly
    (log2(4)=2 levels), and allgather displacement math."""
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.process_rank()
    out = {"rank": r, "size": hvd.size()}
    x = np.full((3,), float(r + 1), np.float32)
    out["sum"] = np.asarray(hvd.allreduce(x, hvd.Sum)).tolist()
    out["avg"] = np.asarray(hvd.allreduce(x, hvd.Average)).tolist()
    g = np.full((1, 2), float(r), np.float32)
    out["gathered"] = np.asarray(hvd.allgather(g)).tolist()
    out["bcast"] = np.asarray(
        hvd.broadcast(np.array([float(r)], np.float32), root_rank=2)
    ).tolist()
    # 4-rank VHDD butterfly: 2 levels (1^2, then pairs of pairs)
    out["adasum"] = np.asarray(
        hvd.allreduce(np.full((4,), float(r + 1), np.float32), hvd.Adasum)
    ).tolist()
    a2a = np.arange(4, dtype=np.float32).reshape(4, 1) + 10 * r
    out["alltoall"] = np.asarray(hvd.alltoall(a2a)).tolist()
    # ISSUE 1 acceptance: a 4-process eager allreduce run shows nonzero
    # op counters and compile-cache accounting via hvd.metrics
    out["metrics"] = {
        "allreduce_count": hvd.metrics.value("allreduce_count"),
        "allreduce_bytes": hvd.metrics.value("allreduce_bytes"),
        "cache_misses": sum(
            sum(fam["samples"].values())
            for name, fam in hvd.metrics.snapshot().items()
            if name == "eager_compile_cache_misses"
        ),
    }
    return out


@pytest.mark.slow
def test_four_process_collectives():
    out = runner.run(
        _four_proc_collectives, np=4, env=_worker_env(), timeout_s=300
    )
    import numpy as np

    for r, res in enumerate(out):
        assert res["rank"] == r and res["size"] == 4
        assert res["sum"] == [10.0] * 3  # 1+2+3+4
        assert res["avg"] == [2.5] * 3
        assert res["gathered"] == [[0.0, 0.0], [1.0, 1.0], [2.0, 2.0],
                                   [3.0, 3.0]]
        assert res["bcast"] == [2.0]
        # row j of every process's tensor lands on process j, process order
        assert res["alltoall"] == [[float(r)], [10.0 + r], [20.0 + r],
                                   [30.0 + r]]
    # adasum vs the NumPy VHDD oracle over 4 rank vectors
    from tests.test_ops import _vhdd_oracle  # noqa

    expect = _vhdd_oracle([np.full((4,), float(i + 1)) for i in range(4)])
    for res in out:
        np.testing.assert_allclose(res["adasum"], expect, rtol=1e-4)
        # sum + avg on (3,) f32 through the instrumented eager path
        # (Adasum rides its own VHDD kernels, not counted under allreduce)
        assert res["metrics"]["allreduce_count"] >= 2
        assert res["metrics"]["allreduce_bytes"] >= 2 * 3 * 4
        assert res["metrics"]["cache_misses"] >= 1


def _two_proc_async_checkpoint():
    """Async save + fence + restore across 2 processes: the writer's status
    broadcast must release both ranks, and the restore broadcast must hand
    rank 1 the state even though only rank 0's directory has files."""
    import os
    import tempfile

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu import checkpoint as ckpt

    hvd.init()
    r = hvd.process_rank()
    # rank-PRIVATE dir: non-root never sees the files, restore must broadcast
    d = os.path.join(tempfile.gettempdir(), f"hvd_async_ck_rank{r}")
    import shutil

    shutil.rmtree(d, ignore_errors=True)
    mgr = ckpt.CheckpointManager(d)
    state = {"w": np.full((3,), 7.0, np.float32), "step": 4}
    mgr.save(4, state, asynchronous=True)
    mgr.wait_until_finished()

    out = {"rank": r, "has_files": os.path.isdir(os.path.join(d, "step_4"))}
    restored = mgr.restore()
    out["w"] = np.asarray(restored["w"]).tolist()
    out["step"] = restored["step"]

    # writer-side failure (step_4 exists, no force) must raise on BOTH ranks
    mgr.save(4, state, asynchronous=True)
    try:
        mgr.wait_until_finished()
        out["err"] = None
    except (FileExistsError, RuntimeError) as e:
        out["err"] = type(e).__name__
    shutil.rmtree(d, ignore_errors=True)
    return out


def test_two_process_async_checkpoint():
    out = runner.run(
        _two_proc_async_checkpoint, np=2, env=_worker_env(), timeout_s=240
    )
    for r, res in enumerate(out):
        assert res["rank"] == r
        assert res["has_files"] == (r == 0)  # rank-0-writer pattern
        assert res["w"] == [7.0, 7.0, 7.0]
        assert res["step"] == 4
        # failure fenced to every rank: writer re-raises the original,
        # non-writers get the wrapped status error
        assert res["err"] == ("FileExistsError" if r == 0 else "RuntimeError")


def _two_proc_torch_ef():
    """Error-feedback compression cross-process: each rank sees a DIFFERENT
    data half (so residuals genuinely differ per rank), gradients exchange
    compressed, and both ranks stay bit-identical in parameters — the
    invariant that proves the residual is per-rank local state while the
    wire carries the same reduced values everywhere."""
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import torch

    import horovod_tpu as hvd
    import horovod_tpu.torch as thvd

    hvd.init()
    r = hvd.process_rank()
    torch.manual_seed(0)  # identical init on both ranks
    model = torch.nn.Sequential(
        torch.nn.Linear(8, 16), torch.nn.Tanh(), torch.nn.Linear(16, 2)
    )
    opt = thvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.05),
        named_parameters=model.named_parameters(),
        compression=thvd.Compression.fp16, error_feedback=True,
    )
    rng = np.random.RandomState(100 + r)  # rank-dependent data
    losses = []
    for _ in range(8):
        x = torch.from_numpy(rng.randn(16, 8).astype(np.float32))
        y = torch.from_numpy(rng.randint(0, 2, 16))
        opt.zero_grad()
        loss = torch.nn.functional.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        losses.append(float(loss))
    # parameter fingerprint must agree across ranks (same reduced updates)
    fp = float(sum(p.detach().abs().sum() for p in model.parameters()))
    n_resid = len(opt._ef_residual)
    # residual fingerprint must DIFFER across ranks (per-rank local error
    # of per-rank gradients) — zeroed or allreduced residuals would match
    resid_fp = float(sum(t.abs().sum() for t in opt._ef_residual.values()))
    return {"rank": r, "fp": fp, "n_resid": n_resid, "resid_fp": resid_fp,
            "finite": all(np.isfinite(losses))}


def test_two_process_torch_error_feedback():
    out = runner.run(
        _two_proc_torch_ef, np=2, env=_worker_env(), timeout_s=300
    )
    assert all(res["finite"] for res in out)
    assert all(res["n_resid"] == 4 for res in out)  # 2 weights + 2 biases
    np.testing.assert_allclose(out[0]["fp"], out[1]["fp"], rtol=1e-5)
    assert all(res["resid_fp"] > 0 for res in out)
    assert abs(out[0]["resid_fp"] - out[1]["resid_fp"]) > 1e-9


def _two_proc_ragged_gather():
    """Variable-leading-dim allgather across dtypes/ranks (the Allgatherv
    displacement semantics added for hostlocal arrays)."""
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    r = hvd.process_rank()
    out = {"rank": r}
    # rank r contributes r+1 rows; 2-D f32, 3-D f32, 1-D int32, 1-D bool
    f2 = np.full((r + 1, 2), float(r), np.float32)
    out["f2"] = np.asarray(hvd.allgather(f2)).tolist()
    f3 = np.full((r + 2, 2, 2), float(10 + r), np.float32)
    out["f3_shape"] = list(np.asarray(hvd.allgather(f3)).shape)
    i1 = np.arange(r + 1, dtype=np.int32) + 100 * r
    out["i1"] = np.asarray(hvd.allgather(i1)).tolist()
    b1 = np.array([bool(r)] * (r + 1))
    out["b1"] = np.asarray(hvd.allgather(b1)).astype(int).tolist()
    return out


@pytest.mark.slow
def test_two_process_ragged_allgather():
    out = runner.run(
        _two_proc_ragged_gather, np=2, env=_worker_env(), timeout_s=300
    )
    r0, r1 = out
    assert r0["f2"] == [[0.0, 0.0]] + [[1.0, 1.0]] * 2
    assert r0["f3_shape"] == [5, 2, 2]  # 2 + 3 rows
    assert r0["i1"] == [0, 100, 101]
    assert r0["b1"] == [0, 1, 1]
    assert r1 == r0 | {"rank": 1}


def _two_proc_fleet_observability():
    """ISSUE 7 fleet plane across REAL processes: both ranks publish metric
    snapshots (+ arrival rings + clock sync) to the launcher's KV, rank 0
    aggregates fleet stats and rank-labeled series, a short-TTL snapshot
    shows the rank as DEAD (not absent), and the two ranks' trace sidecars
    merge into one skew-corrected timeline with correlated spans."""
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    rank_env = int(os.environ["HOROVOD_RANK"])
    trace_dir = os.environ["HVD_FLEET_TRACE_DIR"]
    timeline = os.path.join(trace_dir, f"tl_rank{rank_env}.json")
    os.environ["HOROVOD_TIMELINE"] = timeline
    import time

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.observability import aggregate, clock, straggler, trace
    from horovod_tpu.run.rendezvous import KVStoreClient

    hvd.init()
    r = hvd.process_rank()
    client = KVStoreClient(
        os.environ["HVD_RUN_KV_ADDR"], int(os.environ["HVD_RUN_KV_PORT"])
    )
    off, err = clock.refresh_from_kv(client, rank=r)
    out = {"rank": r, "clock_err": err, "clock_off": off}

    for step in range(2):
        straggler.set_step(step)
        hvd.allreduce(np.full((4,), float(r + 1), np.float32), hvd.Sum)

    # first lease is short-lived: after it expires rank 1 must show DEAD;
    # the generous republish below is what the live fleet view aggregates
    pub = aggregate.MetricsPublisher(
        client, rank=r, interval=60.0, ttl=(0.5 if r == 1 else 60.0)
    )
    pub.publish_once()
    trace.flush(timeline)
    client.put(f"/obs/trace_ready/{r}", timeline.encode())

    if r == 1:
        # wait for rank 0's dead-rank observation, then republish (alive
        # again) so the final aggregation sees both ranks
        client.wait_for("/obs/saw_dead", timeout=60)
        pub2 = aggregate.MetricsPublisher(
            client, rank=r, interval=60.0, ttl=60.0)
        pub2.publish_once()
        client.wait_for("/obs/done", timeout=60)
        return out

    # ---- rank 0: the aggregator ----
    agg = aggregate.FleetAggregator(client, world=2, register=False)
    client.wait_for("/obs/snap/1", timeout=60)
    first = agg.collect()
    out["first_ranks"] = first["ranks"]
    deadline = time.time() + 30
    dead = []
    while time.time() < deadline:
        view = agg.collect()
        if view["dead_ranks"]:
            dead = view["dead_ranks"]
            break
        time.sleep(0.2)
    out["dead_ranks"] = dead
    client.put("/obs/saw_dead", b"1")
    # rank 1 republishes with a generous lease: both ranks live again
    deadline = time.time() + 30
    while time.time() < deadline:
        fleet = agg.collect()
        if fleet["ranks"] == [0, 1]:
            break
        time.sleep(0.2)
    out["final_ranks"] = fleet["ranks"]
    s = fleet["metrics"]["allreduce_count"]["samples"][""]
    out["count_ranks"] = s["ranks"]
    out["count_stats"] = {
        "min": s["min"], "max": s["max"], "mean": s["mean"], "p99": s["p99"]
    }
    prom = aggregate.to_prometheus_fleet(fleet)
    out["rank_series"] = (
        'allreduce_count{rank="0"} 2' in prom
        and 'allreduce_count{rank="1"} 2' in prom
    )
    out["p99_series"] = 'fleet_allreduce_count{stat="p99"} 2' in prom

    # ---- merged skew-corrected trace across both ranks' sidecars ----
    other = client.wait_for("/obs/trace_ready/1", timeout=60).decode()
    merged = clock.merge_rank_traces(
        [timeline, other], os.path.join(trace_dir, "merged.json"))
    by_key = {}
    for e in merged:
        a = e.get("args") or {}
        pid = str(e.get("pid", ""))
        if "seq" in a and pid.startswith("rank") and "-host" not in pid:
            by_key.setdefault(
                (a["step"], a["gen"], a["seq"]), set()).add(pid)
    out["correlated_keys"] = sorted(
        [list(k) for k, pids in by_key.items()
         if pids >= {"rank0", "rank1"}]
    )
    client.put("/obs/done", b"1")
    return out


def test_two_process_fleet_observability(tmp_path):
    env = _worker_env()
    env["HVD_FLEET_TRACE_DIR"] = str(tmp_path)
    out = runner.run(
        _two_proc_fleet_observability, np=2, env=env, timeout_s=240
    )
    r0 = next(r for r in out if r["rank"] == 0)
    # clock sync happened on both ranks with a sane (local-loopback) bound
    assert all(r["clock_err"] is not None and r["clock_err"] < 1.0
               for r in out)
    # both ranks' snapshots aggregated; the short-lease rank showed DEAD
    # (surfaced, not silently absent) and came back on republish
    assert r0["first_ranks"] == [0, 1]
    assert r0["dead_ranks"] == [1]
    assert r0["final_ranks"] == [0, 1]
    # fleet stats + rank-labeled raw series served by rank 0
    assert r0["count_ranks"] == {"0": 2.0, "1": 2.0}
    assert r0["count_stats"]["min"] == 2.0
    assert r0["count_stats"]["p99"] == 2.0
    assert r0["rank_series"] and r0["p99_series"]
    # the merged timeline holds BOTH ranks' spans for the same collectives,
    # tied by (step, gen, seq): 2 steps, seq resetting at each boundary
    assert r0["correlated_keys"] == [[0, 0, 0], [1, 0, 0]]


def _two_proc_flight_sidecars():
    """Each worker records real eager collectives into its own flight
    sidecar (the crash-durable per-rank record) and flushes on shutdown."""
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.observability import flight, straggler

    hvd.init()
    rank = hvd.process_rank()
    for step in range(3):
        straggler.set_step(step)
        flight.step_boundary(step)
        for _ in range(2):
            hvd.allreduce(np.full((2,), float(rank + 1), np.float32))
    path = flight.flush()
    hvd.shutdown()
    return {"rank": rank, "sidecar": path}


def test_two_process_flight_sidecar_merge(tmp_path):
    """Satellite (ISSUE 14): a real 2-process run leaves one sidecar per
    rank; the offline merge assigns each stream to its rank, skew-corrects
    onto one timebase, finds both ranks at the same frontier, and returns
    the no-hang verdict."""
    from horovod_tpu.observability import flight

    d = str(tmp_path / "flight")
    env = _worker_env()
    env["HOROVOD_FLIGHT_DIR"] = d
    out = runner.run(
        _two_proc_flight_sidecars, np=2, env=env, timeout_s=240
    )
    assert sorted(r["rank"] for r in out) == [0, 1]
    assert {os.path.basename(r["sidecar"]) for r in out} == {
        "flight-rank0.jsonl", "flight-rank1.jsonl",
    }
    rank_events, meta = flight.load_dir(d)
    assert sorted(rank_events) == [0, 1]
    assert meta["world"] == 2
    # both ranks recorded the SAME correlation keys (the cross-process
    # agreement everything downstream leans on), each with begin AND end
    def keys(r, ph):
        return [
            (e["step"], e["gen"], e["seq"]) for e in rank_events[r]
            if e["kind"] == "collective" and e["ph"] == ph
        ]

    assert keys(0, "b") == keys(1, "b")
    assert keys(0, "e") == keys(1, "e")
    assert len(keys(0, "b")) == 6  # 3 steps x 2 collectives
    # merged streams are time-sorted on the corrected timebase
    for r in (0, 1):
        ts = [e["t"] for e in rank_events[r]]
        assert ts == sorted(ts)
    v = flight.analyze(rank_events, expected=[0, 1])
    assert v["verdict"] == "progressing"
    assert v["key"] == [2, 0, 1]  # frontier: last collective of step 2


def _two_proc_loader_streams():
    """Each worker drives its own per-rank ResumableLoader over the same
    global stream: first half of the epoch at world 2, cursor saved, a
    FRESH loader restored (the cold-restart path), and — on rank 0 — a
    mid-epoch reshard to world 1 consuming the remainder alone (the
    repartition drill)."""
    import os

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import horovod_tpu as hvd
    from horovod_tpu.data import ResumableLoader, sampler

    hvd.init()
    rank = hvd.process_rank()
    n, bs = 64, 16  # 4 steps/epoch
    rng = np.random.RandomState(0)
    x = rng.rand(n, 3).astype(np.float32)
    y = np.arange(n, dtype=np.int32)

    def make(name):
        return ResumableLoader(
            (x, y), bs, seed=21, rank=rank, size=2, prefetch=2,
            name=name,
        )

    out = {"rank": rank}
    ld = make("mp")
    # first half of the epoch at world 2
    out["head"] = [
        np.asarray(ld.next_batch()[1]).tolist() for _ in range(2)
    ]
    cursor = ld.state()
    ld.close()
    # cold restart: fresh loader + restored cursor must continue exactly
    sampler.reset()
    ld2 = make("mp")
    ld2.restore(cursor)
    out["resumed"] = [
        np.asarray(ld2.next_batch()[1]).tolist() for _ in range(2)
    ]
    # resharding drill: rank 0 re-binds to world 1 at the SAME cursor
    # and consumes the remaining epoch alone with full batches
    if rank == 0:
        ld3 = make("mp-reshard")
        ld3.restore(cursor)
        ld3.reshard(rank=0, size=1, generation=2)
        tail = []
        for _ in range(2):
            _, yb = ld3.next_batch()
            tail.append(np.asarray(yb).tolist())
        out["reshard_tail"] = tail
        out["reshard_state"] = ld3.state()
        ld3.close()
    ld2.close()
    hvd.shutdown()
    return out


def test_two_process_loader_determinism_and_resharding():
    """Satellite (ISSUE 15): 2 real processes drive per-rank loaders —
    both ranks' sample streams are disjoint, their union is exactly the
    epoch, a killed-and-restored loader continues identically, and a
    mid-epoch 2→1 repartition covers the remainder exactly once."""
    from horovod_tpu.data import GlobalSampleIndex

    out = runner.run(
        _two_proc_loader_streams, np=2, env=_worker_env(), timeout_s=240
    )
    by_rank = {r["rank"]: r for r in out}
    assert sorted(by_rank) == [0, 1]
    n, bs = 64, 16
    gsi = GlobalSampleIndex(n, bs, seed=21)
    # per-rank streams match the pure index function
    for rank in (0, 1):
        ref = [
            gsi.rank_indices(0, s, rank, 2).tolist() for s in range(4)
        ]
        stream = by_rank[rank]["head"] + by_rank[rank]["resumed"]
        assert stream == ref, f"rank {rank} stream diverged"
    # disjoint, union == epoch
    flat0 = [v for b in by_rank[0]["head"] + by_rank[0]["resumed"]
             for v in b]
    flat1 = [v for b in by_rank[1]["head"] + by_rank[1]["resumed"]
             for v in b]
    assert not set(flat0) & set(flat1)
    assert sorted(flat0 + flat1) == list(range(n))
    # the reshard: steps 2..3 consumed alone are the FULL global batches
    tail = by_rank[0]["reshard_tail"]
    assert tail == [gsi.batch_indices(0, s).tolist() for s in (2, 3)]
    # half-epoch under world 2 + remainder under world 1 == the epoch,
    # exactly once
    first_half = [v for r in (0, 1) for b in by_rank[r]["head"]
                  for v in b]
    # (head was steps 0..1; resumed re-drew the same steps after the
    # simulated kill — use head for the exactly-once ledger)
    assert sorted(first_half + [v for b in tail for v in b]) == \
        list(range(n))
    assert by_rank[0]["reshard_state"]["generation"] == 2


def _kv_failover_drill_worker():
    """Runs inside each launched worker: publish step-keyed records to
    the EXTERNAL control plane (primary + standby endpoint list), with
    rank 0 delivering a real SIGKILL to the primary process at step 3.
    No jax needed — this is a pure control-plane drill."""
    import os
    import signal
    import time

    from horovod_tpu.resilience.retry import RetryPolicy
    from horovod_tpu.run.rendezvous import KVStoreClient, parse_endpoints

    eps = parse_endpoints(os.environ["HVD_TEST_EXT_KV"])
    primary_pid = int(os.environ["HVD_TEST_EXT_KV_PID"])
    rank = int(os.environ["HOROVOD_RANK"])
    pol = RetryPolicy(
        scope="kv", max_attempts=12, base_delay=0.1, max_delay=0.5,
        multiplier=2.0, jitter=0.1, deadline=60.0,
    )
    client = KVStoreClient(endpoints=eps, retry_policy=pol)
    for step in range(6):
        if rank == 0 and step == 3:
            os.kill(primary_pid, signal.SIGKILL)  # the real kill drill
        client.put(f"/drill/rank{rank}/step{step}", str(step).encode())
        time.sleep(0.05)
    # re-read the whole publication record through the (now promoted)
    # control plane: every step key must still be there, same values
    seen = {
        step: (client.get(f"/drill/rank{rank}/step{step}") or b"").decode()
        for step in range(6)
    }
    return {
        "rank": rank,
        "seen": seen,
        "epoch_seen": client.fencing_epoch_seen,
        "failovers": client.failovers,
    }


def test_two_process_kv_failover_drill(tmp_path):
    """Control-plane HA (ISSUE 19): the primary rendezvous KV runs as a
    REAL separate process replicating to a warm standby; mid-run a worker
    SIGKILLs it. The lease monitor promotes the standby, and both
    workers' step-keyed publications continue under the same keys with
    nothing lost — the client auto-reconnect path, end to end."""
    import signal
    import subprocess
    import sys

    from horovod_tpu.run import replication
    from horovod_tpu.run.rendezvous import KVStoreServer

    standby = KVStoreServer(
        wal_path=str(tmp_path / "standby.wal"), role="standby")
    standby.start()
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.run.replication",
         "--role", "primary", "--port", "0",
         "--wal", str(tmp_path / "primary.wal"),
         "--replicas", f"127.0.0.1:{standby.port}", "--quorum", "1"],
        stdout=subprocess.PIPE, text=True, env=_worker_env(),
        cwd=_REPO_ROOT,
    )
    monitor = None
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("KV primary ready on port "), line
        pport = int(line.rsplit(" ", 1)[1])
        monitor = replication.FailoverMonitor(
            standby, ("127.0.0.1", pport), lease=0.5, poll=0.1)
        monitor.start()

        wenv = _worker_env()
        wenv["HVD_TEST_EXT_KV"] = (
            f"127.0.0.1:{pport},127.0.0.1:{standby.port}")
        wenv["HVD_TEST_EXT_KV_PID"] = str(proc.pid)
        out = runner.run(
            _kv_failover_drill_worker, np=2, env=wenv, timeout_s=240
        )

        assert proc.wait(timeout=10) == -signal.SIGKILL
        assert standby.role == "primary"  # promoted, not just surviving
        assert standby.fencing_epoch == 1
        assert monitor.result is not None
        by_rank = {r["rank"]: r for r in out}
        assert sorted(by_rank) == [0, 1]
        for rank in (0, 1):
            # publications continued across the failover under the SAME
            # step keys, none lost or replayed
            assert by_rank[rank]["seen"] == {
                s: str(s) for s in range(6)}, by_rank[rank]
            assert by_rank[rank]["epoch_seen"] >= 1
        # the killing rank provably failed over at least once
        assert by_rank[0]["failovers"] >= 1
        # and the promoted standby's own store holds every record
        for rank in (0, 1):
            for step in range(6):
                assert standby.get(
                    f"/drill/rank{rank}/step{step}") == str(step).encode()
    finally:
        if monitor is not None:
            monitor.stop()
        if proc.poll() is None:
            proc.kill()
        proc.wait(timeout=10)
        standby.close()
