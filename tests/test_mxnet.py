"""MXNet frontend tests (reference ``test/test_mxnet.py`` pattern). Apache
MXNet is not in the image, so the frontend's duck-typed surface is driven
with fakes that mimic the small mxnet API it touches (optimizer ``update`` +
``rescale_grad``, trainer ``_params``/``list_grad``, dict parameters) —
exactly the seams the real gluon objects plug into. Replicated semantics:
every in-process rank holds the same value, so a summed allreduce
multiplies by ``size()``."""

import numpy as np
import pytest


@pytest.fixture()
def mxhvd(hvd):
    import horovod_tpu.mxnet as mxhvd

    return mxhvd


class FakeOptimizer:
    def __init__(self):
        self.rescale_grad = 1.0
        self.updates = []
        self.lr = None

    def update(self, index, weight, grad, state):
        self.updates.append(("update", index, np.array(grad), state))

    def update_multi_precision(self, index, weight, grad, state):
        self.updates.append(("ump", index, np.array(grad), state))

    def create_state_multi_precision(self, index, weight):
        return ("state", index)

    def set_learning_rate(self, lr):
        self.lr = lr


class FakeParam:
    def __init__(self, name, grad, grad_req="write"):
        self.name = name
        self.grad_req = grad_req
        self._grad = grad

    def list_grad(self):
        return [self._grad]


class TestDistributedOptimizer:
    def test_rescale_grad_divided_by_size(self, mxhvd):
        opt = mxhvd.DistributedOptimizer(FakeOptimizer())
        assert opt.rescale_grad == pytest.approx(1.0 / mxhvd.size())

    def test_update_allreduces_then_delegates(self, mxhvd):
        inner = FakeOptimizer()
        opt = mxhvd.DistributedOptimizer(inner)
        grad = np.full((3,), 2.0, np.float32)
        weight = np.zeros((3,), np.float32)
        opt.update(0, weight, grad, None)
        # summed allreduce of replicated grad = grad * size, in place
        np.testing.assert_allclose(grad, 2.0 * mxhvd.size())
        kind, index, seen_grad, _ = inner.updates[0]
        assert (kind, index) == ("update", 0)
        np.testing.assert_allclose(seen_grad, grad)

    def test_update_multi_precision_and_list_index(self, mxhvd):
        inner = FakeOptimizer()
        opt = mxhvd.DistributedOptimizer(inner)
        grads = [np.ones((2,), np.float32), np.ones((2,), np.float32) * 3]
        weights = [np.zeros((2,), np.float32)] * 2
        opt.update_multi_precision([4, 7], weights, grads, [None, None])
        np.testing.assert_allclose(grads[0], float(mxhvd.size()))
        np.testing.assert_allclose(grads[1], 3.0 * mxhvd.size())
        assert inner.updates[0][0] == "ump"

    def test_delegation_surface(self, mxhvd):
        inner = FakeOptimizer()
        opt = mxhvd.DistributedOptimizer(inner)
        opt.set_learning_rate(0.25)
        assert inner.lr == 0.25
        assert opt.create_state_multi_precision(1, None) == ("state", 1)
        # __getattr__ falls through to the wrapped optimizer
        assert opt.updates is inner.updates

    def test_size_one_skips_allreduce(self):
        import jax

        import horovod_tpu as hvd

        hvd.shutdown()
        hvd.init(devices=jax.devices()[:1])
        try:
            import horovod_tpu.mxnet as mxhvd

            grad = np.full((3,), 2.0, np.float32)
            opt = mxhvd.DistributedOptimizer(FakeOptimizer())
            opt.update(0, np.zeros(3), grad, None)
            np.testing.assert_allclose(grad, 2.0)  # untouched
        finally:
            hvd.shutdown()


class TestDistributedTrainer:
    def test_allreduce_grads_mixin(self, mxhvd):
        from horovod_tpu.mxnet import _TrainerAllreduceMixin

        class FakeTrainer(_TrainerAllreduceMixin):
            def __init__(self, params):
                self._params = params

        g1 = np.ones((2,), np.float32)
        g2 = np.full((2,), 5.0, np.float32)
        params = [
            FakeParam("w", g1),
            FakeParam("frozen", g2, grad_req="null"),
            FakeParam("b", g2),
        ]
        FakeTrainer(params)._allreduce_grads()
        np.testing.assert_allclose(g1, float(mxhvd.size()))
        # grad_req == "null" parameters are skipped... but 'b' shares g2
        np.testing.assert_allclose(g2, 5.0 * mxhvd.size())

    def test_trainer_requires_mxnet(self, mxhvd):
        with pytest.raises(ImportError, match="mxnet"):
            mxhvd.DistributedTrainer([], FakeOptimizer())


class TestBroadcastParameters:
    def test_dict_broadcast_replicated(self, mxhvd):
        params = {
            "w": np.arange(4, dtype=np.float32),
            "b": np.full((2,), 3.0, np.float32),
        }
        mxhvd.broadcast_parameters(params, root_rank=0)
        # replicated: broadcast from root leaves values unchanged, in place
        np.testing.assert_allclose(params["w"], np.arange(4))
        np.testing.assert_allclose(params["b"], 3.0)

    def test_invalid_params_type(self, mxhvd):
        with pytest.raises(ValueError, match="invalid params"):
            mxhvd.broadcast_parameters([("w", np.zeros(2))])


class TestMpiOps:
    def test_allreduce_returns_new(self, mxhvd):
        x = np.full((3,), 2.0, np.float32)
        out = mxhvd.allreduce(x, average=True, name="mxar")
        np.testing.assert_allclose(out, 2.0)  # replicated average
        np.testing.assert_allclose(x, 2.0)  # input untouched

    def test_allgather(self, mxhvd):
        x = np.ones((1, 2), np.float32)
        out = mxhvd.allgather(x, name="mxag")
        assert out.shape == (mxhvd.size(), 2)

    def test_broadcast_in_place(self, mxhvd):
        x = np.arange(3, dtype=np.float32)
        r = mxhvd.broadcast_(x, 0, name="mxbc")
        assert r is x


def test_mxnet_module_importable_without_mxnet():
    # the frontend is real code now (this file's fakes); only the gluon
    # Trainer subclass itself needs a live mxnet install
    import horovod_tpu.mxnet as hvd_mx

    assert hvd_mx.Average is not None
    assert callable(hvd_mx.DistributedOptimizer)
