"""Callback tests — analog of the reference's Keras callback coverage
(``test/test_keras.py``; callback impl ``_keras/callbacks.py``)."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu import callbacks as cbs


class _Trainer:
    def __init__(self, lr=0.1, with_momentum=False):
        self.params = {"w": jnp.ones((2, 2)), "b": jnp.zeros((2,))}
        self.lr = lr
        if with_momentum:
            tx = optax.sgd(lr, momentum=0.9)
            self.opt_state = tx.init(self.params)
        else:
            self.opt_state = None


class TestBroadcast:
    def test_broadcasts_once(self, hvd):
        t = _Trainer()
        cb = cbs.BroadcastGlobalVariablesCallback(root_rank=0)
        cb.set_trainer(t)
        cb.on_train_begin()
        assert cb.broadcast_done
        np.testing.assert_allclose(np.asarray(t.params["w"]), np.ones((2, 2)))
        # second call is a no-op
        cb.on_batch_end(1)

    def test_bad_root_raises(self, hvd):
        t = _Trainer()
        cb = cbs.BroadcastGlobalVariablesCallback(root_rank=99)
        cb.set_trainer(t)
        with pytest.raises(ValueError):
            cb.on_train_begin()


class TestMetricAverage:
    def test_scalars_averaged(self, hvd):
        cb = cbs.MetricAverageCallback()
        logs = {"loss": 2.0, "acc": np.float32(0.5), "name": "epoch3"}
        cb.on_epoch_end(0, logs)
        # replicated semantics: average of identical values is identity
        assert logs["loss"] == pytest.approx(2.0)
        assert logs["acc"] == pytest.approx(0.5)
        assert logs["name"] == "epoch3"

    def test_empty_logs_ok(self, hvd):
        cbs.MetricAverageCallback().on_epoch_end(0, None)


class TestLRSchedule:
    def test_staircase_constant_multiplier(self, hvd):
        t = _Trainer(lr=1.0)
        cb = cbs.LearningRateScheduleCallback(
            multiplier=0.1, start_epoch=2, momentum_correction=False
        )
        cb.set_trainer(t)
        cb.on_train_begin()
        cb.on_epoch_begin(0)
        assert t.lr == pytest.approx(1.0)  # before window
        cb.on_epoch_begin(2)
        assert t.lr == pytest.approx(0.1)

    def test_callable_multiplier_per_epoch(self, hvd):
        t = _Trainer(lr=1.0)
        cb = cbs.LearningRateScheduleCallback(
            multiplier=lambda e: 0.5 ** e, momentum_correction=False
        )
        cb.set_trainer(t)
        cb.on_train_begin()
        for e, want in [(0, 1.0), (1, 0.5), (3, 0.125)]:
            cb.on_epoch_begin(e)
            assert t.lr == pytest.approx(want)

    def test_smooth_requires_steps_per_epoch(self, hvd):
        t = _Trainer(lr=1.0)
        cb = cbs.LearningRateScheduleCallback(
            multiplier=lambda e: 1.0, staircase=False
        )
        cb.set_trainer(t)
        cb.on_train_begin()
        cb.on_epoch_begin(0)
        with pytest.raises(ValueError, match="steps_per_epoch"):
            cb.on_batch_begin(0)

    def test_momentum_correction_scales_trace(self, hvd):
        t = _Trainer(lr=1.0, with_momentum=True)
        # seed a nonzero momentum buffer
        import jax

        t.opt_state = jax.tree_util.tree_map(
            lambda x: jnp.ones_like(x), t.opt_state
        )
        cb = cbs.LearningRateScheduleCallback(multiplier=0.5)
        cb.set_trainer(t)
        cb.on_train_begin()
        cb.on_epoch_begin(0)
        assert t.lr == pytest.approx(0.5)
        trace = t.opt_state[0].trace
        np.testing.assert_allclose(np.asarray(trace["w"]), 0.5 * np.ones((2, 2)))


class TestWarmup:
    def test_ramp_from_one_over_size_to_one(self, hvd):
        size = hvd.size()
        t = _Trainer(lr=float(size))  # target lr = size -> start at 1.0
        cb = cbs.LearningRateWarmupCallback(
            warmup_epochs=2, steps_per_epoch=10, momentum_correction=False
        )
        cb.set_trainer(t)
        cb.on_train_begin()
        cb.on_epoch_begin(0)
        cb.on_batch_begin(0)
        assert t.lr == pytest.approx(1.0)  # initial_lr/size
        cb.on_epoch_begin(2)
        cb.on_batch_begin(0)
        assert t.lr == pytest.approx(float(size))  # ramp complete

    def test_midpoint(self, hvd):
        size = hvd.size()
        t = _Trainer(lr=1.0)
        cb = cbs.LearningRateWarmupCallback(
            warmup_epochs=2, steps_per_epoch=2, momentum_correction=False
        )
        cb.set_trainer(t)
        cb.on_train_begin()
        cb.on_epoch_begin(1)
        cb.on_batch_begin(0)  # epoch 1.0 of 2 => halfway
        want = (1.0 * (size - 1) / 2 + 1) / size
        assert t.lr == pytest.approx(want)


class TestCallbackList:
    def test_dispatch_and_wiring(self, hvd):
        t = _Trainer()
        seen = []

        class Probe(cbs.Callback):
            def on_epoch_begin(self, epoch, logs=None):
                seen.append(("epoch", epoch, self.trainer is t))

        cl = cbs.CallbackList([Probe()], trainer=t)
        cl.on_epoch_begin(3, {})
        assert seen == [("epoch", 3, True)]


class TestApplyLr:
    def test_inject_hyperparams_roundtrip(self, hvd):
        tx = optax.inject_hyperparams(optax.sgd)(learning_rate=0.1)
        params = {"w": jnp.ones(3)}
        st = tx.init(params)
        st = cbs.apply_lr(st, 0.02)
        assert float(st.hyperparams["learning_rate"]) == pytest.approx(0.02)
        # state still usable
        g = {"w": jnp.ones(3)}
        updates, st = tx.update(g, st, params)
        np.testing.assert_allclose(
            np.asarray(updates["w"]), -0.02 * np.ones(3), rtol=1e-6
        )

    def test_plain_state_raises(self, hvd):
        tx = optax.sgd(0.1)
        st = tx.init({"w": jnp.ones(2)})
        with pytest.raises(ValueError, match="inject_hyperparams"):
            cbs.apply_lr(st, 0.5)


class TestMetricsCallback:
    def test_cadence_counters_and_summary(self, hvd):
        from horovod_tpu.observability import metrics

        metrics.reset()
        try:
            lines = []
            cb = cbs.MetricsCallback(every_n_steps=2, printer=lines.append)
            t = _Trainer()
            t.global_batch_size = 8
            cb.set_trainer(t)
            for b in range(4):
                cb.on_batch_begin(b)
                cb.on_batch_end(b)
            assert metrics.value("fit_batches") == 4
            assert metrics.value("fit_examples") == 4 * 8
            assert metrics.value("fit_batch_seconds")["count"] == 4
            assert len(lines) == 2  # batches 2 and 4
            assert "fit_batches" in lines[-1]
            cb.on_train_end()
            assert len(lines) == 3
        finally:
            metrics.reset()

    def test_dump_path_writes_json_snapshot(self, hvd, tmp_path):
        import json

        from horovod_tpu.observability import metrics

        metrics.reset()
        try:
            p = str(tmp_path / "metrics.json")
            # every_n_steps=0: emit only at train end
            cb = cbs.MetricsCallback(every_n_steps=0, dump_path=p)
            cb.set_trainer(_Trainer())
            cb.on_batch_begin(0)
            cb.on_batch_end(0)
            cb.on_train_end()
            with open(p) as f:
                snap = json.load(f)
            assert snap["fit_batches"]["samples"][""] == 1.0
            assert snap["fit_batch_seconds"]["type"] == "histogram"
        finally:
            metrics.reset()
