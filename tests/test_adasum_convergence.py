"""Quantitative Adasum convergence pin (VERDICT r4 item 4).

The reference's claim is quantitative, not a vibe: Adasum's agreement-scaled
pairwise combine tolerates a ~2-2.5x LR (instead of the xN linear-scaling
rule averaging needs) and reaches a loss threshold in fewer steps — "up to
~50% fewer" on its toy case study (reference ``docs/adasum_user_guide.rst``,
case-study section; VHDD combine ``adasum.h:194-398``). This test pins the
DIRECTION of that claim with deterministic seeds on the 8-device mesh:
steps-to-threshold(Adasum, 2.5x lr) <= steps-to-threshold(Average, 1x lr).
"""

import pytest


@pytest.mark.slow
def test_adasum_reaches_threshold_in_fewer_steps(hvd):
    from examples.adasum_small_model import compare_steps_to_threshold

    avg_steps, ada_steps, curves = compare_steps_to_threshold(
        base_lr=0.5, adasum_lr_scale=2.5, threshold=0.45, steps=100
    )
    # both configurations must actually converge on the toy problem
    assert avg_steps is not None, curves["average"][-5:]
    assert ada_steps is not None, curves["adasum"][-5:]
    # the reference's direction: Adasum at the scaled LR needs no MORE
    # steps than averaging at the base LR
    assert ada_steps <= avg_steps, (avg_steps, ada_steps)
    ratio = ada_steps / avg_steps
    print(
        f"steps-to-threshold: average={avg_steps} adasum={ada_steps} "
        f"ratio={ratio:.3f}"
    )


def test_steps_to_threshold_helper():
    from examples.adasum_small_model import steps_to_threshold

    assert steps_to_threshold([1.0, 0.5, 0.1], 0.2) == 3
    assert steps_to_threshold([0.1], 0.2) == 1
    assert steps_to_threshold([1.0, 0.9], 0.2) is None
