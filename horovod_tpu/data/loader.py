"""TPU-native input pipeline: sharded, device-prefetching batch iteration.

The reference has no loader of its own — its examples lean on
``torch.utils.data.distributed.DistributedSampler`` (e.g. reference
``examples/pytorch_mnist.py:98-103``) and ``tf.data`` ``shard()`` to give
each rank a disjoint slice. On TPU the equivalent pieces are:

- :func:`shard_indices` — the DistributedSampler role: a deterministic,
  epoch-reshuffled, padded partition of example indices per process;
- :class:`ShardedLoader` — batches host data onto the mesh (global arrays
  sharded over the data axis) with ``prefetch`` batches kept in flight, so
  step N+1's host->HBM copy overlaps step N's compute (the role the
  reference's pipelined fusion-buffer memcpys + CUDA streams play;
  on TPU ``jax.device_put`` is async and the XLA runtime overlaps it).

Single-controller: the loader sees the whole dataset and emits GLOBAL
batches (the mesh shards them). Multi-process (``hvdrun``): combine
``shard_indices`` (per-process slice) with a loader over the local slice.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import basics


def shard_indices(
    n: int,
    rank: Optional[int] = None,
    size: Optional[int] = None,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epoch: int = 0,
    drop_last: bool = False,
) -> np.ndarray:
    """This process's example indices for one epoch.

    DistributedSampler semantics (reference examples
    ``pytorch_mnist.py:98-103``): every process sees a disjoint slice of a
    deterministic epoch-seeded permutation; unless ``drop_last``, the
    permutation is padded by wrap-around so all slices have equal length
    (keeping collective step counts identical across processes — a
    mismatched count is exactly the stall/join case).
    """
    rank = basics.process_rank() if rank is None else rank
    size = basics.process_size() if size is None else size
    order = np.arange(n)
    if shuffle:
        order = np.random.RandomState(seed + epoch).permutation(n)
    if drop_last:
        per = n // size
        return order[rank * per:(rank + 1) * per]
    per = -(-n // size)  # ceil
    # wrap-around padding may need more than one repetition of the order
    # (n=1, size=4 needs 4 copies) — DistributedSampler-style tiling keeps
    # every slice exactly `per` long
    reps = -(-per * size // n)
    padded = np.tile(order, reps)[: per * size]
    return padded[rank::size][:per]


class ShardedLoader:
    """Iterate host batches as mesh-sharded device arrays with prefetch.

    Args:
      arrays: one array or a tuple/list of arrays sharing dim 0 (e.g.
        ``(images, labels)``).
      batch_size: GLOBAL batch size; must divide by the data-axis size.
      axis: mesh axis to shard over (default: the data axis).
      shuffle/seed: epoch-reshuffled order (``set_epoch`` reseeds, the
        DistributedSampler idiom).
      drop_last: drop the trailing partial batch (default True — static
        shapes keep one compiled step; a ragged tail would retrace).
      prefetch: device batches kept in flight ahead of the consumer.
    """

    def __init__(
        self,
        arrays,
        batch_size: int,
        *,
        axis: Optional[str] = None,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        prefetch: int = 2,
    ):
        self._arrays = tuple(arrays) if isinstance(
            arrays, (tuple, list)
        ) else (arrays,)
        self._single = not isinstance(arrays, (tuple, list))
        n = self._arrays[0].shape[0]
        for a in self._arrays[1:]:
            if a.shape[0] != n:
                raise ValueError(
                    f"arrays disagree on dim 0: {a.shape[0]} != {n}"
                )
        self._n = n
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self._bs = batch_size
        self._axis = axis
        self._shuffle = shuffle
        self._seed = seed
        self._drop_last = drop_last
        if prefetch < 0:
            raise ValueError("prefetch must be >= 0")
        self._prefetch = prefetch
        self._epoch = 0

    def set_epoch(self, epoch: int):
        """Reseed the shuffle for a new epoch (DistributedSampler idiom)."""
        self._epoch = epoch

    def __len__(self) -> int:
        if self._drop_last:
            return self._n // self._bs
        return -(-self._n // self._bs)

    def _order(self) -> np.ndarray:
        if self._shuffle:
            return np.random.RandomState(
                self._seed + self._epoch
            ).permutation(self._n)
        return np.arange(self._n)

    def __iter__(self) -> Iterator:
        from horovod_tpu.ops.collective import _mesh_axis_size

        mesh = basics.mesh()
        ax = self._axis or basics.data_axis()
        n_ax = _mesh_axis_size(mesh, ax)  # product for tuple (host) axes
        if self._bs % n_ax != 0:
            raise ValueError(
                f"global batch size {self._bs} must divide by the "
                f"'{ax}' axis size {n_ax} (static even sharding)"
            )
        tail = self._n % self._bs
        if not self._drop_last and tail % n_ax != 0:
            # fail at iterator start, not mid-epoch on the tail device_put
            raise ValueError(
                f"with drop_last=False the trailing batch of {tail} rows "
                f"must also divide by the '{ax}' axis size "
                f"{n_ax}; drop the tail or pad the dataset"
            )
        sharding = NamedSharding(mesh, P(ax))
        order = self._order()

        def host_batches():
            for i in range(len(self)):
                sel = order[i * self._bs:(i + 1) * self._bs]
                yield tuple(np.asarray(a)[sel] for a in self._arrays)

        if self._prefetch == 0:
            for host in host_batches():
                out = tuple(jax.device_put(b, sharding) for b in host)
                yield out[0] if self._single else out
            return

        # device_put is async: keep `prefetch` batches in flight so the
        # host->HBM copy of batch i+1 overlaps the compute on batch i
        queue: collections.deque = collections.deque()
        it = host_batches()
        try:
            for _ in range(self._prefetch):
                queue.append(
                    tuple(jax.device_put(b, sharding) for b in next(it))
                )
        except StopIteration:
            pass
        for host in it:
            out = queue.popleft()
            queue.append(tuple(jax.device_put(b, sharding) for b in host))
            yield out[0] if self._single else out
        while queue:
            out = queue.popleft()
            yield out[0] if self._single else out
