"""TPU-native input pipeline: sharded, device-prefetching batch iteration.

The reference has no loader of its own — its examples lean on
``torch.utils.data.distributed.DistributedSampler`` (e.g. reference
``examples/pytorch_mnist.py:98-103``) and ``tf.data`` ``shard()`` to give
each rank a disjoint slice. On TPU the equivalent pieces are:

- :func:`shard_indices` — the DistributedSampler role: a deterministic,
  epoch-reshuffled, padded partition of example indices per process;
- :class:`ShardedLoader` — batches host data onto the mesh (global arrays
  sharded over the data axis) with ``prefetch`` batches kept in flight, so
  step N+1's host->HBM copy overlaps step N's compute (the role the
  reference's pipelined fusion-buffer memcpys + CUDA streams play;
  on TPU ``jax.device_put`` is async and the XLA runtime overlaps it).

Single-controller: the loader sees the whole dataset and emits GLOBAL
batches (the mesh shards them). Multi-process (``hvdrun``): combine
``shard_indices`` (per-process slice) with a loader over the local slice.
"""

from __future__ import annotations

import collections
import logging
import os
import queue
import threading
import time
from typing import Iterable, Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import basics
from horovod_tpu.data import sampler as _sampler
from horovod_tpu.observability import metrics as _metrics

logger = logging.getLogger("horovod_tpu.data")

#: host batches kept in flight ahead of the step loop (ResumableLoader)
PREFETCH_ENV = "HOROVOD_PREFETCH_BATCHES"
#: seconds the step loop waits for a prefetched batch before the stall is
#: *detected* (flight event + health strike) instead of silently freezing
WATCHDOG_ENV = "HOROVOD_DATA_WATCHDOG"


def shard_indices(
    n: int,
    rank: Optional[int] = None,
    size: Optional[int] = None,
    *,
    shuffle: bool = True,
    seed: int = 0,
    epoch: int = 0,
    replay_epoch: int = 0,
    drop_last: bool = False,
) -> np.ndarray:
    """This process's example indices for one epoch.

    DistributedSampler semantics (reference examples
    ``pytorch_mnist.py:98-103``): every process sees a disjoint slice of a
    deterministic epoch-seeded permutation; unless ``drop_last``, the
    permutation is padded by wrap-around so all slices have equal length
    (keeping collective step counts identical across processes — a
    mismatched count is exactly the stall/join case).

    ``(seed, epoch, replay_epoch)`` are mixed through a real hash
    (:func:`horovod_tpu.data.sampler.mix_seed`) before seeding the RNG —
    the reference's ``seed + epoch`` recipe makes ``(seed=0, epoch=1)``
    and ``(seed=1, epoch=0)`` identical streams. `replay_epoch` is the
    PR-9 rollback salt: bump it to draw a genuinely fresh permutation of
    the same epoch.
    """
    rank = basics.process_rank() if rank is None else rank
    size = basics.process_size() if size is None else size
    order = np.arange(n)
    if shuffle:
        order = np.random.RandomState(
            _sampler.mix_seed(seed, epoch, replay_epoch)
        ).permutation(n)
    if drop_last:
        per = n // size
        return order[rank * per:(rank + 1) * per]
    per = -(-n // size)  # ceil
    # wrap-around padding may need more than one repetition of the order
    # (n=1, size=4 needs 4 copies) — DistributedSampler-style tiling keeps
    # every slice exactly `per` long
    reps = -(-per * size // n)
    padded = np.tile(order, reps)[: per * size]
    return padded[rank::size][:per]


class ShardedLoader:
    """Iterate host batches as mesh-sharded device arrays with prefetch.

    Args:
      arrays: one array or a tuple/list of arrays sharing dim 0 (e.g.
        ``(images, labels)``).
      batch_size: GLOBAL batch size; must divide by the data-axis size.
      axis: mesh axis to shard over (default: the data axis).
      shuffle/seed: epoch-reshuffled order (``set_epoch`` reseeds, the
        DistributedSampler idiom).
      drop_last: drop the trailing partial batch (default True — static
        shapes keep one compiled step; a ragged tail would retrace).
      prefetch: device batches kept in flight ahead of the consumer.
    """

    def __init__(
        self,
        arrays,
        batch_size: int,
        *,
        axis: Optional[str] = None,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = True,
        prefetch: int = 2,
    ):
        self._arrays = tuple(arrays) if isinstance(
            arrays, (tuple, list)
        ) else (arrays,)
        self._single = not isinstance(arrays, (tuple, list))
        n = self._arrays[0].shape[0]
        for a in self._arrays[1:]:
            if a.shape[0] != n:
                raise ValueError(
                    f"arrays disagree on dim 0: {a.shape[0]} != {n}"
                )
        self._n = n
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        self._bs = batch_size
        self._axis = axis
        self._shuffle = shuffle
        self._seed = seed
        self._drop_last = drop_last
        if prefetch < 0:
            raise ValueError("prefetch must be >= 0")
        self._prefetch = prefetch
        self._epoch = 0
        self._live_iters = 0

    def set_epoch(self, epoch: int):
        """Reseed the shuffle for a new epoch (DistributedSampler idiom).

        Raises while an iterator is live: the running iterator
        materialized its order at ``__iter__`` (the epoch is snapshotted
        there), so a mid-iteration call would silently change *nothing*
        about the batches in flight — a footgun, not a feature."""
        if self._live_iters > 0:
            raise RuntimeError(
                "set_epoch() called while an iterator is live; the "
                "running epoch's order was materialized at __iter__ and "
                "will not change — finish (or close) the iterator first"
            )
        self._epoch = epoch

    def __len__(self) -> int:
        if self._drop_last:
            return self._n // self._bs
        return -(-self._n // self._bs)

    def _order(self, epoch: Optional[int] = None) -> np.ndarray:
        """The (snapshotted) epoch's permutation. Seed mixing includes the
        numerics ``replay_epoch`` so a PR-9 rollback's replay draws fresh
        batches through this loader too."""
        epoch = self._epoch if epoch is None else epoch
        if self._shuffle:
            from horovod_tpu.resilience import numerics as _numerics

            return np.random.RandomState(
                _sampler.mix_seed(
                    self._seed, epoch, _numerics.replay_epoch())
            ).permutation(self._n)
        return np.arange(self._n)

    def __iter__(self) -> Iterator:
        from horovod_tpu.ops.collective import _mesh_axis_size

        mesh = basics.mesh()
        ax = self._axis or basics.data_axis()
        n_ax = _mesh_axis_size(mesh, ax)  # product for tuple (host) axes
        if self._bs % n_ax != 0:
            raise ValueError(
                f"global batch size {self._bs} must divide by the "
                f"'{ax}' axis size {n_ax} (static even sharding)"
            )
        tail = self._n % self._bs
        if not self._drop_last and tail % n_ax != 0:
            # fail at iterator start, not mid-epoch on the tail device_put
            raise ValueError(
                f"with drop_last=False the trailing batch of {tail} rows "
                f"must also divide by the '{ax}' axis size "
                f"{n_ax}; drop the tail or pad the dataset"
            )
        sharding = NamedSharding(mesh, P(ax))
        # snapshot the epoch HERE — at iter(), not at the first next():
        # the iterator's order belongs to the epoch current at its
        # creation, and set_epoch refuses to run while it is live
        # (mid-iteration reseeding was a silent no-op before — the order
        # was already materialized). __iter__ is a plain method returning
        # an inner generator so the snapshot and the live-count are
        # EAGER; a generator-function __iter__ would defer both to the
        # first next(), leaving an iter()-then-set_epoch window open.
        order = self._order(self._epoch)
        self._live_iters += 1
        return _EpochIterator(self, self._iterate(order, sharding))

    def _iterate(self, order: np.ndarray, sharding) -> Iterator:
        def host_batches():
            for i in range(len(self)):
                sel = order[i * self._bs:(i + 1) * self._bs]
                yield tuple(np.asarray(a)[sel] for a in self._arrays)

        if self._prefetch == 0:
            for host in host_batches():
                out = tuple(jax.device_put(b, sharding) for b in host)
                yield out[0] if self._single else out
            return

        # device_put is async: keep `prefetch` batches in flight so
        # the host->HBM copy of batch i+1 overlaps the compute on
        # batch i
        pending: collections.deque = collections.deque()
        it = host_batches()
        try:
            for _ in range(self._prefetch):
                pending.append(
                    tuple(jax.device_put(b, sharding)
                          for b in next(it))
                )
        except StopIteration:
            pass
        for host in it:
            out = pending.popleft()
            pending.append(
                tuple(jax.device_put(b, sharding) for b in host))
            yield out[0] if self._single else out
        while pending:
            out = pending.popleft()
            yield out[0] if self._single else out


class _EpochIterator:
    """One live epoch of a :class:`ShardedLoader`. Owns the loader's
    live-iterator count — in a wrapper, not the generator's ``finally``,
    because closing a never-started generator skips its body entirely
    and would leak the count (making ``set_epoch`` raise forever)."""

    def __init__(self, loader: "ShardedLoader", gen: Iterator):
        self._loader = loader
        self._gen = gen
        self._open = True

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self._gen)
        except BaseException:
            self._finish()
            raise

    def close(self) -> None:
        self._gen.close()
        self._finish()

    def _finish(self) -> None:
        if self._open:
            self._open = False
            self._loader._live_iters -= 1

    def __del__(self):  # pragma: no cover - best effort
        self._finish()


class _ArraySource:
    """In-memory source behind :class:`ResumableLoader` — the duck type
    :class:`~horovod_tpu.data.store.ArrayShardStore` also implements."""

    def __init__(self, arrays):
        self._arrays = tuple(arrays) if isinstance(
            arrays, (tuple, list)) else (arrays,)
        n = self._arrays[0].shape[0]
        for a in self._arrays[1:]:
            if a.shape[0] != n:
                raise ValueError(
                    f"arrays disagree on dim 0: {a.shape[0]} != {n}"
                )
        self.n_rows = n

    def gather(self, indices):
        sel = np.asarray(indices)
        return tuple(np.asarray(a)[sel] for a in self._arrays)


class ResumableLoader:
    """Elastic-aware, deterministically resumable, fault-isolated input
    pipeline — the production loader the 184-line :class:`ShardedLoader`
    could not be.

    Every batch is selected by a :class:`~horovod_tpu.data.sampler
    .GlobalSampleIndex`: a pure function of ``(seed, epoch, step,
    replay_epoch)``, with a rank's share a pure function of ``(rank,
    world_size)`` on top. Consequences, all pinned by tests:

    - **resume** — the ``(epoch, step)`` cursor rides every checkpoint
      (the loader registers with :mod:`horovod_tpu.data.sampler`;
      ``resilience.run``/``elastic.run`` attach and restore it), so a
      kill/resume mid-epoch reproduces the exact remaining stream;
    - **replay** — a PR-9 :class:`~horovod_tpu.resilience.numerics
      .NumericsRollback` bumps ``numerics.replay_epoch()``; the loader
      folds it into selection, so replayed steps draw genuinely fresh
      batches while a plain elastic rollback (same replay epoch)
      re-draws identical ones;
    - **elastic resharding** — the global batch never depends on the
      world size, so an 8→6 resize repartitions the remaining epoch by
      re-slicing: no sample dropped, none double-visited. The elastic
      driver fences the loader on the same generation number as the
      mesh (:func:`sampler.generation_fence` → :meth:`on_generation`);
    - **fault isolation** — a :class:`~horovod_tpu.data.store
      .ArrayShardStore` source brings CRC-verified, retried,
      quarantine-capable reads; the bounded prefetch thread's stall is
      *detected* (``HOROVOD_DATA_WATCHDOG`` → flight-recorder ``data``
      event + ``health.record_input_stall``) instead of silently
      freezing the step loop;
    - **attribution** — per-batch ``data_wait_seconds`` /
      ``input_examples_per_second`` metrics feed
      :mod:`horovod_tpu.observability.straggler`, so a slow rank is
      named *input-bound* vs *compute-bound*
      (``HOROVOD_CHAOS=data_stall=<rank>:<s>`` drills it).

    Args:
      source: one array, a tuple of arrays sharing dim 0, or any object
        with ``n_rows`` + ``gather(indices)`` (e.g. ``ArrayShardStore``).
      batch_size: GLOBAL batch size (drop-last semantics; must divide by
        the data-axis size for device placement, and by ``size`` in
        per-rank mode).
      seed / shuffle: the stream identity.
      rank / size: per-rank mode (multi-process) — emit only this rank's
        strided slice of each global batch; default (None) emits global
        batches for the single-controller mesh to shard.
      device: place batches on the mesh (``P(axis)``); False returns
        host arrays (per-rank mode defaults to host).
      prefetch: host batches produced ahead by the background thread
        (``HOROVOD_PREFETCH_BATCHES``, default 2; 0 = synchronous).
      watchdog: stall-detection timeout seconds
        (``HOROVOD_DATA_WATCHDOG``, default 30).
      name: registry name (cursor checkpointing); unique per process.
    """

    def __init__(
        self,
        source,
        batch_size: int,
        *,
        seed: int = 0,
        shuffle: bool = True,
        axis: Optional[str] = None,
        rank: Optional[int] = None,
        size: Optional[int] = None,
        device: Optional[bool] = None,
        prefetch: Optional[int] = None,
        watchdog: Optional[float] = None,
        name: str = "input",
        register: bool = True,
    ):
        if hasattr(source, "gather") and hasattr(source, "n_rows"):
            self._source = source
        else:
            self._source = _ArraySource(source)
        if (rank is None) != (size is None):
            raise ValueError("pass rank and size together (or neither)")
        self.index = _sampler.GlobalSampleIndex(
            self._source.n_rows, batch_size, seed=seed, shuffle=shuffle)
        self._axis = axis
        self._rank = rank
        self._size = size
        self._device = (rank is None) if device is None else bool(device)
        if prefetch is None:
            prefetch = int(os.environ.get(PREFETCH_ENV, "2"))
        if prefetch < 0:
            raise ValueError("prefetch must be >= 0")
        self._prefetch = prefetch
        if watchdog is None:
            watchdog = float(os.environ.get(WATCHDOG_ENV, "30"))
        self._watchdog = max(0.05, float(watchdog))
        self.name = name
        # cursor: the NEXT (epoch, step) to draw
        self._epoch = 0
        self._step = 0
        self._generation = 0
        self._last_consume_t: Optional[float] = None
        self.last_key: Optional[tuple] = None
        self.last_indices: Optional[np.ndarray] = None
        # prefetch plumbing: entries are (token, key, payload, indices);
        # token bumps invalidate in-flight production (restore/reshard/
        # replay change), stale entries are dropped at consume
        self._lock = threading.Lock()
        self._token = 0
        self._prod_cursor: Optional[tuple] = None
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, prefetch))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._registered = bool(register)
        if register:
            _sampler.register(self, name)

    # ------------------------------------------------------------- cursor

    @property
    def steps_per_epoch(self) -> int:
        return self.index.steps_per_epoch

    def __len__(self) -> int:
        return self.index.steps_per_epoch

    def cursor(self) -> tuple:
        """The next ``(epoch, step)`` this loader will draw."""
        with self._lock:
            return (self._epoch, self._step)

    def state(self) -> dict:
        """JSON/npz-able cursor — what rides the checkpoint payload."""
        with self._lock:
            return {
                "epoch": int(self._epoch),
                "step": int(self._step),
                "seed": int(self.index.seed),
                "generation": int(self._generation),
            }

    def restore(self, state: dict) -> None:
        """Move the cursor (resume, elastic rollback). A seed mismatch is
        loud: restoring another stream's cursor silently would desync
        exactly-once accounting."""
        seed = state.get("seed")
        if seed is not None and int(seed) != self.index.seed:
            logger.warning(
                "loader %r: restoring a cursor recorded under seed %s "
                "onto a loader seeded %s — streams will differ",
                self.name, int(seed), self.index.seed,
            )
        with self._lock:
            self._epoch = int(state["epoch"])
            self._step = int(state["step"])
            gen = state.get("generation")
            if gen is not None:
                self._generation = max(self._generation, int(gen))
        self._resync()
        self._set_cursor_gauges()

    def on_generation(self, generation: int,
                      world_size: Optional[int] = None) -> None:
        """Elastic generation fence: the mesh re-formed under membership
        epoch `generation` with `world_size` ranks. Per-rank loaders are
        re-bound by :meth:`reshard`; the global-batch loader only needs
        its in-flight speculation dropped (host batches are world-size
        independent — the repartition happens at device placement) and
        the generation recorded for the ``data_generation`` gauge."""
        with self._lock:
            self._generation = int(generation)
        self._resync()
        if _metrics.enabled():
            _metrics.gauge(
                "data_generation",
                help="elastic generation the input pipeline is fenced on",
            ).set(int(generation))

    def reshard(self, *, rank: int, size: int,
                generation: Optional[int] = None) -> None:
        """Repartition a per-rank loader mid-epoch (multi-process elastic
        resize): same cursor, same global stream, new ``(rank, size)``
        slice — the union over the new rank set still covers every
        remaining global batch exactly once."""
        if self._rank is None:
            raise RuntimeError(
                "reshard() is for per-rank loaders; the global-batch "
                "loader repartitions at device placement"
            )
        if size < 1 or not 0 <= rank < size:
            # validate BEFORE mutating: a stale rank id from the old
            # world must fail here, not mid-step after the speculation
            # was already discarded
            raise ValueError(f"invalid rank {rank} of size {size}")
        if self.index.batch_size % size != 0:
            raise ValueError(
                f"batch size {self.index.batch_size} must divide by the "
                f"new world size {size}"
            )
        with self._lock:
            self._rank = int(rank)
            self._size = int(size)
            if generation is not None:
                self._generation = int(generation)
        self._resync()

    # ------------------------------------------------------------ pipeline

    def _replay(self) -> int:
        from horovod_tpu.resilience import numerics as _numerics

        return _numerics.replay_epoch()

    def _key_locked(self) -> tuple:
        return (self._epoch, self._step, self._replay())

    def _resync(self) -> None:
        """Invalidate in-flight speculation and point the producer at the
        consumer cursor (restore/reshard/replay-epoch change)."""
        with self._lock:
            self._token += 1
            token = self._token
            self._prod_cursor = self._key_locked()
        # drain STALE entries so a producer blocked in put() wakes. A
        # fresh-token entry must survive: the producer may already have
        # produced under the new token (and advanced its cursor past it)
        # between the bump and this drain — discarding it would leave
        # the consumer waiting forever for a key the producer believes
        # it delivered. Single producer ⇒ FIFO order: once the head is
        # fresh, everything behind it is too.
        while True:
            try:
                entry = self._q.get_nowait()
            except queue.Empty:
                break
            if entry[0] == token:
                self._q.put(entry)
                break

    def _maybe_stall(self) -> float:
        """Apply an armed ``data_stall`` charge; returns the injected
        seconds (0 when unarmed or another rank's charge)."""
        from horovod_tpu.resilience import chaos as _chaos

        if not _chaos.enabled():
            return 0.0
        charge = _chaos.data_stall()
        if charge is None or charge[1] <= 0:
            return 0.0
        rank, seconds = charge
        if basics.is_initialized() and basics.process_size() > 1:
            if basics.process_rank() != rank:
                return 0.0
        _chaos.record_injection("data_stall")
        time.sleep(seconds)
        return seconds

    def _produce(self, key: tuple):
        """One host batch for cursor `key` — the (possibly background)
        producer half. Chaos stalls land here, where a real slow disk
        would."""
        epoch, step, replay = key
        stalled = self._maybe_stall()
        if self._rank is not None:
            idx = self.index.rank_indices(
                epoch, step, self._rank, self._size, replay)
        else:
            idx = self.index.batch_indices(epoch, step, replay)
        return self._source.gather(idx), idx, stalled

    def _producer_loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                token = self._token
                key = self._prod_cursor
            if key is None:
                time.sleep(0.001)
                continue
            failed = False
            try:
                payload, idx, stalled = self._produce(key)
                entry = (token, key, payload, idx, stalled)
            except BaseException as e:  # surfaced at consume
                entry = (token, key, e, None, 0.0)
                failed = True
            while not self._stop.is_set():
                try:
                    self._q.put(entry, timeout=0.1)
                    break
                except queue.Full:
                    continue
            with self._lock:
                if self._token == token and not failed:
                    self._prod_cursor = (
                        *self.index.advance(key[0], key[1]), key[2])
            if failed:
                # don't spin on a persistently failing key: the consumer
                # raised (or will); re-produce on its cadence, not ours
                time.sleep(0.05)

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        with self._lock:
            if self._prod_cursor is None:
                self._prod_cursor = self._key_locked()
        self._thread = threading.Thread(
            target=self._producer_loop,
            name=f"hvd-data-{self.name}",
            daemon=True,
        )
        self._thread.start()

    def _record_stall_detected(self, waited: float, key: tuple) -> None:
        from horovod_tpu.resilience import health as _health

        logger.warning(
            "data: input pipeline stalled — no batch for (epoch=%d, "
            "step=%d) after %.1fs", key[0], key[1], waited,
        )
        _health.record_input_stall(waited)
        if _metrics.enabled():
            _metrics.counter(
                "data_prefetch_stalls",
                help="watchdog expiries while waiting on the prefetch "
                     "thread",
            ).inc()
        try:
            from horovod_tpu.observability import flight as _flight

            _flight.record(
                "data", event="input_stall", seconds=round(waited, 3),
                epoch=int(key[0]), step=int(key[1]),
            )
        except Exception as e:
            logger.debug("flight input-stall event skipped: %s", e)

    def next_batch(self):
        """The next batch on the cursor (advancing it): a tuple of arrays
        (or the single array for a one-array source), device-placed over
        the data axis unless ``device=False``. ``last_key`` /
        ``last_indices`` record what was just consumed."""
        t0 = time.monotonic()
        with self._lock:
            expected = self._key_locked()
            stale_replay = (
                self._prod_cursor is not None
                and self._prod_cursor[2] != expected[2]
            )
        if stale_replay:
            # the replay epoch moved under us (numerics rollback):
            # in-flight speculation belongs to the abandoned stream
            self._resync()
        if self._prefetch == 0:
            payload, idx, stalled = self._produce(expected)
        else:
            self._ensure_thread()
            while True:
                try:
                    entry = self._q.get(timeout=self._watchdog)
                except queue.Empty:
                    # detected, not silent: one strike per watchdog
                    # interval, then keep waiting (the producer may
                    # recover — a crash surfaces as its exception entry)
                    self._record_stall_detected(
                        time.monotonic() - t0, expected)
                    continue
                e_token, e_key, e_payload, e_idx, e_stalled = entry
                with self._lock:
                    token = self._token
                if e_token != token or e_key != expected:
                    continue  # stale speculation: drop
                payload, idx, stalled = e_payload, e_idx, e_stalled
                break
        if isinstance(payload, BaseException):
            raise payload
        wait = time.monotonic() - t0
        self._note_consumed(expected, idx, wait, stalled)
        with self._lock:
            self._epoch, self._step = self.index.advance(
                expected[0], expected[1])
        self._set_cursor_gauges()
        out = self._place(payload)
        return out[0] if len(out) == 1 else out

    def _place(self, payload):
        if not self._device:
            return tuple(payload)
        mesh = basics.mesh()
        ax = self._axis or basics.data_axis()
        from horovod_tpu.ops.collective import _mesh_axis_size

        n_ax = _mesh_axis_size(mesh, ax)
        # validate the rows actually being placed: in per-rank mode the
        # payload holds only this rank's batch_size // size slice, and a
        # global-batch-size check would pass while device_put fails deep
        # in JAX with an opaque uneven-sharding error
        rows = (
            self.index.batch_size // self._size
            if self._size else self.index.batch_size
        )
        if rows % n_ax != 0:
            raise ValueError(
                f"batch of {rows} rows must divide by the '{ax}' axis "
                f"size {n_ax} (static even sharding)"
            )
        sharding = NamedSharding(mesh, P(ax))
        return tuple(jax.device_put(b, sharding) for b in payload)

    def _note_consumed(self, key, idx, wait: float, stalled: float
                       ) -> None:
        self.last_key = (key[0], key[1], key[2], self._generation)
        self.last_indices = idx
        now = time.monotonic()
        from horovod_tpu.observability import straggler as _straggler

        multi = basics.is_initialized() and basics.process_size() > 1
        if multi:
            # this process's own pipeline: measured wait attributes to it
            _straggler.note_data_wait(basics.process_rank(), wait)
        elif stalled > 0:
            # single-controller: the chaos charge names the simulated
            # victim (the rank_slow convention) — without a charge there
            # is no per-rank skew to attribute
            from horovod_tpu.resilience import chaos as _chaos

            charge = _chaos.data_stall()
            if charge is not None:
                _straggler.note_data_wait(
                    charge[0], max(wait, stalled))
        else:
            # a batch produced without a stall CLEARS previously noted
            # single-controller waits — the documented recovery
            # semantics; a disarmed chaos charge must not leave a
            # permanent false input-bound straggler behind
            for r, w in _straggler.data_waits().items():
                if w > 0:
                    _straggler.note_data_wait(r, 0.0)
        if not _metrics.enabled():
            self._last_consume_t = now
            return
        _metrics.histogram(
            "data_wait_seconds",
            help="time the step loop waited on the input pipeline per "
                 "batch",
        ).observe(wait)
        _metrics.gauge(
            "data_wait_seconds_recent",
            help="input-pipeline wait of the most recent batch (the "
                 "input-bound attribution signal on /fleet)",
        ).set(wait)
        _metrics.counter(
            "input_batches", help="batches consumed by the step loop",
        ).inc()
        if self._last_consume_t is not None:
            dt = now - self._last_consume_t
            per_rank = (
                self.index.batch_size // self._size
                if self._size else self.index.batch_size
            )
            if dt > 0:
                _metrics.gauge(
                    "input_examples_per_second",
                    help="examples/s delivered by the input pipeline "
                         "over the last inter-batch interval",
                ).set(per_rank / dt)
        self._last_consume_t = now

    def _set_cursor_gauges(self) -> None:
        if not _metrics.enabled():
            return
        with self._lock:
            e, s = self._epoch, self._step
        _metrics.gauge(
            "data_cursor_epoch",
            help="epoch of the next batch the loader will draw",
        ).set(e)
        _metrics.gauge(
            "data_cursor_step",
            help="step-in-epoch of the next batch the loader will draw",
        ).set(s)

    def close(self) -> None:
        """Stop the prefetch thread and unregister (tests / teardown).
        Only a loader that registered itself unregisters — and only
        while it still owns the name (a replacement registration, e.g. a
        cold restart's fresh loader, must not be torn out of the
        registry by the old instance's teardown)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        if self._registered and \
                _sampler.active_loaders().get(self.name) is self:
            _sampler.unregister(self.name)

    def __del__(self):  # pragma: no cover - best effort
        # only the flag flip: joining (or logging) from a finalizer at
        # interpreter teardown is unsafe; the producer is a daemon thread
        stop = getattr(self, "_stop", None)
        if stop is not None:
            stop.set()
