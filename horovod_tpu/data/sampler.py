"""Global sample index: deterministic, elastic-aware batch selection.

The reference punts data sharding to ``DistributedSampler`` /
``tf.data.shard()`` (PAPER.md §L6, reference ``examples/pytorch_mnist.py:
98-103``): per-epoch reshuffle, per-rank slice, and *nothing else* — no
resume cursor, no elastic awareness, no replay semantics. This module is
the TPU-native replacement those layers build on:

- :func:`mix_seed` — ``(seed, epoch, replay_epoch)`` mixed through a real
  hash before seeding the permutation RNG. The naive ``seed + epoch``
  recipe (what ``DistributedSampler`` and our own PR-0 loader did) makes
  ``(seed=0, epoch=1)`` and ``(seed=1, epoch=0)`` the SAME stream — two
  runs an ablation believes are independent draw identical batches.
- :class:`GlobalSampleIndex` — every batch's member indices are a **pure
  function** of ``(seed, epoch, step, replay_epoch)``; a rank's share of
  that batch is a pure function of ``(rank, world_size)`` *on top*. The
  global batch never depends on the world size, which is the whole
  elastic-resharding story: an 8→6 resize repartitions the remaining
  epoch by re-slicing the same global stream — no sample dropped, none
  double-visited, and the post-resize stream is pinned against a fresh
  same-seed run by construction.
- a **cursor registry** — loaders register here so their ``(epoch, step)``
  cursors ride every checkpoint (:func:`horovod_tpu.checkpoint
  .attach_data_state`), the emergency-drain path, and the elastic
  driver's committed snapshots; :func:`generation_fence` re-anchors every
  registered loader on the mesh's membership epoch, the same fence
  ``resilience.elastic`` uses for the mesh itself.

``replay_epoch`` is the PR-9 salt: a :class:`~horovod_tpu.resilience
.numerics.NumericsRollback` bumps it so the replayed steps draw genuinely
fresh batches — same cursor, different stream, intentionally.

stdlib + numpy only: the resilience layers import this at checkpoint /
resize time without dragging in the data plane's JAX half.
"""

from __future__ import annotations

import hashlib
import logging
import struct
import threading
import weakref
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

__all__ = [
    "mix_seed",
    "GlobalSampleIndex",
    "register",
    "unregister",
    "export_state",
    "restore_state",
    "generation_fence",
    "active_loaders",
    "reset",
]

logger = logging.getLogger("horovod_tpu.data")


def mix_seed(seed: int, epoch: int, replay_epoch: int = 0) -> int:
    """Mix ``(seed, epoch, replay_epoch)`` into one 32-bit RNG seed through
    a real hash (blake2b), so no two distinct triples collide the way
    ``seed + epoch`` does: ``(seed=0, epoch=1)`` and ``(seed=1, epoch=0)``
    must be *different* permutations, and every ``replay_epoch`` bump must
    reshuffle the epoch it replays."""
    h = hashlib.blake2b(
        struct.pack("<qqq", int(seed), int(epoch), int(replay_epoch)),
        digest_size=8,
        person=b"hvd-data",
    ).digest()
    return int.from_bytes(h[:4], "little")


class GlobalSampleIndex:
    """Pure-function batch selection over ``n`` examples.

    ``batch_indices(epoch, step)`` is the global batch — a contiguous
    window of the epoch's :func:`mix_seed`-seeded permutation — and
    ``rank_indices(epoch, step, rank, size)`` is one rank's strided slice
    of it. Neither touches any state, so checkpoint resume, rollback
    replay, an elastic resize, and a cold restart all reproduce (or, with
    a bumped ``replay_epoch``, intentionally diverge) the exact stream.

    ``drop_last`` semantics are fixed at True (``steps_per_epoch = n //
    batch_size``): a ragged tail batch would retrace the compiled step,
    and exactly-once accounting is over the *selected* window — the
    permutation makes the dropped tail a different sample set each epoch,
    so no example is starved across epochs.
    """

    def __init__(self, n: int, batch_size: int, *, seed: int = 0,
                 shuffle: bool = True):
        if batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if n < batch_size:
            raise ValueError(
                f"dataset of {n} rows cannot fill one batch of "
                f"{batch_size}"
            )
        self.n = int(n)
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.steps_per_epoch = self.n // self.batch_size
        # one-entry order cache: sequential iteration re-derives the same
        # epoch's permutation steps_per_epoch times otherwise
        self._cached: Optional[Tuple[Tuple[int, int], np.ndarray]] = None

    def epoch_order(self, epoch: int, replay_epoch: int = 0) -> np.ndarray:
        """The epoch's full permutation (or ``arange`` unshuffled)."""
        key = (int(epoch), int(replay_epoch))
        # single atomic read: the prefetch producer and the step loop
        # share this index, and a two-step read could hand one caller
        # the OTHER key's permutation mid-swap
        cached = self._cached
        if cached is not None and cached[0] == key:
            return cached[1]
        if self.shuffle:
            order = np.random.RandomState(
                mix_seed(self.seed, epoch, replay_epoch)
            ).permutation(self.n)
        else:
            order = np.arange(self.n)
        self._cached = (key, order)
        return order

    def batch_indices(self, epoch: int, step: int,
                      replay_epoch: int = 0) -> np.ndarray:
        """The global batch at ``(epoch, step)`` — world-size independent."""
        if not 0 <= step < self.steps_per_epoch:
            raise IndexError(
                f"step {step} out of range [0, {self.steps_per_epoch})"
            )
        order = self.epoch_order(epoch, replay_epoch)
        return order[step * self.batch_size:(step + 1) * self.batch_size]

    def rank_indices(self, epoch: int, step: int, rank: int, size: int,
                     replay_epoch: int = 0) -> np.ndarray:
        """Rank ``rank``-of-``size``'s strided slice of the global batch.
        The slices partition the batch exactly (``batch_size`` must divide
        by ``size`` — static even sharding, same rule the loader's
        device_put enforces), so the union over any rank set that covers
        ``range(size)`` is the global batch — the exactly-once invariant
        an elastic repartition leans on."""
        if size < 1 or not 0 <= rank < size:
            raise ValueError(f"invalid rank {rank} of size {size}")
        if self.batch_size % size != 0:
            raise ValueError(
                f"batch size {self.batch_size} must divide by world size "
                f"{size} (static even sharding)"
            )
        return self.batch_indices(epoch, step, replay_epoch)[rank::size]

    def stream(self, epoch: int = 0, step: int = 0, *, num_steps: int,
               replay_epoch: int = 0
               ) -> Iterator[Tuple[int, int, np.ndarray]]:
        """Yield ``(epoch, step, batch_indices)`` for ``num_steps`` cursor
        advances from ``(epoch, step)`` — the reference stream tests pin
        resumed/replayed loaders against."""
        e, s = int(epoch), int(step)
        for _ in range(int(num_steps)):
            yield e, s, self.batch_indices(e, s, replay_epoch)
            s += 1
            if s >= self.steps_per_epoch:
                s, e = 0, e + 1

    def advance(self, epoch: int, step: int) -> Tuple[int, int]:
        """The cursor after consuming ``(epoch, step)``."""
        step = int(step) + 1
        if step >= self.steps_per_epoch:
            return int(epoch) + 1, 0
        return int(epoch), step


# --------------------------------------------------------------- registry
#
# Loaders register here (by name) so the resilience layers can move every
# cursor without holding loader references: checkpoint.save attaches
# `export_state()` to its payload, resume/rollback paths call
# `restore_state()`, and the elastic driver's resize calls
# `generation_fence()` beside the mesh re-formation.

_reg_lock = threading.Lock()
_registry: "weakref.WeakValueDictionary[str, object]" = (
    weakref.WeakValueDictionary()
)
#: cursors restored before their loader existed (cold restart: the
#: checkpoint is read before user code rebuilds its loaders) — applied at
#: register() time
_pending: Dict[str, dict] = {}


def register(loader, name: Optional[str] = None) -> str:
    """Register `loader` (anything with ``state()``/``restore(state)`` and
    ``on_generation(generation, world_size)``) under `name` (default: its
    ``.name``). Re-registering a name replaces the old binding — a cold
    restart's fresh loader takes over its predecessor's cursor. Returns
    the name; a cursor restored before registration is applied here."""
    name = name or getattr(loader, "name", None)
    if not name:
        raise ValueError("loader needs a name to register")
    with _reg_lock:
        _registry[name] = loader
        cursor = _pending.pop(name, None)
    if cursor is not None:
        loader.restore(cursor)
    return name


def unregister(name: str) -> None:
    with _reg_lock:
        _registry.pop(name, None)
        _pending.pop(name, None)


def active_loaders() -> Dict[str, object]:
    with _reg_lock:
        return dict(_registry)


def export_state() -> Dict[str, dict]:
    """``{name: cursor}`` for every registered loader — what rides the
    checkpoint payload and the elastic driver's committed snapshot. Empty
    when no loader is registered (callers skip attaching it)."""
    out = {}
    for name, loader in active_loaders().items():
        try:
            out[name] = dict(loader.state())
        except Exception as e:
            logger.warning("loader %r cursor export failed: %s", name, e)
    return out


def restore_state(cursors: Optional[Dict[str, dict]]) -> None:
    """Apply exported cursors to the registered loaders. A cursor whose
    loader is not registered yet is kept pending and applied when it
    registers (the cold-restart order: restore the checkpoint first,
    build the loaders after)."""
    if not cursors:
        return
    for name, cursor in cursors.items():
        loader = active_loaders().get(name)
        if loader is None:
            with _reg_lock:
                _pending[name] = dict(cursor)
            continue
        loader.restore(cursor)


def generation_fence(generation: int, world_size: Optional[int] = None
                     ) -> None:
    """Re-anchor every registered loader on elastic generation
    `generation` (world size `world_size` when known) — called by the
    elastic driver beside the mesh re-formation, so the loader's
    partitioning identity can never straddle two membership epochs.
    Best-effort per loader: the data plane must never fail a resize."""
    for name, loader in active_loaders().items():
        try:
            loader.on_generation(int(generation), world_size)
        except Exception as e:
            logger.warning(
                "loader %r generation fence failed: %s", name, e)


def reset() -> None:
    """Forget every registration and pending cursor (tests)."""
    with _reg_lock:
        _registry.clear()
        _pending.clear()
