"""Storage backends for the estimator workflow.

Reference: ``horovod/spark/common/store.py`` (0.19.2) — a ``Store`` stages
intermediate training data (parquet), checkpoints, and run state on a
filesystem every worker can reach (``store.py:149-377``: ``LocalStore`` /
``HDFSStore``). Here the training data is pandas→parquet (pyarrow), the
natural TPU-host staging format; workers read their shard by rank.
"""

from __future__ import annotations

import os
import shutil
from typing import Optional


class Store:
    """Abstract storage endpoint (reference ``spark/common/store.py:40-147``)."""

    def get_run_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_train_data_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "train_data.parquet")

    def get_val_data_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "val_data.parquet")

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "logs")

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def make_dirs(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    # -- dataframe staging --------------------------------------------------

    def write_dataframe(self, df, path: str) -> None:
        """Stage a pandas DataFrame as parquet at `path`."""
        raise NotImplementedError

    def read_dataframe(self, path: str):
        raise NotImplementedError


class LocalStore(Store):
    """Filesystem store (reference ``spark/common/store.py:149-216``
    ``LocalStore``)."""

    def __init__(self, prefix_path: str):
        self.prefix_path = os.path.abspath(prefix_path)

    def get_run_path(self, run_id: str) -> str:
        return os.path.join(self.prefix_path, run_id)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def make_dirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def write_dataframe(self, df, path: str) -> None:
        self.make_dirs(os.path.dirname(path))
        df.to_parquet(path, index=False)

    def read_dataframe(self, path: str):
        import pandas as pd

        return pd.read_parquet(path)


class HDFSStore(Store):
    """HDFS store (reference ``spark/common/store.py:219-377``). Requires an
    HDFS client library, which is not in the TPU image; constructing raises
    with the parity note."""

    def __init__(self, prefix_path: str, host: Optional[str] = None,
                 port: Optional[int] = None, user: Optional[str] = None):
        try:
            import pyarrow.fs as pafs

            self._fs = pafs.HadoopFileSystem(
                host=host or "default", port=port or 0, user=user
            )
        except Exception as e:  # pragma: no cover - no hadoop in image
            raise ImportError(
                "HDFSStore needs a reachable libhdfs (reference "
                "spark/common/store.py:219-377); use LocalStore on a shared "
                "mount instead"
            ) from e
        self.prefix_path = prefix_path

    def get_run_path(self, run_id: str) -> str:  # pragma: no cover
        return os.path.join(self.prefix_path, run_id)

    def exists(self, path: str) -> bool:  # pragma: no cover
        import pyarrow.fs as pafs

        return self._fs.get_file_info(path).type != pafs.FileType.NotFound

    def make_dirs(self, path: str) -> None:  # pragma: no cover
        self._fs.create_dir(path, recursive=True)

    def delete(self, path: str) -> None:  # pragma: no cover
        self._fs.delete_dir_contents(path)
