"""Storage backends for the data plane.

Two layers:

- the estimator stores (reference ``horovod/spark/common/store.py``
  0.19.2 — a ``Store`` stages intermediate training data, checkpoints,
  and run state on a filesystem every worker can reach: ``LocalStore`` /
  ``HDFSStore``);
- :class:`ArrayShardStore` — the fault-isolated training-data store the
  input plane (:class:`horovod_tpu.data.ResumableLoader`) reads from:
  row-range shards of numpy arrays with a CRC-carrying manifest, each
  read verified, transient failures retried through the shared
  :class:`~horovod_tpu.resilience.retry.RetryPolicy` (scope ``DATA`` →
  ``HOROVOD_RETRY_DATA_*`` env), and a shard whose corruption survives
  the retry budget **quarantined** — its samples deterministically
  substituted from healthy shards, the skip surfaced in metrics
  (``data_samples_substituted``) and health (SUSPECT naming the shard),
  never silently ignored and never a crash. The
  ``HOROVOD_CHAOS=shard_corrupt=<shard>:<k>`` charge drives the whole
  path deterministically in tier-1.
"""

from __future__ import annotations

import io
import json
import logging
import os
import shutil
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from horovod_tpu.observability import metrics as _metrics

logger = logging.getLogger("horovod_tpu.data")


class Store:
    """Abstract storage endpoint (reference ``spark/common/store.py:40-147``)."""

    def get_run_path(self, run_id: str) -> str:
        raise NotImplementedError

    def get_train_data_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "train_data.parquet")

    def get_val_data_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "val_data.parquet")

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "logs")

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def make_dirs(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    # -- dataframe staging --------------------------------------------------

    def write_dataframe(self, df, path: str) -> None:
        """Stage a pandas DataFrame as parquet at `path`."""
        raise NotImplementedError

    def read_dataframe(self, path: str):
        raise NotImplementedError


class LocalStore(Store):
    """Filesystem store (reference ``spark/common/store.py:149-216``
    ``LocalStore``)."""

    def __init__(self, prefix_path: str):
        self.prefix_path = os.path.abspath(prefix_path)

    def get_run_path(self, run_id: str) -> str:
        return os.path.join(self.prefix_path, run_id)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def make_dirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def write_dataframe(self, df, path: str) -> None:
        self.make_dirs(os.path.dirname(path))
        df.to_parquet(path, index=False)

    def read_dataframe(self, path: str):
        import pandas as pd

        return pd.read_parquet(path)


class HDFSStore(Store):
    """HDFS store (reference ``spark/common/store.py:219-377``). Requires an
    HDFS client library, which is not in the TPU image; constructing raises
    with the parity note."""

    def __init__(self, prefix_path: str, host: Optional[str] = None,
                 port: Optional[int] = None, user: Optional[str] = None):
        try:
            import pyarrow.fs as pafs

            self._fs = pafs.HadoopFileSystem(
                host=host or "default", port=port or 0, user=user
            )
        except Exception as e:  # pragma: no cover - no hadoop in image
            raise ImportError(
                "HDFSStore needs a reachable libhdfs (reference "
                "spark/common/store.py:219-377); use LocalStore on a shared "
                "mount instead"
            ) from e
        self.prefix_path = prefix_path

    def get_run_path(self, run_id: str) -> str:  # pragma: no cover
        return os.path.join(self.prefix_path, run_id)

    def exists(self, path: str) -> bool:  # pragma: no cover
        import pyarrow.fs as pafs

        return self._fs.get_file_info(path).type != pafs.FileType.NotFound

    def make_dirs(self, path: str) -> None:  # pragma: no cover
        self._fs.create_dir(path, recursive=True)

    def delete(self, path: str) -> None:  # pragma: no cover
        self._fs.delete_dir_contents(path)


# ---------------------------------------------------------- sharded arrays


MANIFEST_NAME = "manifest.json"

#: shard-array caches kept hot per store (the working set of a sequential
#: epoch touches shards in permutation order, so a handful suffices)
CACHE_SHARDS_ENV = "HOROVOD_DATA_CACHE_SHARDS"


class ShardCorruptError(Exception):
    """A shard's bytes failed CRC verification. Classified transient for
    the retry layer (a torn concurrent write or flaky read heals on
    retry); corruption that survives the retry budget becomes a
    quarantine, not an exception."""


class DataUnavailableError(RuntimeError):
    """Every shard is quarantined — there is no healthy row left to
    substitute from; degrading further would mean training on nothing."""


class ArrayShardStore:
    """CRC-verified, retry-isolated, quarantine-capable shard reader.

    Layout (written by :meth:`write`): ``shard-00000.npz`` … holding each
    array's row range under keys ``a0..ak``, plus ``manifest.json`` with
    per-shard ``{file, start, rows, crc}`` (crc32 of the file bytes).

    Reads go through :meth:`read_shard`: bytes → chaos
    (``shard_corrupt``) → CRC check → ``np.load``. A CRC mismatch raises
    :class:`ShardCorruptError` and is retried on the shared
    ``RetryPolicy`` backoff schedule (scope ``DATA``); exhaustion
    **quarantines** the shard — ``health.record_data_corruption`` (→
    SUSPECT naming the shard), ``data_shards_quarantined`` /
    ``data_quarantined_shards`` metrics, a flight-recorder ``data``
    event — and :meth:`gather` substitutes its rows deterministically
    from healthy shards (``idx → healthy_rows[idx % n_healthy]``),
    counting every substitution in ``data_samples_substituted``.
    """

    def __init__(self, directory: str, *, retry_policy=None):
        self.directory = os.path.abspath(directory)
        with open(os.path.join(self.directory, MANIFEST_NAME)) as f:
            self.manifest = json.load(f)
        self.n = int(self.manifest["n"])
        self.n_arrays = int(self.manifest["arrays"])
        self._shards: List[dict] = list(self.manifest["shards"])
        self._starts = np.array(
            [int(s["start"]) for s in self._shards], dtype=np.int64
        )
        if retry_policy is None:
            from horovod_tpu.resilience.retry import policy_from_env

            retry_policy = policy_from_env(
                "DATA", max_attempts=3, base_delay=0.01, max_delay=0.2,
            )
        self._retry = retry_policy
        self._lock = threading.Lock()
        self._cache: "Dict[int, Tuple[np.ndarray, ...]]" = {}
        self._cache_order: List[int] = []
        self._reads: Dict[int, int] = {}
        self._quarantined: set = set()
        self._healthy_rows_cache: Optional[np.ndarray] = None

    # ---------------------------------------------------------------- write

    @staticmethod
    def write(directory: str, arrays, rows_per_shard: int) -> dict:
        """Stage `arrays` (one array or a tuple sharing dim 0) as CRC'd
        row-range shards under `directory`; returns the manifest."""
        arrs = tuple(arrays) if isinstance(arrays, (tuple, list)) \
            else (arrays,)
        n = arrs[0].shape[0]
        for a in arrs[1:]:
            if a.shape[0] != n:
                raise ValueError(
                    f"arrays disagree on dim 0: {a.shape[0]} != {n}"
                )
        if rows_per_shard < 1:
            raise ValueError("rows_per_shard must be >= 1")
        os.makedirs(directory, exist_ok=True)
        shards = []
        for i, start in enumerate(range(0, n, rows_per_shard)):
            rows = min(rows_per_shard, n - start)
            fname = f"shard-{i:05d}.npz"
            path = os.path.join(directory, fname)
            payload = {
                f"a{k}": np.asarray(a[start:start + rows])
                for k, a in enumerate(arrs)
            }
            buf = io.BytesIO()
            np.savez(buf, **payload)
            data = buf.getvalue()
            with open(path, "wb") as f:
                f.write(data)
            shards.append({
                "file": fname, "start": int(start), "rows": int(rows),
                "crc": int(zlib.crc32(data)),
            })
        manifest = {
            "version": 1, "n": int(n), "arrays": len(arrs),
            # per-array dtype + trailing shape: empty gathers (and shape
            # probes) answer from metadata instead of a shard read
            "dtypes": [np.dtype(a.dtype).str for a in arrs],
            "row_shapes": [list(a.shape[1:]) for a in arrs],
            "shards": shards,
        }
        with open(os.path.join(directory, MANIFEST_NAME), "w") as f:
            json.dump(manifest, f)
        return manifest

    # ----------------------------------------------------------------- read

    @property
    def n_rows(self) -> int:
        return self.n

    @property
    def n_shards(self) -> int:
        return len(self._shards)

    def quarantined(self) -> List[int]:
        """Quarantined shard ids, ascending."""
        with self._lock:
            return sorted(self._quarantined)

    def shard_of(self, index: int) -> int:
        """The shard holding row `index`."""
        return int(
            np.searchsorted(self._starts, int(index), side="right") - 1
        )

    def _cache_cap(self) -> int:
        return max(1, int(os.environ.get(CACHE_SHARDS_ENV, "8")))

    def _read_shard_once(self, i: int) -> Tuple[np.ndarray, ...]:
        meta = self._shards[i]
        with open(os.path.join(self.directory, meta["file"]), "rb") as f:
            data = f.read()
        data = self._maybe_corrupt(i, data)
        crc = zlib.crc32(data)
        if crc != int(meta["crc"]):
            raise ShardCorruptError(
                f"shard {i} ({meta['file']}): crc {crc:#010x} != manifest "
                f"{int(meta['crc']):#010x}"
            )
        loaded = np.load(io.BytesIO(data))
        return tuple(loaded[f"a{k}"] for k in range(self.n_arrays))

    def _maybe_corrupt(self, i: int, data: bytes) -> bytes:
        from horovod_tpu.resilience import chaos as _chaos

        if not _chaos.enabled():
            return data
        charge = _chaos.shard_corrupt()
        if charge is None or charge[0] != i:
            return data
        with self._lock:
            count = self._reads.get(i, 0)
            self._reads[i] = count + 1
        if count < charge[1]:
            return data
        _chaos.record_injection("shard_corrupt")
        # flip one payload byte: CRC must catch it
        mid = len(data) // 2
        return data[:mid] + bytes([data[mid] ^ 0xFF]) + data[mid + 1:]

    def read_shard(self, i: int) -> Optional[Tuple[np.ndarray, ...]]:
        """Shard `i`'s arrays, CRC-verified and cached; None when the
        shard is (or just became) quarantined."""
        with self._lock:
            if i in self._quarantined:
                return None
            cached = self._cache.get(i)
        if cached is not None:
            return cached
        try:
            arrays = self._call_with_retry(i)
        except ShardCorruptError as e:
            self._quarantine(i, str(e))
            return None
        with self._lock:
            if i not in self._cache:
                # a concurrent miss on the same shard may have raced us
                # here: one insertion only, or _cache_order accumulates
                # a ghost duplicate that shrinks the effective capacity
                self._cache[i] = arrays
                self._cache_order.append(i)
                while len(self._cache_order) > self._cache_cap():
                    old = self._cache_order.pop(0)
                    self._cache.pop(old, None)
        return arrays

    def _call_with_retry(self, i: int) -> Tuple[np.ndarray, ...]:
        """Retry on the shared policy's backoff schedule, but own the
        exhaustion outcome: a shard that stays corrupt is a *quarantine*
        (SUSPECT, degrade-don't-crash), not the retry layer's generic
        DEGRADED — so this walks ``policy.delays()`` directly instead of
        ``policy.call()`` (whose exhaustion hook marks DEGRADED)."""
        import time as _time

        from horovod_tpu.resilience import health as _health

        last: Optional[BaseException] = None
        for delay in list(self._retry.delays()) + [None]:
            try:
                return self._read_shard_once(i)
            except (ShardCorruptError, OSError) as e:
                last = e
                if _metrics.enabled():
                    _metrics.counter(
                        "data_shard_retries",
                        help="shard reads retried after CRC/IO failure",
                        shard=i,
                    ).inc()
                _health.record_retry(self._retry.scope)
                if delay is None:
                    break
                _time.sleep(delay)
        if isinstance(last, ShardCorruptError):
            raise last
        raise ShardCorruptError(f"shard {i}: {last!r}")

    def _quarantine(self, i: int, reason: str) -> None:
        from horovod_tpu.resilience import health as _health

        with self._lock:
            if i in self._quarantined:
                return
            self._quarantined.add(i)
            self._cache.pop(i, None)
            self._healthy_rows_cache = None
            n_q = len(self._quarantined)
        logger.error(
            "data: quarantining corrupt shard %d (%s); its samples will "
            "be substituted from healthy shards", i, reason,
        )
        _health.record_data_corruption(self._shards[i]["file"], reason)
        if _metrics.enabled():
            _metrics.counter(
                "data_shards_quarantined",
                help="data shards quarantined after unrecoverable "
                     "corruption",
                shard=i,
            ).inc()
            _metrics.gauge(
                "data_quarantined_shards",
                help="data shards currently quarantined",
            ).set(n_q)
        try:
            from horovod_tpu.observability import flight as _flight

            _flight.record(
                "data", event="shard_quarantined", shard=int(i),
                file=self._shards[i]["file"],
            )
        except Exception as e:
            logger.debug("flight shard-quarantine event skipped: %s", e)

    # --------------------------------------------------------------- gather

    def _healthy_rows(self) -> np.ndarray:
        """Row indices living in non-quarantined shards, ascending (the
        substitution pool)."""
        with self._lock:
            if self._healthy_rows_cache is not None:
                return self._healthy_rows_cache
            quarantined = set(self._quarantined)
        spans = [
            np.arange(s["start"], s["start"] + s["rows"])
            for i, s in enumerate(self._shards) if i not in quarantined
        ]
        pool = (
            np.concatenate(spans) if spans
            else np.empty((0,), dtype=np.int64)
        )
        with self._lock:
            if self._quarantined != quarantined:
                # a concurrent _quarantine invalidated the pool we just
                # built — storing it would resurrect the bad shard's
                # rows as substitution targets; serve the stale copy
                # once (harmless: those reads already raced) but leave
                # the cache invalidated for the next call
                return pool
            self._healthy_rows_cache = pool
        return pool

    def _shards_of(self, idx: np.ndarray) -> np.ndarray:
        """Vectorized :meth:`shard_of` (the per-batch hot path)."""
        return np.searchsorted(self._starts, idx, side="right") - 1

    def gather(self, indices: Sequence[int]) -> Tuple[np.ndarray, ...]:
        """Rows `indices` across every array, in order. Indices landing in
        a quarantined shard are substituted deterministically
        (``healthy_rows[idx % n_healthy]``) and counted
        (``data_samples_substituted``) — batch shapes stay static, the
        skip is never silent, and the remap is a pure function of the
        index (given the quarantine set) so replay/resume reproduce it."""
        idx = np.asarray(indices, dtype=np.int64)
        if idx.size == 0:
            dtypes = self.manifest.get("dtypes")
            shapes = self.manifest.get("row_shapes")
            if dtypes and shapes:
                return tuple(
                    np.empty((0, *shapes[k]), dtype=np.dtype(dtypes[k]))
                    for k in range(self.n_arrays)
                )
            return tuple(np.empty((0,)) for _ in range(self.n_arrays))
        if idx.min() < 0 or idx.max() >= self.n:
            raise IndexError(
                f"indices out of range [0, {self.n}): "
                f"[{idx.min()}, {idx.max()}]"
            )
        # substitution resolves lazily — reading a shard may quarantine
        # it (and a substitution target can go bad mid-gather), so loop
        # until the resolved set reads clean; bounded by the shard count
        # since every retry permanently removes at least one shard
        resolved = idx.copy()
        sub_mask = np.zeros(idx.shape, dtype=bool)
        shard_data: Dict[int, Optional[Tuple[np.ndarray, ...]]] = {}
        for _attempt in range(self.n_shards + 1):
            shards = self._shards_of(resolved)
            shard_data = {
                int(s): self.read_shard(int(s))
                for s in np.unique(shards)
            }
            bad = sorted(s for s, d in shard_data.items() if d is None)
            if not bad:
                break
            pool = self._healthy_rows()
            if pool.size == 0:
                raise DataUnavailableError(
                    "every data shard is quarantined; no healthy rows "
                    "left to substitute from"
                )
            mask = np.isin(shards, np.asarray(bad))
            sub_mask |= mask  # counted once per position, not per retry
            resolved[mask] = pool[idx[mask] % pool.size]
        else:  # pragma: no cover - defensive: cannot shrink forever
            raise DataUnavailableError(
                "shard substitution did not converge"
            )
        n_sub = int(sub_mask.sum())
        if _metrics.enabled() and n_sub:
            _metrics.counter(
                "data_samples_substituted",
                help="samples remapped off quarantined shards",
            ).inc(n_sub)
        shards = self._shards_of(resolved)
        local = resolved - self._starts[shards]
        pos_by_shard = {
            int(s): np.nonzero(shards == s)[0]
            for s in np.unique(shards)
        }
        out = []
        for k in range(self.n_arrays):
            sample = next(iter(shard_data.values()))[k]
            outk = np.empty(
                (resolved.size,) + sample.shape[1:], dtype=sample.dtype)
            for s, pos in pos_by_shard.items():
                outk[pos] = shard_data[s][k][local[pos]]
            out.append(outk)
        return tuple(out)

