"""Data plumbing: estimator stores (reference ``horovod/spark/common/``)
plus the TPU-native input plane — the DistributedSampler/tf.data-shard
role of the reference's examples, grown into an elastic-aware,
deterministically resumable, fault-isolated pipeline (see
``docs/data.md``): :mod:`~horovod_tpu.data.sampler`'s pure-function
:class:`GlobalSampleIndex`, the cursor-checkpointed
:class:`ResumableLoader`, and the CRC-verified, quarantine-capable
:class:`ArrayShardStore`."""

from horovod_tpu.data import sampler  # noqa: F401
from horovod_tpu.data.sampler import (  # noqa: F401
    GlobalSampleIndex,
    mix_seed,
)
from horovod_tpu.data.store import (  # noqa: F401
    ArrayShardStore,
    DataUnavailableError,
    HDFSStore,
    LocalStore,
    ShardCorruptError,
    Store,
)
from horovod_tpu.data.loader import (  # noqa: F401
    ResumableLoader,
    ShardedLoader,
    shard_indices,
)
