"""Data-plumbing for the estimator workflow (reference
``horovod/spark/common/``): stores that stage training data and checkpoints
on a shared filesystem."""

from horovod_tpu.data.store import Store, LocalStore, HDFSStore  # noqa: F401
