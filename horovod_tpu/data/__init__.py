"""Data plumbing: estimator stores (reference ``horovod/spark/common/``)
plus the TPU-native input pipeline (sharded, device-prefetching loader —
the DistributedSampler/tf.data-shard role of the reference's examples)."""

from horovod_tpu.data.store import Store, LocalStore, HDFSStore  # noqa: F401
from horovod_tpu.data.loader import (  # noqa: F401
    ShardedLoader,
    shard_indices,
)
