"""Static analysis + runtime sanitization for collective schedules.

Horovod's coordinator layer (PAPER.md L4) exists because the #1 failure
mode of collective training is ranks issuing *different* collective
schedules — a silent deadlock or corruption. This package is the
TPU-native defense, in three layers:

- :mod:`horovod_tpu.analysis.lint` — AST rule engine (``HVD0xx`` rules)
  over any Python source: collectives under rank-dependent control flow,
  host syncs on tracers inside jit, unguarded thread-shared state,
  swallowed exceptions in retry/KV paths. CLI: ``tools/hvdlint.py``.
- :mod:`horovod_tpu.analysis.schedule` — jaxpr-level collective-schedule
  extraction: trace a step fn, emit the ordered collective signature
  sequence as a canonical fingerprint, and flag branch-divergent
  collective counts under ``lax.cond`` statically.
- :mod:`horovod_tpu.analysis.sanitizer` — runtime cross-rank schedule
  sanitizer (``HOROVOD_SANITIZE=1``): eager dispatch appends each op's
  signature to a per-step ring, a rolling hash is published to the
  rendezvous KV, and rank 0 cross-checks — on mismatch the first
  divergent op and the divergent rank are named (health SUSPECT +
  ``sanitizer_schedule_divergence`` metric).

Everything here loads lazily: training processes import this package on
every ``import horovod_tpu`` (ops/collective.py and training.py hook the
sanitizer), so neither the AST rule engine nor the JAX-touching schedule
extractor may cost them anything until actually used. The ``hvdlint``
CLI does not even go through this ``__init__`` — it file-loads
``lint.py`` directly so it runs JAX-free.
"""

from __future__ import annotations

__all__ = [
    "Finding",
    "RULES",
    "lint_file",
    "lint_paths",
    "lint_source",
    "load_waivers",
    "collective_schedule",
    "assert_same_schedule",
    "diff_schedules",
    "interleave_profile",
    "collectives_before_last_compute",
    "Schedule",
    "ScheduleDivergence",
    "sanitizer",
]

#: lazy attributes -> providing submodule
_LAZY = {
    "Finding": "lint",
    "RULES": "lint",
    "lint_file": "lint",
    "lint_paths": "lint",
    "lint_source": "lint",
    "load_waivers": "lint",
    "collective_schedule": "schedule",
    "assert_same_schedule": "schedule",
    "diff_schedules": "schedule",
    "interleave_profile": "schedule",
    "collectives_before_last_compute": "schedule",
    "Schedule": "schedule",
    "ScheduleDivergence": "schedule",
    "sanitizer": "sanitizer",
}


def __getattr__(name: str):
    mod_name = _LAZY.get(name)
    if mod_name is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    mod = importlib.import_module(f"{__name__}.{mod_name}")
    return mod if name == mod_name else getattr(mod, name)
