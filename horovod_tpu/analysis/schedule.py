"""Collective-schedule extraction from jaxprs.

The property Horovod's coordinator negotiates at runtime (PAPER.md L4:
"negotiate readiness across ranks") is, in the SPMD world, a *static*
property of the traced program: every rank runs the same jaxpr, so the
ordered sequence of collective primitives it contains IS the schedule all
ranks will issue. This module extracts that sequence:

- :func:`collective_schedule` traces a step fn (``jax.make_jaxpr``) and
  walks the jaxpr — recursing through ``pjit`` closed calls,
  ``custom_vjp``/``custom_jvp``, ``shard_map``, ``scan``, ``while`` and
  ``cond`` — emitting one :class:`CollectiveSig` per collective primitive
  (primitive name, axis names, shape, dtype, structural context).
- :meth:`Schedule.fingerprint` canonicalizes the sequence to a SHA-256 —
  the pinnable identity a refactor (e.g. the coming SyncPipeline) must
  preserve cell-by-cell across the sync-mode matrix.
- branch-divergent collective sequences under ``lax.cond`` are flagged
  *statically* (``Schedule.issues``): a collective count that differs
  between branches means the schedule depends on a runtime predicate —
  exactly the divergence class the runtime sanitizer exists to catch.
- :func:`assert_same_schedule` / :func:`diff_schedules` compare two
  schedules and name the first divergent op — the schedule-equivalence
  harness.

Example::

    sched = collective_schedule(step_fn, params, opt_state, x, y)
    assert sched.ops[0].primitive == "psum"
    assert_same_schedule(sched, collective_schedule(refactored_fn, ...))
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, List, Optional, Sequence, Tuple

import jax

__all__ = [
    "COLLECTIVE_PRIMITIVES",
    "COMPUTE_PRIMITIVES",
    "CollectiveSig",
    "Schedule",
    "ScheduleDivergence",
    "collective_schedule",
    "schedule_of_jaxpr",
    "assert_same_schedule",
    "diff_schedules",
    "interleave_profile",
    "collectives_before_last_compute",
]

#: jaxpr primitive names that move data across ranks
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmax", "pmin", "ppermute", "pbroadcast", "pgather",
    "all_gather", "all_to_all", "reduce_scatter", "psum_scatter",
    "allreduce",  # spelled by some lowering paths
})

#: eqn params that hold sub-jaxprs we must recurse through, beyond the
#: generic "any Jaxpr/ClosedJaxpr-valued param" sweep (kept for clarity —
#: the generic sweep already finds these)
_STRUCTURED_PRIMS = ("pjit", "shard_map", "cond", "while", "scan",
                     "custom_vjp_call", "custom_jvp_call", "remat",
                     "checkpoint", "closed_call", "core_call")


class ScheduleDivergence(AssertionError):
    """Two schedules (or two cond branches) disagree on the collective
    sequence; carries the first divergent index and both signatures."""

    def __init__(self, message: str, index: Optional[int] = None,
                 left: Optional["CollectiveSig"] = None,
                 right: Optional["CollectiveSig"] = None):
        super().__init__(message)
        self.index = index
        self.left = left
        self.right = right


@dataclasses.dataclass(frozen=True)
class CollectiveSig:
    """One collective's canonical signature inside a schedule."""

    primitive: str
    axes: Tuple[str, ...]
    shape: Tuple[int, ...]
    dtype: str
    context: Tuple[str, ...] = ()

    def key(self) -> tuple:
        """Equality key for schedule comparison — context included: a
        collective that moved into/out of a scan body is a different
        schedule even if its signature matches."""
        return (self.primitive, self.axes, self.shape, self.dtype,
                self.context)

    def describe(self) -> str:
        ctx = "/".join(self.context) or "top"
        ax = ",".join(self.axes) or "?"
        shp = "x".join(str(d) for d in self.shape) or "scalar"
        return f"{self.primitive}[{ax}] {self.dtype}:{shp} @{ctx}"

    def to_json(self) -> list:
        return [self.primitive, list(self.axes), list(self.shape),
                self.dtype, list(self.context)]


@dataclasses.dataclass
class Schedule:
    """Ordered collective signature sequence of one traced program."""

    ops: List[CollectiveSig]
    issues: List[str] = dataclasses.field(default_factory=list)

    def fingerprint(self) -> str:
        """Canonical SHA-256 over the ordered signature sequence (issues
        excluded: two programs with the same schedule and different
        warnings are schedule-equivalent)."""
        blob = json.dumps(
            [op.to_json() for op in self.ops], separators=(",", ":")
        ).encode()
        return hashlib.sha256(blob).hexdigest()

    def signature(self) -> Tuple[tuple, ...]:
        return tuple(op.key() for op in self.ops)

    def __len__(self) -> int:
        return len(self.ops)

    def counts(self) -> dict:
        out: dict = {}
        for op in self.ops:
            out[op.primitive] = out.get(op.primitive, 0) + 1
        return out

    def describe(self) -> str:
        lines = [op.describe() for op in self.ops]
        lines.extend(f"ISSUE: {i}" for i in self.issues)
        return "\n".join(lines)

    def to_json(self) -> dict:
        return {
            "fingerprint": self.fingerprint(),
            "ops": [op.to_json() for op in self.ops],
            "issues": list(self.issues),
        }


def _axes_of(eqn) -> Tuple[str, ...]:
    """Axis names a collective eqn runs over, normalized to a str tuple."""
    for param in ("axes", "axis_name"):
        ax = eqn.params.get(param)
        if ax is None:
            continue
        if not isinstance(ax, (tuple, list)):
            ax = (ax,)
        return tuple(str(a) for a in ax)
    return ()


def _sig_of(eqn, context: Tuple[str, ...]) -> CollectiveSig:
    aval = eqn.invars[0].aval if eqn.invars else None
    shape = tuple(getattr(aval, "shape", ()) or ())
    dtype = str(getattr(aval, "dtype", "?"))
    return CollectiveSig(
        primitive=eqn.primitive.name,
        axes=_axes_of(eqn),
        shape=shape,
        dtype=dtype,
        context=context,
    )


def _sub_jaxprs(params: dict):
    """Every Jaxpr/ClosedJaxpr hiding in an eqn's params (handles pjit's
    ``jaxpr``, custom_vjp's ``fun_jaxpr``, remat, closed calls, ...)."""
    for k, v in params.items():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for item in vs:
            if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                yield k, item


def _walk(jaxpr, ops: List[CollectiveSig], issues: List[str],
          context: Tuple[str, ...]) -> None:
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMITIVES:
            ops.append(_sig_of(eqn, context))
            continue
        if name == "cond":
            _walk_cond(eqn, ops, issues, context)
            continue
        if name == "while":
            _walk_while(eqn, ops, issues, context)
            continue
        if name == "scan":
            length = eqn.params.get("length")
            sub_ctx = context + (f"scan[{length}]",)
            for _, sub in _sub_jaxprs(eqn.params):
                _walk(sub, ops, issues, sub_ctx)
            continue
        sub_ctx = (
            context + (name,) if name in _STRUCTURED_PRIMS else context
        )
        for _, sub in _sub_jaxprs(eqn.params):
            _walk(sub, ops, issues, sub_ctx)


def _walk_cond(eqn, ops: List[CollectiveSig], issues: List[str],
               context: Tuple[str, ...]) -> None:
    """Only ONE cond branch executes, so the schedule contribution is a
    single branch's sequence — legal only when every branch issues the
    SAME collective sequence. Divergent branches are the static spelling
    of the bug the runtime sanitizer hunts: the schedule then depends on
    a runtime predicate that may differ across ranks."""
    branches = eqn.params.get("branches", ())
    per_branch: List[List[CollectiveSig]] = []
    for i, br in enumerate(branches):
        sub: List[CollectiveSig] = []
        # branch index is NOT part of the context: equal-sequence branches
        # must compare (and fingerprint) identically
        _walk(br, sub, issues, context + ("cond",))
        per_branch.append(sub)
    if not per_branch:
        return
    base = [s.key() for s in per_branch[0]]
    divergent = False
    for i, branch_ops in enumerate(per_branch[1:], start=1):
        if [s.key() for s in branch_ops] != base:
            divergent = True
            issues.append(
                f"branch-divergent collective schedule under lax.cond at "
                f"{'/'.join(context) or 'top'}: branch 0 issues "
                f"{len(per_branch[0])} collective(s) "
                f"[{', '.join(s.describe() for s in per_branch[0])}], "
                f"branch {i} issues {len(branch_ops)} "
                f"[{', '.join(s.describe() for s in branch_ops)}] — ranks "
                f"disagreeing on the predicate will deadlock"
            )
    if not divergent:
        ops.extend(per_branch[0])
        return
    # a divergence must perturb the fingerprint too, not only the issues
    # list — equal-LENGTH divergent branches would otherwise fingerprint
    # identically to a clean program. Record the (deterministically)
    # largest branch re-contextualized as divergent.
    chosen = max(per_branch, key=lambda b: (len(b), [s.key() for s in b]))
    ops.extend(
        dataclasses.replace(s, context=s.context + ("!divergent",))
        for s in chosen
    )


def _walk_while(eqn, ops: List[CollectiveSig], issues: List[str],
                context: Tuple[str, ...]) -> None:
    """A while body's collectives execute a data-dependent number of
    times: the static schedule cannot count them. Record the body once
    under a ``while`` context and flag the dynamic trip count."""
    body_ops: List[CollectiveSig] = []
    for key, sub in _sub_jaxprs(eqn.params):
        if key == "cond_jaxpr":
            cond_ops: List[CollectiveSig] = []
            _walk(sub, cond_ops, issues, context + ("while_cond",))
            body_ops.extend(cond_ops)
        else:
            _walk(sub, body_ops, issues, context + ("while",))
    if body_ops:
        issues.append(
            f"collective(s) inside lax.while_loop at "
            f"{'/'.join(context) or 'top'}: trip count is data-dependent, "
            f"so the per-step collective count is not statically fixed "
            f"[{', '.join(s.describe() for s in body_ops)}]"
        )
    ops.extend(body_ops)


def schedule_of_jaxpr(jaxpr) -> Schedule:
    """Extract the schedule from an already-traced (Closed)Jaxpr."""
    ops: List[CollectiveSig] = []
    issues: List[str] = []
    _walk(jaxpr, ops, issues, ())
    return Schedule(ops=ops, issues=issues)


def collective_schedule(fn, *args, strict: bool = False,
                        **kwargs) -> Schedule:
    """Trace ``fn(*args, **kwargs)`` and return its collective schedule.

    ``fn`` may be a plain function, a ``jax.jit``-wrapped one, or a
    ``shard_map``-bound step — tracing recurses through all of them. With
    ``strict=True`` any static issue (branch-divergent ``cond``,
    collectives under a data-dependent ``while``) raises
    :class:`ScheduleDivergence` instead of riding along in ``.issues``.
    """
    inner = getattr(fn, "_fn", fn)  # unwrap InstrumentedStep transparently
    jaxpr = jax.make_jaxpr(inner)(*args, **kwargs)
    sched = schedule_of_jaxpr(jaxpr)
    if strict and sched.issues:
        raise ScheduleDivergence("; ".join(sched.issues))
    return sched


#: FLOP-carrying primitives — the "compute fragment" markers of
#: :func:`interleave_profile` (matmuls and convolutions; elementwise ops
#: are fused around them and carry no scheduling weight of their own)
COMPUTE_PRIMITIVES = frozenset({"dot_general", "conv_general_dilated"})


def interleave_profile(fn, *args, **kwargs) -> List[str]:
    """Ordered coarse profile of a traced program: ``"compute"`` per
    FLOP-carrying primitive (:data:`COMPUTE_PRIMITIVES`), the collective
    primitive's own name per collective, in jaxpr emission order and
    recursing through the same structured primitives as
    :func:`collective_schedule`.

    This is the structural pin for comm/compute overlap (ISSUE 10): a
    bucketed step whose collectives are issued inside the backward —
    e.g. via :func:`horovod_tpu.ops.overlap.sync_hook` with barrier
    threading — shows collectives BETWEEN compute fragments; a
    monolithic step shows them all trailing. ``cond`` branches both
    contribute (the profile is a superset view, not a schedule)."""
    inner = getattr(fn, "_fn", fn)  # unwrap InstrumentedStep
    jaxpr = jax.make_jaxpr(inner)(*args, **kwargs)
    seq: List[str] = []

    def walk(j) -> None:
        j = getattr(j, "jaxpr", j)
        for eqn in j.eqns:
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMITIVES:
                seq.append(name)
            elif name in COMPUTE_PRIMITIVES:
                seq.append("compute")
            else:
                for _, sub in _sub_jaxprs(eqn.params):
                    walk(sub)

    walk(jaxpr)
    return seq


def collectives_before_last_compute(profile: Sequence[str]) -> int:
    """How many collectives the profile interleaves strictly before its
    last compute fragment — 0 means every collective trails the whole
    computation (the monolithic shape); >= 2 is the overlap acceptance
    pin."""
    last = -1
    for i, kind in enumerate(profile):
        if kind == "compute":
            last = i
    return sum(1 for kind in profile[:max(last, 0)] if kind != "compute")


def diff_schedules(a: Schedule, b: Schedule) -> Optional[dict]:
    """First divergence between two schedules, or None when equivalent.

    Returns ``{"index", "left", "right", "reason"}`` where left/right are
    the differing :class:`CollectiveSig` (None past the shorter
    schedule's end)."""
    for i, (sa, sb) in enumerate(zip(a.ops, b.ops)):
        if sa.key() != sb.key():
            return {
                "index": i,
                "left": sa,
                "right": sb,
                "reason": f"op {i} differs: {sa.describe()} vs "
                          f"{sb.describe()}",
            }
    if len(a.ops) != len(b.ops):
        longer, which = (a, "left") if len(a.ops) > len(b.ops) else (b,
                                                                     "right")
        i = min(len(a.ops), len(b.ops))
        extra = longer.ops[i]
        return {
            "index": i,
            "left": extra if which == "left" else None,
            "right": extra if which == "right" else None,
            "reason": f"{which} schedule has {abs(len(a) - len(b))} extra "
                      f"collective(s) from op {i} ({extra.describe()})",
        }
    return None


def assert_same_schedule(a, b, *args, **kwargs) -> None:
    """Assert two step fns (or two extracted :class:`Schedule`\\ s) issue
    the identical collective sequence; raises :class:`ScheduleDivergence`
    naming the first divergent op otherwise.

    Call as ``assert_same_schedule(sched_a, sched_b)`` or
    ``assert_same_schedule(fn_a, fn_b, *trace_args)`` (both fns traced on
    the same arguments)."""
    if not isinstance(a, Schedule):
        a = collective_schedule(a, *args, **kwargs)
    if not isinstance(b, Schedule):
        b = collective_schedule(b, *args, **kwargs)
    d = diff_schedules(a, b)
    if d is not None:
        raise ScheduleDivergence(
            f"collective schedules diverge: {d['reason']}",
            index=d["index"], left=d["left"], right=d["right"],
        )
