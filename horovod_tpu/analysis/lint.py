"""AST rule engine: static checks for collective-schedule and threading
discipline (``HVD0xx`` rules).

The coordinator layer Horovod carries (negotiate readiness, stall-check,
response cache — PAPER.md L4) is a *runtime* defense against ranks issuing
different collective schedules. These rules are the *static* half: they run
over plain Python source (no imports of the linted code, no JAX) and flag
the patterns that produce divergent schedules, host-sync stalls, thread
races, and swallowed failures before a job ever reaches a TPU.

Rule catalog (see ``docs/static_analysis.md`` for rationale + examples):

- **HVD001** — collective call under rank-dependent control flow
  (``if hvd.rank() == 0: allreduce(...)``) or after a rank-dependent early
  return: some ranks dispatch, others don't → deadlock.
- **HVD002** — collective inside a data-dependent Python loop (``while``
  on a non-constant predicate, or ``for`` over a host-synced bound): trip
  counts can differ across ranks, desynchronizing the schedule.
- **HVD003** — host sync on a traced value inside a jitted/traced fn
  (``.item()``, ``float()``/``int()``/``bool()``, ``np.asarray``): forces
  a device round-trip per trace or a ConcretizationTypeError.
- **HVD004** — wall-clock / host RNG inside a traced fn (``time.time()``,
  ``random.*``, ``np.random.*``): bakes a trace-time constant into the
  compiled program, different per rank/compile.
- **HVD005** — write to module-level mutable state from a function
  reachable from a ``threading.Thread``/``Timer`` target without a held
  lock (lock inference: ``with <lock>`` ancestors, ``*_locked`` helper
  convention).
- **HVD006** — bare ``except:`` or a swallowed handler (body is only
  ``pass``): hides real failures, deadliest in retry/KV paths.

Waivers — intentional cases are *declared*, not silenced:

- inline, on the finding line or the line above::

      risky_call()  # hvdlint: waive=HVD006 server teardown is best-effort

- central file (``tools/hvdlint_waivers.txt``), one per line::

      HVD005 horovod_tpu/observability/straggler.py  caches are benign races

  (``<rule> <path-glob>[:<line>] <reason>``; the reason is mandatory —
  a waiver without a why rots.)

stdlib-only by design: this module is imported by the ``tools/hvdlint.py``
CLI and by the tier-1 self-lint test; neither should pay a JAX import.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import io
import os
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Finding",
    "Waiver",
    "RULES",
    "lint_source",
    "lint_file",
    "lint_paths",
    "load_waivers",
]

#: rule id -> (summary, fix hint)
RULES: Dict[str, Tuple[str, str]] = {
    "HVD001": (
        "collective call under rank-dependent control flow",
        "every rank must issue the same collective sequence; hoist the "
        "collective out of the rank guard (broadcast the result instead of "
        "gating the call)",
    ),
    "HVD002": (
        "collective inside a data-dependent Python loop",
        "make the trip count static, or synchronize the predicate first "
        "(allreduce the stop condition so every rank loops the same "
        "number of times)",
    ),
    "HVD003": (
        "host sync on a traced value inside a jitted function",
        "keep the value on device (jnp ops) or move the read outside jit; "
        ".item()/float()/np.asarray on a tracer blocks or fails the trace",
    ),
    "HVD004": (
        "wall-clock or host RNG inside a traced function",
        "pass timestamps/keys in as arguments (jax.random with an explicit "
        "key); host time/RNG is baked in at trace time, differently per "
        "rank and per compile",
    ),
    "HVD005": (
        "module-level mutable state written from a thread-reachable "
        "function without a held lock",
        "guard the write with the module lock (`with _lock:`) or move it "
        "into a `*_locked` helper called under one",
    ),
    "HVD006": (
        "bare or swallowed except",
        "catch the narrow exception and at least log it "
        "(logging.debug(...)); a silent `except: pass` in a retry/KV path "
        "turns real failures into hangs",
    ),
}

#: Horovod-level + lax-level collective call names (HVD001/HVD002 targets)
COLLECTIVE_FNS: Set[str] = {
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "grouped_allreduce", "grouped_allreduce_async",
    "allgather", "grouped_allgather", "allgather_async", "allgather_object",
    "broadcast", "broadcast_", "broadcast_async", "broadcast_async_",
    "broadcast_object", "broadcast_parameters", "broadcast_variables",
    "broadcast_optimizer_state",
    "alltoall", "alltoall_async",
    "reducescatter", "reducescatter_async", "quantized_reducescatter",
    "quantized_psum_scatter",
    "hier_allreduce", "hier_allgather",
    "hierarchical_allreduce", "hierarchical_allgather",
    "adasum_allreduce", "grouped_adasum_allreduce",
    "psum", "pmean", "pmax", "pmin", "ppermute", "pbroadcast",
    "all_gather", "all_to_all", "psum_scatter",
    "barrier", "join",
}

#: calls whose result is a rank identity (HVD001 predicate markers)
RANK_FNS: Set[str] = {
    "rank", "local_rank", "cross_rank", "process_rank", "process_index",
    "axis_index", "_flat_axis_index", "flat_axis_index",
}

#: transforms that trace their function argument (HVD003/HVD004 scope)
TRACING_FNS: Set[str] = {
    "jit", "pjit", "pmap", "vmap", "grad", "value_and_grad", "jacfwd",
    "jacrev", "make_jaxpr", "shard_map", "_smap", "smap", "checkpoint",
    "remat", "scan", "cond", "while_loop", "custom_vjp", "custom_jvp",
    "named_call", "eval_shape",
}

#: host-sync markers inside traced fns (HVD003)
HOST_SYNC_NP_FNS = {"asarray", "array", "copy"}

#: mutating method names on module-level containers (HVD005)
MUTATOR_METHODS: Set[str] = {
    "append", "appendleft", "add", "update", "pop", "popitem", "clear",
    "extend", "extendleft", "remove", "discard", "insert", "setdefault",
}

#: with-context name fragments treated as a held lock (HVD005 inference)
LOCK_NAME_FRAGMENTS = ("lock", "_cv", "cond", "mutex")


@dataclasses.dataclass
class Finding:
    """One rule violation: id, location, message, and a fix hint."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    hint: str

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: {self.rule} "
            f"{self.message} (fix: {self.hint})"
        )

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Waiver:
    """One central-file waiver: rule + path glob (+ optional line) + why."""

    rule: str
    path_glob: str
    line: Optional[int]
    reason: str

    def matches(self, finding: Finding) -> bool:
        if self.rule != finding.rule and self.rule != "*":
            return False
        norm = finding.path.replace(os.sep, "/")
        if not (
            fnmatch.fnmatch(norm, self.path_glob)
            or fnmatch.fnmatch(norm, "*/" + self.path_glob)
            or norm.endswith("/" + self.path_glob)
            or norm == self.path_glob
        ):
            return False
        return self.line is None or self.line == finding.line


def load_waivers(path: str) -> List[Waiver]:
    """Parse the central waivers file; blank lines and ``#`` comments are
    skipped. A waiver line without a reason raises — waivers document
    intent, and intent needs words."""
    waivers: List[Waiver] = []
    with open(path, encoding="utf-8") as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(None, 2)
            if len(parts) < 3:
                raise ValueError(
                    f"{path}:{lineno}: waiver needs '<rule> <path>[:line] "
                    f"<reason>', got {line!r} (the reason is mandatory)"
                )
            rule, target, reason = parts
            if rule != "*" and rule not in RULES:
                raise ValueError(
                    f"{path}:{lineno}: unknown rule {rule!r} "
                    f"(known: {', '.join(sorted(RULES))})"
                )
            line_no: Optional[int] = None
            if ":" in target:
                target, _, tail = target.rpartition(":")
                line_no = int(tail)
            waivers.append(Waiver(rule, target, line_no, reason))
    return waivers


# --------------------------------------------------------------------------
# inline waivers


def _inline_waivers(source: str) -> Dict[int, Set[str]]:
    """line -> set of waived rule ids, from ``# hvdlint: waive=HVD00x[,..]``
    comments (``disable=`` accepted as an alias). A waiver on line L covers
    findings on L-1, L and L+1: a comment above the construct, trailing on
    the finding line, or on a handler's body line all work."""
    out: Dict[int, Set[str]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            text = tok.string
            marker = "hvdlint:"
            idx = text.find(marker)
            if idx < 0:
                continue
            spec = text[idx + len(marker):].strip()
            for prefix in ("waive=", "disable="):
                if spec.startswith(prefix):
                    spec = spec[len(prefix):]
                    break
            else:
                continue
            rules = {
                r.strip() for r in spec.split()[0].split(",") if r.strip()
            }
            line = tok.start[0]
            for covered in (line - 1, line, line + 1):
                out.setdefault(covered, set()).update(rules)
    except tokenize.TokenError:
        pass
    return out


# --------------------------------------------------------------------------
# AST helpers


def _call_name(node: ast.AST) -> Optional[str]:
    """Bare name of a call target: ``allreduce(...)`` and
    ``hvd.allreduce(...)`` both -> ``"allreduce"``."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _attr_root(node: ast.AST) -> Optional[str]:
    """Leftmost name of an attribute chain (``np.random.rand`` -> ``np``)."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _contains_rank_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _call_name(sub) in RANK_FNS:
            return True
        # `rank == 0` where rank was bound from a rank call is invisible
        # statically; the literal env spellings are not:
        if isinstance(sub, ast.Attribute) and sub.attr in (
            "process_index", "process_rank",
        ):
            return True
    return False


def _collective_calls(node: ast.AST) -> List[ast.Call]:
    return [
        sub
        for sub in ast.walk(node)
        if isinstance(sub, ast.Call) and _call_name(sub) in COLLECTIVE_FNS
    ]


def _is_host_synced_bound(node: ast.AST) -> bool:
    """Does this expression derive from a host sync (``.item()``,
    ``float(...)``, ``np.asarray``)? Marks a loop bound as data-dependent."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = sub.func
            if isinstance(fn, ast.Attribute) and fn.attr == "item":
                return True
            name = _call_name(sub)
            if name in ("float", "int") and sub.args and not isinstance(
                sub.args[0], ast.Constant
            ):
                return True
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr in HOST_SYNC_NP_FNS
                and _attr_root(fn) in ("np", "numpy", "onp", "jnp")
            ):
                return True
    return False


def _terminates(stmts: Sequence[ast.stmt]) -> bool:
    """Does this block unconditionally leave the enclosing function/loop?"""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


def _is_lockish(expr: ast.AST, module_locks: Set[str]) -> bool:
    """Is a with-context expression a lock? Either a module-level
    ``threading.Lock()`` name, or any name/attr whose last segment smells
    like a lock (``self._lock``, ``_attr_lock``, ``cv``)."""
    target = expr
    if isinstance(target, ast.Call):  # lock.acquire_timeout() style
        target = target.func
    name = None
    if isinstance(target, ast.Attribute):
        name = target.attr
    elif isinstance(target, ast.Name):
        name = target.id
    if name is None:
        return False
    if name in module_locks:
        return True
    low = name.lower()
    return any(frag in low for frag in LOCK_NAME_FRAGMENTS)


# --------------------------------------------------------------------------
# module context (pass 1)


class _ModuleContext:
    """Everything the rules need to know about the module as a whole."""

    def __init__(self, tree: ast.Module):
        self.module_globals: Set[str] = set()
        self.module_locks: Set[str] = set()
        self.thread_targets: Set[str] = set()
        self.traced_fns: Set[str] = set()
        self.func_defs: Dict[str, List[ast.FunctionDef]] = {}
        self.call_graph: Dict[str, Set[str]] = {}
        self._collect(tree)

    def _collect(self, tree: ast.Module) -> None:
        for stmt in tree.body:
            for target in self._assign_names(stmt):
                self.module_globals.add(target)
                value = getattr(stmt, "value", None)
                if isinstance(value, ast.Call) and _call_name(value) in (
                    "Lock", "RLock", "Condition", "Semaphore",
                    "BoundedSemaphore",
                ):
                    self.module_locks.add(target)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.func_defs.setdefault(node.name, []).append(node)
                self.call_graph[node.name] = {
                    _call_name(sub)
                    for sub in ast.walk(node)
                    if isinstance(sub, ast.Call) and _call_name(sub)
                }
                for deco in node.decorator_list:
                    d = deco
                    if isinstance(d, ast.Call):
                        d = d.func
                    name = (
                        d.id if isinstance(d, ast.Name)
                        else d.attr if isinstance(d, ast.Attribute) else None
                    )
                    if name in TRACING_FNS:
                        self.traced_fns.add(node.name)
            if isinstance(node, ast.Call):
                callee = _call_name(node)
                if callee in ("Thread", "Timer"):
                    self._note_thread_target(node, callee)
                if callee in TRACING_FNS:
                    for arg in node.args:
                        if isinstance(arg, ast.Name):
                            self.traced_fns.add(arg.id)
                    for kw in node.keywords:
                        if kw.arg in ("fun", "f", "fn", "body_fun",
                                      "cond_fun") and isinstance(
                                          kw.value, ast.Name):
                            self.traced_fns.add(kw.value.id)

    @staticmethod
    def _assign_names(stmt: ast.stmt) -> List[str]:
        names: List[str] = []
        targets: List[ast.AST] = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            targets = [stmt.target]
        for t in targets:
            if isinstance(t, ast.Name):
                names.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                names.extend(
                    e.id for e in t.elts if isinstance(e, ast.Name)
                )
        return names

    def _note_thread_target(self, node: ast.Call, callee: str) -> None:
        target: Optional[ast.AST] = None
        for kw in node.keywords:
            if kw.arg in ("target", "function"):
                target = kw.value
        if target is None and callee == "Timer" and len(node.args) >= 2:
            target = node.args[1]
        if target is None and callee == "Thread" and node.args:
            target = node.args[0]
        if isinstance(target, ast.Name):
            self.thread_targets.add(target.id)
        elif isinstance(target, ast.Attribute):
            self.thread_targets.add(target.attr)
        elif isinstance(target, ast.Lambda):
            for sub in ast.walk(target.body):
                if isinstance(sub, ast.Call) and _call_name(sub):
                    self.thread_targets.add(_call_name(sub))

    def thread_reachable(self) -> Set[str]:
        """Function names reachable (same-module call graph) from any
        thread/timer entry point."""
        seen: Set[str] = set()
        frontier = [t for t in self.thread_targets if t in self.func_defs]
        while frontier:
            name = frontier.pop()
            if name in seen:
                continue
            seen.add(name)
            for callee in self.call_graph.get(name, ()):
                if callee in self.func_defs and callee not in seen:
                    frontier.append(callee)
        return seen


# --------------------------------------------------------------------------
# rule passes (pass 2)


class _Linter:
    def __init__(self, tree: ast.Module, path: str, source: str):
        self.tree = tree
        self.path = path
        self.ctx = _ModuleContext(tree)
        self.findings: List[Finding] = []
        self._inline = _inline_waivers(source)

    def run(self) -> List[Finding]:
        self._rule_rank_divergence()
        self._rule_data_dependent_loops()
        self._rule_traced_host_syncs()
        self._rule_thread_state()
        self._rule_swallowed_except()
        self.findings = [
            f for f in self.findings
            if f.rule not in self._inline.get(f.line, ())
        ]
        self.findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return self.findings

    def _emit(self, rule: str, node: ast.AST, detail: str = "") -> None:
        summary, hint = RULES[rule]
        message = f"{summary}{': ' + detail if detail else ''}"
        self.findings.append(
            Finding(
                rule=rule,
                path=self.path,
                line=getattr(node, "lineno", 0),
                col=getattr(node, "col_offset", 0),
                message=message,
                hint=hint,
            )
        )

    # ------------------------------------------------------------- HVD001

    def _rule_rank_divergence(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.If, ast.IfExp)):
                if not _contains_rank_call(node.test):
                    continue
                branches = (
                    [node.body, node.orelse]
                    if isinstance(node, ast.If)
                    else [[ast.Expr(node.body)], [ast.Expr(node.orelse)]]
                )
                for branch in branches:
                    for stmt in branch:
                        for call in _collective_calls(stmt):
                            self._emit(
                                "HVD001", call,
                                f"'{_call_name(call)}' guarded by a "
                                f"rank test at line {node.test.lineno}",
                            )
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._rank_divergent_flow(node)

    def _rank_divergent_flow(self, fn: ast.AST) -> None:
        """``if rank() != 0: return`` followed by a collective later in
        the same function: the early-returning ranks never dispatch it."""
        divergent_at: Optional[int] = None
        for stmt in fn.body:
            if divergent_at is not None:
                for call in _collective_calls(stmt):
                    if self._in_nested_def(stmt, call):
                        continue  # a nested def has its own flow
                    self._emit(
                        "HVD001", call,
                        f"'{_call_name(call)}' is only reached by ranks "
                        f"that passed the rank-dependent early exit at "
                        f"line {divergent_at}",
                    )
            if (
                isinstance(stmt, ast.If)
                and _contains_rank_call(stmt.test)
                and _terminates(stmt.body)
                and not stmt.orelse
            ):
                divergent_at = stmt.lineno

    @staticmethod
    def _in_nested_def(stmt: ast.stmt, call: ast.Call) -> bool:
        for sub in ast.walk(stmt):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                if any(c is call for c in ast.walk(sub)):
                    return True
        return False

    # ------------------------------------------------------------- HVD002

    def _rule_data_dependent_loops(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.While):
                test = node.test
                if isinstance(test, ast.Constant):
                    continue  # `while True:` — static
                if not (_is_host_synced_bound(test)
                        or _contains_rank_call(test)
                        or isinstance(test, ast.Compare)):
                    continue
                for call in _collective_calls(node):
                    self._emit(
                        "HVD002", call,
                        f"'{_call_name(call)}' inside `while` with a "
                        f"non-static predicate at line {node.lineno}",
                    )
            elif isinstance(node, ast.For):
                if _is_host_synced_bound(node.iter):
                    for call in _collective_calls(node):
                        self._emit(
                            "HVD002", call,
                            f"'{_call_name(call)}' inside `for` whose "
                            f"bound is host-synced at line {node.lineno}",
                        )

    # ------------------------------------------------------- HVD003 / 004

    def _rule_traced_host_syncs(self) -> None:
        for name in sorted(self.ctx.traced_fns):
            for fn in self.ctx.func_defs.get(name, ()):
                self._scan_traced(fn)

    def _scan_traced(self, fn: ast.AST) -> None:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # HVD003: host syncs
            if isinstance(f, ast.Attribute) and f.attr == "item":
                self._emit("HVD003", node, ".item() on a traced value")
            elif (
                isinstance(f, ast.Attribute)
                and f.attr in HOST_SYNC_NP_FNS
                and _attr_root(f) in ("np", "numpy", "onp")
            ):
                self._emit(
                    "HVD003", node,
                    f"np.{f.attr}() materializes the traced value on host",
                )
            elif (
                isinstance(f, ast.Name)
                and f.id in ("float", "int", "bool")
                and node.args
                and not isinstance(node.args[0], ast.Constant)
            ):
                self._emit(
                    "HVD003", node, f"{f.id}() forces a host readback"
                )
            # HVD004: wall clock / host RNG
            root = _attr_root(f) if isinstance(f, ast.Attribute) else None
            if root == "time" and isinstance(f, ast.Attribute) and f.attr in (
                "time", "monotonic", "perf_counter", "process_time",
                "time_ns", "monotonic_ns",
            ):
                self._emit("HVD004", node, f"time.{f.attr}() at trace time")
            elif root == "random" and isinstance(f, ast.Attribute):
                self._emit(
                    "HVD004", node, f"random.{f.attr}() at trace time"
                )
            elif (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Attribute)
                and f.value.attr == "random"
                and _attr_root(f) in ("np", "numpy", "onp")
            ):
                self._emit(
                    "HVD004", node, f"np.random.{f.attr}() at trace time"
                )

    # ------------------------------------------------------------- HVD005

    def _rule_thread_state(self) -> None:
        reachable = self.ctx.thread_reachable()
        for name in sorted(reachable):
            for fn in self.ctx.func_defs.get(name, ()):
                if fn.name.endswith("_locked"):
                    continue  # convention: caller holds the lock
                self._scan_thread_fn(fn)

    def _scan_thread_fn(self, fn: ast.AST) -> None:
        declared_global: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                declared_global.update(node.names)

        def visit(node: ast.AST, lock_held: bool) -> None:
            if isinstance(node, ast.With):
                held = lock_held or any(
                    _is_lockish(item.context_expr, self.ctx.module_locks)
                    for item in node.items
                )
                for child in node.body:
                    visit(child, held)
                return
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return  # nested defs: separate reachability question
            if not lock_held:
                self._check_unlocked_write(node, declared_global)
            for child in ast.iter_child_nodes(node):
                visit(child, lock_held)

        for stmt in fn.body:
            visit(stmt, False)

    def _check_unlocked_write(
        self, node: ast.AST, declared_global: Set[str]
    ) -> None:
        mg = self.ctx.module_globals
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Name) and t.id in declared_global \
                        and t.id in mg:
                    self._emit(
                        "HVD005", node,
                        f"unguarded write to module global '{t.id}'",
                    )
                elif isinstance(t, ast.Subscript) and isinstance(
                    t.value, ast.Name
                ) and t.value.id in mg:
                    self._emit(
                        "HVD005", node,
                        f"unguarded item-write to module global "
                        f"'{t.value.id}'",
                    )
        elif isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in MUTATOR_METHODS
                and isinstance(f.value, ast.Name)
                and f.value.id in mg
            ):
                self._emit(
                    "HVD005", node,
                    f"unguarded '{f.value.id}.{f.attr}()' on module "
                    f"global",
                )

    # ------------------------------------------------------------- HVD006

    def _rule_swallowed_except(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                self._emit(
                    "HVD006", node,
                    "bare `except:` catches SystemExit/KeyboardInterrupt "
                    "too",
                )
            elif (
                len(node.body) == 1
                and isinstance(node.body[0], ast.Pass)
                and self._catches_broadly(node.type)
            ):
                exc = (
                    ast.unparse(node.type)
                    if hasattr(ast, "unparse") else "Exception"
                )
                self._emit(
                    "HVD006", node,
                    f"`except {exc}: pass` swallows every failure "
                    f"silently",
                )

    @staticmethod
    def _catches_broadly(exc_type: ast.AST) -> bool:
        """Only broad swallows are findings: `except OSError: pass` is an
        explicit, narrow decision; `except Exception: pass` hides
        everything including the bugs this package exists to catch."""
        types = (
            exc_type.elts if isinstance(exc_type, ast.Tuple) else [exc_type]
        )
        for t in types:
            name = (
                t.id if isinstance(t, ast.Name)
                else t.attr if isinstance(t, ast.Attribute) else None
            )
            if name in ("Exception", "BaseException"):
                return True
        return False


# --------------------------------------------------------------------------
# public entry points


def lint_source(source: str, path: str = "<string>") -> List[Finding]:
    """Lint one source string; inline waivers applied, central waivers
    not (the caller owns those)."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [
            Finding(
                rule="HVD000",
                path=path,
                line=e.lineno or 0,
                col=e.offset or 0,
                message=f"syntax error: {e.msg}",
                hint="fix the syntax error; nothing else was checked",
            )
        ]
    return _Linter(tree, path, source).run()


def lint_file(path: str) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path)


def _iter_py_files(paths: Iterable[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [
                d for d in dirs
                if d not in ("__pycache__", ".git", ".pytest_cache")
            ]
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def lint_paths(
    paths: Iterable[str],
    waivers: Optional[Sequence[Waiver]] = None,
) -> List[Finding]:
    """Lint every ``*.py`` under `paths`; central + inline waivers
    applied. Returns the surviving findings, sorted."""
    waivers = list(waivers or ())
    findings: List[Finding] = []
    for path in _iter_py_files(paths):
        for f in lint_file(path):
            if not any(w.matches(f) for w in waivers):
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
