"""Runtime cross-rank collective-schedule sanitizer (``HOROVOD_SANITIZE=1``).

The static layers (:mod:`~horovod_tpu.analysis.lint`,
:mod:`~horovod_tpu.analysis.schedule`) prove a *traced* program's schedule
is rank-independent; the eager path has no trace to prove anything about —
each dispatch is a fresh decision the host makes at runtime, which is
exactly where Horovod's coordinator earned its keep (PAPER.md L4: rank 0
knows which ranks submitted which tensors). This module rebuilds that
defense on the observability plane:

- every eager collective dispatch appends its **signature** (op name,
  per-tensor shape/dtype, axis) to a per-step ring and folds it into a
  **rolling hash**;
- at each step boundary the finished step's ``{hash, count, ops}`` record
  is published to the rendezvous KV under ``/sanitize/<step>/<rank>``
  (TTL'd; an in-process store stands in when no KV is wired up);
- rank 0 **cross-checks** the previous step: every rank's hash must match
  rank 0's. On mismatch the first divergent op index and the divergent
  rank are named — ``sanitizer_schedule_divergence{rank=}`` increments,
  and :func:`horovod_tpu.resilience.health.record_schedule_divergence`
  strikes the health machine to SUSPECT with the rank + op in the reason.

Topology note: single-controller SPMD dispatches on behalf of every rank,
so per-rank schedules are identical by construction — there the sanitizer
is exercised by the deterministic chaos charge
``HOROVOD_CHAOS=schedule_diverge_at_step=K`` (the highest rank's published
record is perturbed at step K, mirroring ``rank_fail``'s never-rank-0
convention), which is also how tier-1 pins the detection latency: the
divergence is named within one step. Multi-process ranks each publish only
their own record and rank 0 cross-checks for real.

Env knobs:

- ``HOROVOD_SANITIZE`` — ``1`` to enable (default off: the happy path
  costs one boolean per dispatch).
- ``HOROVOD_SANITIZE_MAX_OPS`` (default 512) — per-step ring capacity;
  overflowing ops still roll the hash but drop their diagnostic
  signature.
- ``HOROVOD_SANITIZE_TTL`` (default 120 s) — KV record TTL.

stdlib-only at import; chaos/health are imported lazily at call time.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from horovod_tpu.observability import metrics as _metrics

__all__ = [
    "SANITIZE_ENV",
    "enabled",
    "configure",
    "reset",
    "record",
    "set_step",
    "flush",
    "publish",
    "cross_check",
    "last_divergence",
    "schedule_key",
]

SANITIZE_ENV = "HOROVOD_SANITIZE"
MAX_OPS_ENV = "HOROVOD_SANITIZE_MAX_OPS"
TTL_ENV = "HOROVOD_SANITIZE_TTL"

_lock = threading.Lock()
_enabled: Optional[bool] = None  # None = read env
_kv = None  # KVStoreServer/KVStoreClient duck-type, or the local store
_step = 0
_ops: List[list] = []
_dropped = 0
_hash = hashlib.sha256()
_last_divergence: Optional[dict] = None
_world_override: Optional[int] = None
#: steps rank 0 could not fully cross-check yet (a peer's publication had
#: not landed) -> remaining recheck attempts; retried at later boundaries
_pending_checks: Dict[int, int] = {}

#: boundaries a step with missing peers is retried before being dropped
#: (a peer that never publishes is the heartbeat layer's finding, not a
#: schedule verdict)
PENDING_CHECK_ATTEMPTS = 8


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get(SANITIZE_ENV, "0").lower() not in (
            "0", "false", "off", "",
        )
    return _enabled


def configure(on: Optional[bool] = None, *, kv=None,
              world: Optional[int] = None) -> None:
    """Programmatic setup: flip the switch, wire a KV store (a
    :class:`~horovod_tpu.run.rendezvous.KVStoreServer` or ``...Client``),
    or pin the world size (defaults to what dispatches report)."""
    global _enabled, _kv, _world_override
    with _lock:
        if on is not None:
            _enabled = bool(on)
        if kv is not None:
            _kv = kv
        if world is not None:
            _world_override = int(world)


def reset() -> None:
    """Back to env-driven config and an empty ring (tests)."""
    global _enabled, _kv, _step, _ops, _dropped, _hash
    global _last_divergence, _world_override
    with _lock:
        _enabled = None
        _kv = None  # a fresh in-process store is built on next use
        _step = 0
        _ops = []
        _dropped = 0
        _hash = hashlib.sha256()
        _last_divergence = None
        _world_override = None
        _pending_checks.clear()


def _max_ops() -> int:
    return max(8, int(os.environ.get(MAX_OPS_ENV, "512")))


def _ttl() -> float:
    return float(os.environ.get(TTL_ENV, "120"))


def _store():
    """Explicit :func:`configure` store, else a client from the launcher
    env (``HVD_RUN_KV_ADDR``/``HVD_RUN_KV_PORT``), else a fresh
    in-process stand-in — the shared
    :mod:`~horovod_tpu.run.rendezvous` wiring, lazily imported so this
    module stays importable from collection-time contexts."""
    global _kv
    if _kv is None:
        from horovod_tpu.run.rendezvous import (
            InProcessKVStore, kv_client_from_env,
        )

        _kv = kv_client_from_env() or InProcessKVStore()
    return _kv


def schedule_key(step: int, rank: int) -> str:
    return f"/sanitize/{int(step)}/{int(rank)}"


# --------------------------------------------------------------------------
# recording


def _axis_repr(axis) -> str:
    if axis is None:
        return "data"
    if isinstance(axis, (tuple, list)):
        return "+".join(str(a) for a in axis)
    return str(axis)


def record(op: str, tensors, axis=None) -> None:
    """Append one dispatched eager collective's signature to the current
    step's ring and roll the hash. Called from
    ``ops.collective._record_eager_op`` — the one choke point every eager
    dispatch passes through."""
    if not enabled():
        return
    sig = [
        str(op),
        _axis_repr(axis),
        [
            [list(getattr(t, "shape", ()) or ()),
             str(getattr(t, "dtype", "?"))]
            for t in tensors
        ],
    ]
    blob = json.dumps(sig, separators=(",", ":")).encode()
    global _dropped
    with _lock:
        _hash.update(blob)
        if len(_ops) < _max_ops():
            _ops.append(sig)
        else:
            _dropped += 1


def _snapshot_locked() -> dict:
    return {
        "hash": _hash.hexdigest(),
        "n": len(_ops) + _dropped,
        "dropped": _dropped,
        "ops": list(_ops),
    }


# --------------------------------------------------------------------------
# step boundary: publish + cross-check


def set_step(step: int) -> None:
    """Open step `step`'s recording scope; the step that just finished is
    published and (rank 0) cross-checked. ``InstrumentedStep`` calls this
    per dispatched train step, next to the straggler correlation scope;
    explicit loops call it themselves."""
    if not enabled():
        return
    flush()
    global _step
    with _lock:
        _step = int(step)


def flush() -> Optional[dict]:
    """Publish + cross-check the current step's record and clear the
    ring; also retry earlier steps whose cross-check was incomplete (a
    peer's publication had not landed at its own boundary — the race a
    multi-process job hits when rank 0 reaches the boundary first).
    Returns the newest divergence detected (also kept in
    :func:`last_divergence`)."""
    if not enabled():
        return None
    with _lock:
        step = _step
        record_now = _snapshot_locked()
        _reset_ring_locked()
        pending_steps = sorted(_pending_checks)
    out: Optional[dict] = None
    for pending in pending_steps:
        out = cross_check(pending) or out
    if record_now["n"] == 0:
        return out
    publish(step, record_now)
    return cross_check(step) or out


def _reset_ring_locked() -> None:
    global _ops, _dropped, _hash
    _ops = []
    _dropped = 0
    _hash = hashlib.sha256()


def _identity() -> Tuple[int, int, int]:
    """(world, process_rank, process_size) — lazily, so this module never
    imports the data plane at import time."""
    try:
        from horovod_tpu import basics

        if basics.is_initialized():
            return basics.size(), basics.process_rank(), \
                basics.process_size()
    except Exception as e:  # pre-init dispatch: treat as a 1-rank world
        import logging

        logging.getLogger("horovod_tpu").debug(
            "sanitizer identity probe failed: %s", e)
    return 1, 0, 1


def _chaos_mod():
    from horovod_tpu.resilience import chaos

    return chaos


def publish(step: int, record_dict: Optional[dict] = None) -> None:
    """Publish `step`'s schedule record to the KV.

    Single-controller (``process_size == 1``): one record is written for
    EVERY rank — they dispatched the same ops by construction — except
    when the ``schedule_diverge_at_step`` chaos charge fires, in which
    case the highest rank's copy is perturbed (first op renamed, hash
    re-rolled) so the cross-check has a real divergence to find.
    Multi-process: each process writes only its own rank's record; the
    chaos charge fires on the highest process rank."""
    if record_dict is None:
        with _lock:
            record_dict = _snapshot_locked()
    world, prank, psize = _identity()
    if _world_override is not None:
        world = _world_override
    store = _store()
    ttl = _ttl()
    chaos = _chaos_mod()
    # only the process that would actually perturb consumes the charge:
    # resilience_chaos_injected{site=} must count injections that FIRED
    # (every publishing rank taking it would over-count the fleet total,
    # and a 1-rank world would count a perturbation that cannot exist)
    can_perturb = (
        prank == psize - 1 if psize > 1 else world > 1
    )
    diverge = (
        can_perturb and chaos.enabled() and chaos.take_schedule_diverge(step)
    )
    blob = json.dumps(record_dict, separators=(",", ":")).encode()
    if _metrics.enabled():
        _metrics.counter(
            "sanitizer_ops_recorded",
            help="eager collective signatures folded into the schedule "
                 "sanitizer ring",
        ).inc(record_dict["n"])
    if psize > 1:
        if diverge:
            record_dict = _perturb(record_dict)
            blob = json.dumps(record_dict, separators=(",", ":")).encode()
        # the flight ring keeps this rank's per-step schedule hash (the
        # perturbed one when the chaos charge fired — that IS what this
        # rank "dispatched"): offline hang forensics cross-checks these
        # to tell "rank missing" from "schedules diverged"
        _flight_sched(step, record_dict)
        store.put(schedule_key(step, prank), blob, ttl=ttl)
        return
    _flight_sched(step, record_dict)
    victim = world - 1 if diverge else None
    perturbed = (
        json.dumps(_perturb(record_dict), separators=(",", ":")).encode()
        if victim is not None else None
    )
    for r in range(max(1, world)):
        store.put(
            schedule_key(step, r),
            perturbed if r == victim else blob,
            ttl=ttl,
        )


def _flight_sched(step: int, record_dict: dict) -> None:
    try:
        from horovod_tpu.observability import flight as _flight

        _flight.record(
            "sched", step=int(step), hash=record_dict["hash"][:16],
            n=record_dict["n"],
        )
    except Exception as e:
        import logging

        logging.getLogger("horovod_tpu").debug(
            "flight sched event skipped: %s", e)


def _perturb(record_dict: dict) -> dict:
    """The chaos divergence: rename the first op (or invent one in an
    empty step) and re-roll the hash, as if the victim rank had dispatched
    a different collective first."""
    ops = [list(o) for o in record_dict["ops"]]
    if ops:
        ops[0] = [str(ops[0][0]) + "!chaos", ops[0][1], ops[0][2]]
    else:
        ops = [["allreduce!chaos", "data", [[[1], "float32"]]]]
    h = hashlib.sha256()
    for sig in ops:
        h.update(json.dumps(sig, separators=(",", ":")).encode())
    return {
        "hash": h.hexdigest(),
        "n": max(1, record_dict["n"]),
        "dropped": record_dict.get("dropped", 0),
        "ops": ops,
    }


def _first_divergent_op(ours: dict, theirs: dict) -> Tuple[int, str]:
    """(index, description) of the first op the two records disagree on."""
    for i, (a, b) in enumerate(zip(ours["ops"], theirs["ops"])):
        if a != b:
            return i, f"{b[0]} (rank's op {i}; coordinator saw {a[0]})"
    na, nb = ours["n"], theirs["n"]
    i = min(len(ours["ops"]), len(theirs["ops"]))
    if nb > na:
        extra = theirs["ops"][i][0] if i < len(theirs["ops"]) else "?"
        return i, f"{extra} (rank issued {nb - na} extra op(s) from {i})"
    if na > nb:
        missing = ours["ops"][i][0] if i < len(ours["ops"]) else "?"
        return i, f"{missing} (rank missing {na - nb} op(s) from {i})"
    return i, "schedules hash-diverge past the diagnostic ring"


def cross_check(step: int) -> Optional[dict]:
    """Rank 0: compare every rank's published record for `step` against
    our own; on the first mismatch name the divergent rank and op, count
    ``sanitizer_schedule_divergence{rank=}``, and strike the health
    machine (SUSPECT names the rank + op). A step with a peer whose
    publication has not landed yet is NOT dropped: it is remembered and
    re-checked at the next :data:`PENDING_CHECK_ATTEMPTS` step
    boundaries (rank 0 reaching the boundary before a peer's KV put is
    the common race in a real multi-process job — the divergent rank is
    often the *slow* one). A peer still missing after the retry budget
    is the straggler/heartbeat layers' business, not a schedule
    verdict."""
    global _last_divergence
    world, prank, psize = _identity()
    if _world_override is not None:
        world = _world_override
    if prank != 0:
        return None
    store = _store()
    mine_blob = store.get(schedule_key(step, 0))
    if mine_blob is None:
        return None
    try:
        mine = json.loads(mine_blob)
    except ValueError:
        return None
    checked = False
    missing = False
    divergence: Optional[dict] = None
    ranks = range(1, max(1, world if psize == 1 else psize))
    for r in ranks:
        blob = store.get(schedule_key(step, r))
        if blob is None:
            missing = True  # not published yet: defer, don't drop
            continue
        try:
            theirs = json.loads(blob)
        except ValueError:
            continue
        checked = True
        if theirs.get("hash") == mine.get("hash"):
            continue
        idx, op_desc = _first_divergent_op(mine, theirs)
        divergence = {
            "step": step,
            "rank": r,
            "op_index": idx,
            "op": op_desc,
            "expected_n": mine.get("n"),
            "got_n": theirs.get("n"),
        }
        break
    with _lock:
        if missing and divergence is None:
            left = _pending_checks.get(step, PENDING_CHECK_ATTEMPTS) - 1
            if left > 0:
                _pending_checks[step] = left
            else:
                _pending_checks.pop(step, None)
        else:
            _pending_checks.pop(step, None)
    if checked and _metrics.enabled():
        _metrics.counter(
            "sanitizer_steps_checked",
            help="steps whose cross-rank schedule hashes rank 0 compared",
        ).inc()
    if divergence is None:
        return None
    _last_divergence = divergence
    if _metrics.enabled():
        _metrics.counter(
            "sanitizer_schedule_divergence",
            help="cross-rank collective-schedule mismatches detected by "
                 "the sanitizer",
            rank=divergence["rank"],
        ).inc()
    from horovod_tpu.resilience import health

    health.record_schedule_divergence(
        divergence["rank"], divergence["op"], step=step,
    )
    return divergence


def last_divergence() -> Optional[dict]:
    """The most recent divergence this process detected, or None."""
    return _last_divergence
