"""Import-path alias for the reference's ``horovod.spark.torch``
(``TorchEstimator``/``TorchModel``) — see :mod:`horovod_tpu.spark.keras`."""

from horovod_tpu.spark import TorchEstimator  # noqa: F401
from horovod_tpu.estimator import TorchModel  # noqa: F401
from horovod_tpu.data.store import HDFSStore, LocalStore, Store  # noqa: F401
