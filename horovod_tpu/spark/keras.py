"""Import-path alias for the reference's ``horovod.spark.keras``
(``KerasEstimator``/``KerasModel``): the implementations live Spark-free in
:mod:`horovod_tpu.estimator` with the Spark veneer in
:mod:`horovod_tpu.spark`; this module keeps migrating imports working."""

from horovod_tpu.estimator import KerasEstimator, KerasModel  # noqa: F401
from horovod_tpu.data.store import HDFSStore, LocalStore, Store  # noqa: F401
