"""Import-path alias for the reference's ``horovod.spark.keras``
(``KerasEstimator``/``KerasModel``): re-exports the Spark-facing estimator
(accepts Spark or pandas DataFrames) from :mod:`horovod_tpu.spark`; the
Spark-free engine lives in :mod:`horovod_tpu.estimator`."""

from horovod_tpu.spark import KerasEstimator  # noqa: F401
from horovod_tpu.estimator import KerasModel  # noqa: F401
from horovod_tpu.data.store import HDFSStore, LocalStore, Store  # noqa: F401
