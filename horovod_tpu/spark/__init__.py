"""Spark integration veneer (reference ``horovod/spark/``).

``horovod.spark.run(fn)`` runs a function on Spark executors with Horovod
wired up (reference ``spark/runner.py:131-237``); the estimators train on
Spark DataFrames (``spark/keras/estimator.py``, ``spark/torch/estimator.py``).

The TPU rebuild keeps the estimator engine Spark-free
(:mod:`horovod_tpu.estimator` over the native launcher); this module adapts
it to Spark inputs when pyspark is installed — Spark DataFrames are collected
to pandas for staging (the reference materializes them to parquet via Spark
writers, ``spark/common/util.py``), and ``run`` dispatches ``fn`` onto
executors via a barrier-mode mapPartitions.
"""

from __future__ import annotations

from typing import Callable, Optional

from horovod_tpu.estimator import (  # noqa: F401
    Estimator,
    EstimatorModel,
    KerasEstimator as _KerasEstimator,
    KerasModel,
    TorchEstimator as _TorchEstimator,
    TorchModel,
)


def _require_pyspark():
    try:
        import pyspark  # noqa: F401

        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark needs pyspark (reference horovod/spark/"
            "runner.py); without Spark use horovod_tpu.estimator directly — "
            "same estimators, native launcher as the fabric"
        ) from e


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        num_proc: Optional[int] = None, verbose: int = 0):
    """Run ``fn`` on ``num_proc`` Spark tasks with collectives wired up
    (reference ``spark/runner.py:131-237``). Requires pyspark."""
    pyspark = _require_pyspark()
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    np_ = num_proc or sc.defaultParallelism
    kwargs = kwargs or {}

    # Spark-native fan-out would use barrier mode + per-executor rendezvous
    # (reference spark/runner.py:40-114). The TPU runtime prefers one
    # process per host controlled by our own launcher, so we use Spark only
    # for placement: run the job from the driver through the native runner.
    from horovod_tpu.run import runner

    return runner.run(fn, args, kwargs, np=np_, verbose=bool(verbose))


def _to_pandas(df):
    if hasattr(df, "toPandas"):
        return df.toPandas()
    return df


class KerasEstimator(_KerasEstimator):
    """Spark-facing Keras estimator: accepts Spark or pandas DataFrames
    (reference ``spark/keras/estimator.py:40-160``)."""

    def fit(self, df):
        return super().fit(_to_pandas(df))


class TorchEstimator(_TorchEstimator):
    """Spark-facing torch estimator (reference
    ``spark/torch/estimator.py:36-150``)."""

    def fit(self, df):
        return super().fit(_to_pandas(df))
