"""Spark integration veneer (reference ``horovod/spark/``).

``horovod.spark.run(fn)`` runs a function on Spark executors with Horovod
wired up (reference ``spark/runner.py:131-237``); the estimators train on
Spark DataFrames (``spark/keras/estimator.py``, ``spark/torch/estimator.py``).

The TPU rebuild keeps the estimator engine Spark-free
(:mod:`horovod_tpu.estimator` over the native launcher); this module adapts
it to Spark inputs when pyspark is installed — Spark DataFrames are collected
to pandas for staging (the reference materializes them to parquet via Spark
writers, ``spark/common/util.py``), and ``run`` dispatches ``fn`` onto
executors via a barrier-mode mapPartitions.
"""

from __future__ import annotations

from typing import Callable, Optional

from horovod_tpu.estimator import (  # noqa: F401
    Estimator,
    EstimatorModel,
    KerasEstimator as _KerasEstimator,
    KerasModel,
    TorchEstimator as _TorchEstimator,
    TorchModel,
)


def _require_pyspark():
    try:
        import pyspark  # noqa: F401

        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark needs pyspark (reference horovod/spark/"
            "runner.py); without Spark use horovod_tpu.estimator directly — "
            "same estimators, native launcher as the fabric"
        ) from e


def _run_barrier_slot(ctx, fn, args, kwargs):
    """Executor-side body of the barrier-mode dispatch, one invocation per
    Spark barrier task (reference ``spark/runner.py:40-114`` task fn +
    ``:194-221`` host-hash rank grouping).

    ``ctx`` is a ``pyspark.BarrierTaskContext`` — only ``partitionId()`` and
    ``allGather(str)`` are used, so tests drive this with a fake. Steps:

    1. allGather ``partition:host`` and order ranks host-major, so tasks on
       the same host get consecutive ranks (the reference's host-hash
       grouping; matches the launcher's rank-major slot allocation).
    2. second allGather publishes rank 0's ``host:port`` as the JAX/core
       coordinator address.
    3. export the launcher-identical identity env
       (``run/hosts.py::slot_env``) and run ``fn``.

    Yields ``(rank, result)``; the driver sorts by rank.
    """
    import os
    import socket

    idx = int(ctx.partitionId())
    host = socket.gethostname()
    infos = sorted(
        (s.split(":", 1)[1], int(s.split(":", 1)[0]))
        for s in ctx.allGather(f"{idx}:{host}")
    )  # [(host, partition)] host-major
    size = len(infos)
    rank_of = {part: r for r, (_, part) in enumerate(infos)}
    my_rank = rank_of[idx]

    # local/cross coordinates within the host grouping
    my_host = host
    local_rank = sum(1 for h, p in infos[: my_rank] if h == my_host)
    local_size = sum(1 for h, _ in infos if h == my_host)
    hosts_in_order = []
    for h, _ in infos:
        if h not in hosts_in_order:
            hosts_in_order.append(h)
    cross_rank = hosts_in_order.index(my_host)
    cross_size = len(hosts_in_order)

    port = 0
    if my_rank == 0:
        from horovod_tpu.run.runner import _free_port

        port = _free_port()
    coords = [
        s for s in ctx.allGather(f"{my_rank}:{host}:{port}")
        if s.startswith("0:")
    ]
    _, coord_host, coord_port = coords[0].split(":")

    os.environ.update({
        "HOROVOD_RANK": str(my_rank),
        "HOROVOD_SIZE": str(size),
        "HOROVOD_LOCAL_RANK": str(local_rank),
        "HOROVOD_LOCAL_SIZE": str(local_size),
        "HOROVOD_CROSS_RANK": str(cross_rank),
        "HOROVOD_CROSS_SIZE": str(cross_size),
        "HVD_PROCESS_ID": str(my_rank),
        "HVD_NUM_PROCESSES": str(size),
        "HVD_COORDINATOR_ADDR": f"{coord_host}:{coord_port}",
        "HVD_CORE_COORD_ADDR": coord_host,
    })
    yield (my_rank, fn(*(args or ()), **(kwargs or {})))


def run(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
        num_proc: Optional[int] = None, verbose: int = 0,
        use_barrier: Optional[bool] = None):
    """Run ``fn`` on ``num_proc`` Spark tasks with collectives wired up
    (reference ``spark/runner.py:131-237``). Requires pyspark.

    Dispatch is barrier-mode ``mapPartitions`` on the executors by default
    (each barrier task computes its rank via allGather and runs ``fn`` —
    :func:`_run_barrier_slot`); ``use_barrier=False`` falls back to running
    the job from the *driver* through the native launcher, using Spark only
    for placement.
    """
    _require_pyspark()
    from pyspark.sql import SparkSession

    spark = SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    np_ = num_proc or sc.defaultParallelism
    kwargs = kwargs or {}
    if use_barrier is None:
        use_barrier = True

    if use_barrier:
        def _task(_it):
            from pyspark import BarrierTaskContext

            return list(
                _run_barrier_slot(BarrierTaskContext.get(), fn, args, kwargs)
            )

        pairs = (
            sc.parallelize(range(np_), np_).barrier().mapPartitions(_task)
            .collect()
        )
        return [r for _, r in sorted(pairs)]

    from horovod_tpu.run import runner

    return runner.run(fn, args, kwargs, np=np_, verbose=bool(verbose))


def _to_pandas(df):
    if hasattr(df, "toPandas"):
        return df.toPandas()
    return df


class KerasEstimator(_KerasEstimator):
    """Spark-facing Keras estimator: accepts Spark or pandas DataFrames
    (reference ``spark/keras/estimator.py:40-160``)."""

    def fit(self, df):
        return super().fit(_to_pandas(df))


class TorchEstimator(_TorchEstimator):
    """Spark-facing torch estimator (reference
    ``spark/torch/estimator.py:36-150``)."""

    def fit(self, df):
        return super().fit(_to_pandas(df))
