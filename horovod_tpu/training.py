"""Training-step builders: the framework's equivalent of the reference's
benchmark/example training loops (``examples/tensorflow2_synthetic_benchmark.py:45-70``:
loss under ``DistributedGradientTape``, allreduced grads, apply).

Two step styles, same user-visible semantics:

- :func:`make_jit_train_step` — *pjit style*: one global jitted step, batch
  sharded over the data axis, parameters replicated. XLA's sharding propagation
  inserts the gradient ``psum`` and fuses/overlaps it with the backward pass —
  this subsumes the reference's tensor-fusion + cycle pipeline
  (``controller.cc:640-761``, ``operations.cc:550-600``) in the compiler.
- :func:`make_shardmap_train_step` — *explicit-collective style*: per-shard
  compute inside ``shard_map`` with ``hvd.allreduce`` on each gradient, the
  literal Horovod programming model. BatchNorm running stats are rank-averaged
  to keep them replicated (the reference leaves them per-worker and broadcasts
  at checkpoint time; averaging is equivalent in steady state).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import basics
from horovod_tpu.analysis import sanitizer as _sanitizer
from horovod_tpu.observability import flight as _flight
from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.observability import regression as _regression
from horovod_tpu.observability import slo as _slo
from horovod_tpu.observability import straggler as _straggler
from horovod_tpu.ops.collective import Average, allreduce, _smap
from horovod_tpu.ops import overlap as _overlap
from horovod_tpu.compression import Compression
from horovod_tpu.resilience import health as _health
from horovod_tpu.resilience import numerics as _numerics


def softmax_xent(logits, labels):
    """Cross entropy with integer labels."""
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))


def token_xent(logits, targets):
    """Per-token cross entropy for causal LMs (logits ``[..., T, V]``,
    int targets ``[..., T]``), log-softmax in fp32."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, targets[..., None], axis=-1))


def init_model(model, rng, sample_input, train: bool = True):
    """Initialize (params, batch_stats) replicated over the mesh."""
    variables = model.init(rng, sample_input, train=train)
    params = variables.get("params", variables)
    batch_stats = variables.get("batch_stats", {})
    return params, batch_stats


class InstrumentedStep:
    """Wrap a step callable so every call feeds the metrics registry:
    ``train_steps``/``train_examples`` counters, a ``train_step_seconds``
    histogram of the call-to-call interval (in a donation-throttled async
    pipeline the inter-dispatch interval converges to the true device step
    time — the same steady-state argument ``profiler.timed_steps`` makes),
    and ``train_examples_per_sec``/``train_mfu`` gauges.

    MFU uses the existing :func:`horovod_tpu.profiler.device_peak_flops`
    table; without ``flops_per_step`` (or on untabled devices, e.g. CPU)
    the gauge is simply not set. Attribute access (``.lower``, AOT
    compilation, etc.) delegates to the wrapped callable, so the wrapper
    is transparent to callers that lower/compile the step themselves.
    """

    def __init__(self, fn, *, batch_arg: Optional[int] = None,
                 examples_per_step: Optional[int] = None,
                 flops_per_step: Optional[float] = None,
                 name: str = "train"):
        self._fn = fn
        self._batch_arg = batch_arg
        self._examples = examples_per_step
        self._flops = flops_per_step
        self._name = name
        self._last_t: Optional[float] = None
        self._step_idx = 0
        self._peak_total: Optional[float] = None  # n_chips * peak, lazy

    def _peak(self) -> Optional[float]:
        if self._peak_total is None:
            from horovod_tpu import profiler

            peak = profiler.device_peak_flops()
            try:
                n = basics.size()
            except RuntimeError:
                n = len(jax.devices())
            self._peak_total = (peak or 0.0) * n
        return self._peak_total or None

    def __call__(self, *args, **kwargs):
        # open this step's correlation scope BEFORE dispatch: eager
        # collectives issued by/around the step share (step, gen, seq)
        # keys across ranks (fleet trace correlation + straggler
        # attribution — ISSUE 7). The schedule sanitizer shares the
        # boundary: the finished step's op ring is published and
        # cross-checked here (HOROVOD_SANITIZE=1).
        _straggler.set_step(self._step_idx)
        _sanitizer.set_step(self._step_idx)
        # the flight ring records the boundary too (and counts it as
        # forward progress for the hang watchdog)
        _flight.step_boundary(self._step_idx)
        # the numerics fingerprint plane shares the sanitizer's boundary:
        # the finished step's per-dtype gradient fingerprint is published
        # and rank-0 cross-checked here (no-op unless enabled)
        _numerics.set_step(self._step_idx)
        self._step_idx += 1
        out = self._fn(*args, **kwargs)
        # standalone fingerprint path: without the elastic wrapper nobody
        # calls note_step, and the record published at the next boundary
        # would be a default — read the verdict from the returned state
        # (one sync per step; gated on the opt-in plane)
        _numerics.maybe_note_output(self._step_idx - 1, out)
        # a dispatched step is forward progress: walk the health machine
        # back toward HEALTHY (cheap: one lock, no metrics involved)
        _health.beat()
        if not _metrics.enabled():
            return out
        now = time.perf_counter()
        name = self._name
        examples = self._examples
        if examples is None and self._batch_arg is not None:
            try:
                examples = int(args[self._batch_arg].shape[0])
            except (IndexError, AttributeError, TypeError):
                examples = None
        _metrics.counter(
            f"{name}_steps", help="train steps dispatched"
        ).inc()
        if examples:
            _metrics.counter(
                f"{name}_examples", help="examples trained on"
            ).inc(examples)
        if self._last_t is not None:
            dt = now - self._last_t
            if dt > 0:
                _metrics.histogram(
                    f"{name}_step_seconds",
                    help="inter-dispatch step interval",
                ).observe(dt)
                if examples:
                    _metrics.gauge(
                        f"{name}_examples_per_sec",
                        help="throughput over the last step interval",
                    ).set(examples / dt)
                if self._flops:
                    peak = self._peak()
                    if peak:
                        _metrics.gauge(
                            f"{name}_mfu",
                            help="model FLOP utilization vs device peak",
                        ).set(self._flops / dt / peak)
                # SLO plane: the step interval is the step_time series
                # (counted in steps, not wall clock), and the
                # gauge-sourced objectives (subscriber staleness, input
                # data-wait) sample here so THEY are counted in steps too
                _slo.observe("step_time", dt)
                _slo.sample_gauges()
                # regression sentinel: step time / throughput / data
                # wait against their warmup-guarded rolling baselines
                _regression.track(f"{name}_step_seconds", dt)
                if examples:
                    _regression.track(
                        f"{name}_examples_per_sec", examples / dt)
                wait = _metrics.value("data_wait_seconds_recent")
                if isinstance(wait, (int, float)):
                    _regression.track("data_wait_seconds", float(wait))
        self._last_t = now
        return out

    def __getattr__(self, item):
        return getattr(self._fn, item)


def instrument_step(fn, *, batch_arg: Optional[int] = None,
                    examples_per_step: Optional[int] = None,
                    flops_per_step: Optional[float] = None,
                    name: str = "train"):
    """Public spelling of the step wrapper: ``bench.py`` wraps its
    AOT-compiled executable with the measured per-step FLOPs so
    ``train_mfu`` lands in the registry; the ``make_*_train_step``
    builders apply it automatically (``instrument=False`` opts out)."""
    return InstrumentedStep(
        fn, batch_arg=batch_arg, examples_per_step=examples_per_step,
        flops_per_step=flops_per_step, name=name,
    )


def make_loader_step(step_fn: Callable, loader) -> Callable:
    """Adapt a batch-consuming step to the ``(state, i) -> state`` shape
    :func:`horovod_tpu.resilience.run` / ``elastic.run`` drive, drawing
    each step's batch from a :class:`~horovod_tpu.data.ResumableLoader`::

        stepped = make_loader_step(
            lambda state, batch, i: train(state, *batch), loader)
        final = elastic.run(lambda world: stepped, state, num_steps=N)

    The loader's **cursor** — not the loop index — decides what each step
    consumes: a checkpoint resume, an elastic rollback, or a numerics
    replay moves the cursor (with the replay salt folded in), so the
    adapted step re-draws exactly the batches the recovery semantics
    promise (``docs/data.md``). ``step_fn(state, batch, i)`` receives the
    placed batch (a tuple for multi-array sources)."""

    def stepped(state, i):
        batch = loader.next_batch()
        return step_fn(state, batch, i)

    return stepped


def make_jit_train_step(
    model,
    tx: optax.GradientTransformation,
    *,
    loss_fn: Callable = softmax_xent,
    donate: bool = True,
    instrument: bool = True,
    overlap: Optional[bool] = None,
    bucket_bytes: Optional[int] = None,
):
    """Global-jit DP train step. Inputs: (params, batch_stats, opt_state,
    images, labels) with images/labels sharded P(data) and the rest replicated.
    Returns (params, batch_stats, opt_state, loss).

    A numerics-guarded ``tx`` (``DistributedOptimizer(numerics_guard=True)``)
    is detected automatically: the loss is multiplied by the guard's
    dynamic loss scale before the backward pass (unscaled again for the
    return value) and threaded into the update, so a non-finite loss also
    marks the step BAD.

    ``overlap=True`` (env ``HOROVOD_OVERLAP=1``): in the pjit style XLA's
    sharding propagation already emits the gradient ``psum``s where the
    backward produces each cotangent — the overlap opportunity exists in
    the dataflow, and what is missing on TPU is only the compiler
    features that exploit it. The kwarg therefore arms the
    async-collective/latency-hiding flags
    (:func:`horovod_tpu.tuning.apply_xla_flags`; a warning fires if the
    backend initialized first) and leaves the step itself unchanged. For
    explicit per-bucket collectives use
    :func:`make_shardmap_train_step`."""
    if _overlap.resolve_bucket_bytes(overlap, bucket_bytes):
        from horovod_tpu import tuning as _tuning

        _tuning.apply_xla_flags()
    guarded = _numerics.is_guarded(tx)

    def step(params, batch_stats, opt_state, images, labels):
        scale = _numerics.current_scale(opt_state) if guarded else None

        def loss_and_logits(p):
            variables = {"params": p}
            if batch_stats:
                variables["batch_stats"] = batch_stats
                logits, updates = model.apply(
                    variables, images, train=True, mutable=["batch_stats"]
                )
                loss_val = loss_fn(logits, labels)
            else:
                logits = model.apply(variables, images, train=True)
                updates = {"batch_stats": {}}
                loss_val = loss_fn(logits, labels)
            if scale is not None:
                # scale INSIDE the differentiated fn so the backward pass
                # runs at the scaled magnitude (the mixed-precision
                # underflow defense); the guard divides the grads back
                loss_val = loss_val * scale
            return loss_val, updates["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(loss_and_logits, has_aux=True)(
            params
        )
        if scale is not None:
            loss = loss / scale
        if guarded:
            updates, opt_state = tx.update(
                grads, opt_state, params, loss=loss)
        else:
            updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    donate_argnums = (0, 1, 2) if donate else ()
    jitted = jax.jit(step, donate_argnums=donate_argnums)
    # args: (params, batch_stats, opt_state, images, labels) -> the global
    # batch is images.shape[0]
    return instrument_step(jitted, batch_arg=3) if instrument else jitted


def make_shardmap_train_step(
    model,
    tx: optax.GradientTransformation,
    *,
    loss_fn: Callable = softmax_xent,
    axis: Optional[str] = None,
    compression=Compression.none,
    reduce_op=Average,
    shard_optimizer: bool = False,
    shard_params: bool = False,
    donate: bool = True,
    instrument: bool = True,
    overlap: Optional[bool] = None,
    bucket_bytes: Optional[int] = None,
):
    """Explicit Horovod-style step: shard_map over the data axis, per-shard
    grads allreduced with ``hvd.allreduce`` (the in-jit path -> lax.psum).

    Pass a *plain* optax optimizer: this step already performs the gradient
    allreduce, so wrapping `tx` in DistributedOptimizer would reduce twice
    (numerically idempotent for Average, but doubled collective traffic).

    ``shard_optimizer=True`` selects the ZeRO-1 step: `tx` must then be a
    ``DistributedOptimizer(..., shard_optimizer=True)`` — the step skips
    its own gradient allreduce (the optimizer reduce-scatters the flat
    gradient buffers, updates this rank's moment shard, and all-gathers the
    update shards), and the optimizer state rides the mesh sharded
    ``P(data)`` on its leading rank axis, so per-chip moment HBM drops by
    the axis size. Build ``opt_state = tx.init(params)`` with that same
    wrapped optimizer; ``compression``/``reduce_op`` here are then unused
    (configure them on the DistributedOptimizer), and
    ``backward_passes_per_step`` must stay 1 (MultiSteps state has no rank
    axis to shard). Both modes report ``grad_sync_bytes_per_step``.

    A numerics-guarded ``tx`` (``DistributedOptimizer(numerics_guard=
    True)`` — works in both modes, wrapping either the plain optax
    optimizer or the ZeRO-1 DistributedOptimizer) is detected
    automatically: the loss is scaled by the guard's dynamic loss scale
    before the backward pass and threaded into the update, and the
    sharded state spec becomes the guard's pytree prefix (scalars
    replicated, inner state ``P(data)``).

    ``shard_params=True`` selects the ZeRO-3 step: ``tx`` must be a
    ``DistributedOptimizer(shard_params=True)`` and ``params`` the packed
    :class:`~horovod_tpu.optim.FsdpParams` shards from
    :func:`horovod_tpu.optim.fsdp_pack_params` (spec'd ``P(data)`` as a
    pytree prefix, like the opt state). The step gathers the full tree
    on use (:func:`~horovod_tpu.optim.fsdp_gather_params` — one
    all-gather per pack group, issue-order pinned; ``HOROVOD_FSDP_WIRE=
    int8`` quantizes the wire), runs the forward under ``jax.checkpoint``
    so the gathered tree is DISCARDED after the forward and re-gathered
    in the backward, and differentiates straight through the gather: its
    transpose reduce-scatters the gradient shards, so the optimizer sees
    exactly ZeRO-1's reduced buffers and the fp32 trajectory is
    bit-identical to ``shard_optimizer=True``. Per-chip param AND
    optimizer HBM drop by the axis size; wire cost is
    ``(N-1)/N·(2·P_gather + P_grad)`` vs ZeRO-1's ``(N-1)/N·2·P``
    (``grad_sync_bytes_per_step{mode=zero3}`` /
    ``param_gather_bytes_per_step{mode=zero3}``). The numerics guard
    does not compose with this mode yet.

    ``overlap=True`` (env ``HOROVOD_OVERLAP=1``; ``bucket_bytes=``
    overrides ``HOROVOD_BUCKET_BYTES``, default 64 MB): the gradient
    exchange becomes **bucketed** — ~bucket-sized flat collectives in
    reverse backprop-emission order, each depending only on its own
    leaves' cotangents, so XLA can launch them while the remaining
    backward still runs (:mod:`horovod_tpu.ops.overlap`). In the
    ``shard_optimizer=True`` mode the exchange belongs to the
    DistributedOptimizer — build it with ``overlap=True`` there (the
    same ``HOROVOD_OVERLAP=1`` env flips both layers together); this
    kwarg then changes nothing here.
    """
    mesh = basics.mesh()
    ax = axis or basics.data_axis()
    ov_bytes = _overlap.resolve_bucket_bytes(overlap, bucket_bytes)
    if getattr(compression, "factorized", False) and not shard_optimizer:
        raise ValueError(
            "PowerSGD compression is stateful (warm-started Q + error "
            "feedback); wrap the optimizer in DistributedOptimizer("
            "compression=Compression.powersgd(r), error_feedback=True) and "
            "pass shard_optimizer=True (or use it without this builder) "
            "instead of passing it as the step's compression="
        )
    guarded = _numerics.is_guarded(tx)

    if shard_params:
        if guarded:
            raise ValueError(
                "numerics_guard does not compose with shard_params=True "
                "yet (see DistributedOptimizer); train ZeRO-3 unguarded "
                "or guard the ZeRO-1 step"
            )
        from horovod_tpu import optim as _optim

        def fsdp_step(params, batch_stats, opt_state, images, labels):
            def loss_and_stats(fp):
                p = _optim.fsdp_gather_params(fp)
                variables = {"params": p}
                if batch_stats:
                    variables["batch_stats"] = batch_stats
                    logits, updates = model.apply(
                        variables, images, train=True,
                        mutable=["batch_stats"]
                    )
                    stats = updates["batch_stats"]
                else:
                    logits = model.apply(variables, images, train=True)
                    stats = {}
                return loss_fn(logits, labels), stats

            # jax.checkpoint: the gathered tree is DISCARDED after the
            # forward and re-gathered in the backward — param liveness
            # stays one bucket deep instead of the whole model, the
            # ZeRO-3 memory deal (the gather wire runs twice for it)
            (loss, new_stats), gshards = jax.value_and_grad(
                jax.checkpoint(loss_and_stats), has_aux=True)(params)
            new_stats = jax.tree_util.tree_map(
                lambda s: allreduce(s, Average, axis=ax), new_stats
            )
            loss = allreduce(loss, Average, axis=ax)
            updates, new_opt_state = tx.update(gshards, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, new_stats, new_opt_state, loss

        rep = P()
        sharded = P(ax)
        smapped = _smap(
            fsdp_step,
            mesh,
            (P(ax), rep, P(ax), sharded, sharded),
            (P(ax), rep, P(ax), rep),
        )
        donate_argnums = (0, 1, 2) if donate else ()
        jitted = jax.jit(smapped, donate_argnums=donate_argnums)
        return instrument_step(jitted, batch_arg=3) if instrument else jitted

    def shard_step(params, batch_stats, opt_state, images, labels):
        scale = _numerics.current_scale(opt_state) if guarded else None

        def loss_and_stats(p):
            variables = {"params": p}
            if batch_stats:
                variables["batch_stats"] = batch_stats
                logits, updates = model.apply(
                    variables, images, train=True, mutable=["batch_stats"]
                )
                stats = updates["batch_stats"]
            else:
                logits = model.apply(variables, images, train=True)
                stats = {}
            loss_val = loss_fn(logits, labels)
            if scale is not None:
                loss_val = loss_val * scale
            return loss_val, stats

        (loss, new_stats), grads = jax.value_and_grad(loss_and_stats, has_aux=True)(
            params
        )
        if scale is not None:
            loss = loss / scale
        if not shard_optimizer:
            if ov_bytes:
                # bucketed backward-pass sync: K reverse-emission flat
                # collectives, overlappable with the remaining backward
                # (bucketed_allreduce records the wire-byte gauges)
                grads, _ = _overlap.bucketed_allreduce(
                    grads, reduce_op, axis=ax, compression=compression,
                    bucket_bytes=ov_bytes,
                )
            else:
                # the Horovod step: combine gradients across ranks
                # (Average, Sum, or Adasum — reference op= on
                # DistributedOptimizer)
                from horovod_tpu.optim import (
                    _record_sync_bytes, _tree_sync_wire_bytes,
                )
                from horovod_tpu.ops.collective import _axis_size

                _record_sync_bytes(
                    "allreduce", _axis_size(ax),
                    _tree_sync_wire_bytes(grads, compression),
                )
                grads = jax.tree_util.tree_map(
                    lambda g: allreduce(
                        g, reduce_op, axis=ax, compression=compression),
                    grads,
                )
        # keep BN running stats replicated
        new_stats = jax.tree_util.tree_map(
            lambda s: allreduce(s, Average, axis=ax), new_stats
        )
        loss = allreduce(loss, Average, axis=ax)
        if guarded:
            # the guard consumes the (already rank-averaged) loss so a
            # non-finite loss marks the step BAD alongside the grads
            updates, new_opt_state = tx.update(
                grads, opt_state, params, loss=loss)
        else:
            updates, new_opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, new_opt_state, loss

    rep = P()
    sharded = P(ax)
    opt_spec = P(ax) if shard_optimizer else rep
    if guarded and shard_optimizer:
        # pytree-prefix spec: the guard's EWMA/loss-scale scalars are
        # replicated; only the wrapped [N, shard] inner state rides P(ax)
        opt_spec = _numerics.shard_state_spec(P(ax))
    smapped = _smap(
        shard_step,
        mesh,
        (rep, rep, opt_spec, sharded, sharded),
        (rep, rep, opt_spec, rep),
    )
    donate_argnums = (0, 1, 2) if donate else ()
    jitted = jax.jit(smapped, donate_argnums=donate_argnums)
    return instrument_step(jitted, batch_arg=3) if instrument else jitted


def make_pp_train_step(
    stage_fn: Callable,
    tx: optax.GradientTransformation,
    *,
    loss_fn: Optional[Callable] = None,
    interleaved: bool = False,
    axis: Optional[str] = None,
    donate: bool = True,
):
    """Pipeline-parallel train step over the ``pipe`` axis (TPU-native
    extension — the reference is DP-only, SURVEY.md §2.7).

    ``stage_fn(stage_params, activation) -> activation`` is one stage's
    forward. Stage parameters arrive stacked on a leading device axis
    (``make_stage_params`` for GPipe: ``[S, ...]``;
    ``make_interleaved_stage_params`` + ``interleaved=True`` for the
    circular schedule: ``[S, v, ...]``) and sharded ``P("pipe")``;
    ``opt_state`` likewise (build it with ``jax.vmap(tx.init)(stacked)``
    so every leaf gains the stage axis). ``x_micro``/``y_micro`` are
    ``[n_micro, mb, ...]`` replicated.

    The backward runs through the schedule's scan (mirrored order); the
    per-device gradient of the psum-replicated loss over-counts by the
    pipe size (psum's transpose is psum — every device differentiates its
    own copy of the same scalar), normalized here before the update.
    Returns jitted ``(stacked_params, opt_state, x_micro, y_micro) ->
    (stacked_params, opt_state, loss)``.
    """
    from jax import lax

    from horovod_tpu.parallel.pipeline import (
        pipeline_apply, pipeline_apply_interleaved,
    )
    from horovod_tpu.parallel.mesh import PIPELINE_AXIS

    if loss_fn is None:
        loss_fn = lambda out, y: jnp.mean((out - y) ** 2)  # noqa: E731
    mesh = basics.mesh()
    ax = axis or PIPELINE_AXIS
    apply_fn = pipeline_apply_interleaved if interleaved else pipeline_apply

    def pp_step(stacked, opt_state, xm, ym):
        local = jax.tree_util.tree_map(lambda p: p[0], stacked)
        local_opt = jax.tree_util.tree_map(lambda s: s[0], opt_state)

        def local_loss(lp):
            out = apply_fn(stage_fn, lp, xm, axis_name=ax)
            out = lax.psum(out, ax)  # valid on the last stage only
            return loss_fn(out, ym)

        loss, grads = jax.value_and_grad(local_loss)(local)
        k = lax.psum(1, ax)
        grads = jax.tree_util.tree_map(lambda g: g / k, grads)
        updates, local_opt = tx.update(grads, local_opt, local)
        local = optax.apply_updates(local, updates)
        return (
            jax.tree_util.tree_map(lambda p: p[None], local),
            jax.tree_util.tree_map(lambda s: s[None], local_opt),
            loss,
        )

    smapped = _smap(
        pp_step,
        mesh,
        (P(ax), P(ax), P(), P()),
        (P(ax), P(ax), P()),
    )
    donate_argnums = (0, 1) if donate else ()
    return jax.jit(smapped, donate_argnums=donate_argnums)


def make_sp_train_step(
    model,
    tx: optax.GradientTransformation,
    *,
    data_axis: Optional[str] = None,
    seq_axis: str = "seq",
    donate: bool = True,
):
    """Sequence-parallel causal-LM train step: shard_map over (data, seq),
    tokens/targets sharded ``P(data, seq)``, params replicated, the model's
    attention running as a ring over the ``seq`` axis
    (:func:`horovod_tpu.parallel.ring_attention`).

    Build the model with
    ``attention_fn=functools.partial(ring_attention, axis_name=seq_axis)`` —
    this step supplies per-shard ``positions`` so embeddings line up, computes
    the next-token loss on aligned ``(tokens, targets)`` shards, and combines
    gradients over *both* axes (data psum = the Horovod exchange; seq psum =
    the sequence-parallel gradient fold). No reference counterpart: Horovod
    0.19.2 has no sequence axis (SURVEY.md §5.7).
    """
    mesh = basics.mesh()
    dax = data_axis or basics.data_axis()

    def shard_step(params, opt_state, tokens, targets):
        t_local = tokens.shape[1]
        seq_idx = jax.lax.axis_index(seq_axis)
        positions = seq_idx * t_local + jnp.arange(t_local)[None, :]

        def loss_fn(p):
            logits = model.apply({"params": p}, tokens, positions=positions)
            return token_xent(logits, targets)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = jax.tree_util.tree_map(
            lambda g: allreduce(allreduce(g, Average, axis=dax),
                                Average, axis=seq_axis),
            grads,
        )
        loss = allreduce(allreduce(loss, Average, axis=dax),
                         Average, axis=seq_axis)
        updates, new_opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_opt_state, loss

    rep = P()
    sharded = P(dax, seq_axis)
    smapped = _smap(
        shard_step,
        mesh,
        (rep, rep, sharded, sharded),
        (rep, rep, rep),
    )
    donate_argnums = (0, 1) if donate else ()
    return jax.jit(smapped, donate_argnums=donate_argnums)


def shard_batch(batch, *, axis: Optional[str] = None):
    """Place a host array with leading batch dim onto the mesh, sharded over
    the data axis (the launcher-side analog of Horovod's per-rank data
    sharding in every example script)."""
    mesh = basics.mesh()
    ax = axis or basics.data_axis()
    return jax.device_put(batch, NamedSharding(mesh, P(ax)))


def replicate(tree):
    """Replicate a pytree over the mesh (params/opt state)."""
    mesh = basics.mesh()
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)


def host_snapshot(tree):
    """Host-offloaded copy of a state pytree: every array leaf (device or
    host) becomes an owned ``np.ndarray``; other leaves pass through.

    This is the elastic layer's rollback snapshot
    (:mod:`horovod_tpu.resilience.elastic`) and the weight publisher's
    consolidation step (:mod:`horovod_tpu.serving` — the payload must not
    be invalidated mid-upload by the next donated step): the copy blocks on
    each leaf (``np.array`` of a ``jax.Array`` synchronizes), survives a
    mesh teardown — the arrays no longer reference any device buffer — and,
    being an owned copy, cannot be invalidated by a later donated step
    consuming the live state. Cost: one D2H transfer of the state per
    committed step; size it with ``snapshot_every``."""

    def one(x):
        if isinstance(x, (jax.Array, np.ndarray, np.generic)):
            return np.array(x)
        return x

    return jax.tree_util.tree_map(one, tree)


def zero_shard_opt_state(opt_state, *, axis: Optional[str] = None):
    """ZeRO-1 style optimizer-state sharding (no reference analog — upstream
    is pure DP with fully replicated optimizer state on every worker).

    Places every optimizer-state leaf sharded over the data axis on dim 0
    (when divisible; small/indivisible leaves stay replicated). On TPU,
    sharding is a *layout annotation*: the update math is unchanged and XLA
    inserts the reduce-scatter / all-gather pattern around the sharded
    moment update automatically, so per-chip optimizer-state HBM drops by
    ~axis-size x — the ZeRO-1 memory result without a new algorithm. Use on
    the output of ``tx.init`` before entering the step loop::

        opt_state = zero_shard_opt_state(tx.init(params))

    Works with :func:`make_jit_train_step` (donation keeps the layout
    steady across steps).
    """
    return _shard_dim0_tree(opt_state, axis)


def fsdp_shard_params(params, *, axis: Optional[str] = None):
    """FSDP / ZeRO-3 style parameter sharding (no reference analog).

    Same dim-0-over-data-axis placement as :func:`zero_shard_opt_state`,
    applied to the *parameters*: per-chip param HBM drops ~axis-size x, and
    under jit XLA inserts the FSDP communication pattern itself — all-gather
    each weight where the forward/backward consumes it, reduce-scatter the
    gradient where the sharded state updates it. Shard the optimizer state
    too (its leaves inherit the params' layout through ``tx.init``, or pass
    them through :func:`zero_shard_opt_state`) and keep donation on so the
    layout is steady across steps::

        params = fsdp_shard_params(params)
        opt_state = zero_shard_opt_state(tx.init(params))
        step = make_jit_train_step(model, tx)   # unchanged

    Pair with ``jax.checkpoint`` on the model for the usual FSDP memory win
    on deep stacks (re-gather instead of holding gathered weights).
    """
    return _shard_dim0_tree(params, axis)


def _shard_dim0_tree(tree, axis: Optional[str]):
    from horovod_tpu.ops.collective import _mesh_axis_size

    mesh = basics.mesh()
    ax = axis or basics.data_axis()
    n = _mesh_axis_size(mesh, ax)  # product for tuple (host) axes
    repl = NamedSharding(mesh, P())
    #: leaves that WOULD shard but for dim-0 divisibility: (nbytes, name)
    indivisible = []

    def _axes_in(entry):
        if entry is None:
            return ()
        return entry if isinstance(entry, tuple) else (entry,)

    def place(path, x):
        shape = getattr(x, "shape", ())
        existing = getattr(x, "sharding", None)
        spec = (
            list(existing.spec)
            if isinstance(existing, NamedSharding) and existing.spec
            else []
        )
        spec += [None] * (len(shape) - len(spec))
        ax_parts = set(ax) if isinstance(ax, tuple) else {ax}
        ax_used = any(ax_parts & set(_axes_in(e)) for e in spec)
        if (
            len(shape) >= 1
            and shape[0] > 0
            and shape[0] % n == 0
            and spec[0] is None
            and not ax_used
        ):
            # merge the data axis into dim 0, preserving any existing
            # model/pipe/... sharding on the other dims (TP-sharded params
            # give their optimizer moments the same layout; clobbering it
            # would re-replicate them and inflate per-chip HBM)
            spec[0] = ax
            return jax.device_put(x, NamedSharding(mesh, P(*spec)))
        if any(e is not None for e in spec):
            return x  # keep a non-trivial existing layout untouched
        if len(shape) >= 1 and shape[0] > 0 and shape[0] % n != 0:
            # the ONLY disqualifier was divisibility: this leaf stays
            # replicated on every chip — count it so a mostly-replicated
            # "sharded" model shows up in the metrics instead of as a
            # mystery OOM
            nbytes = int(
                np.prod(shape, dtype=np.int64)
            ) * jnp.dtype(getattr(x, "dtype", jnp.float32)).itemsize
            indivisible.append(
                (nbytes, jax.tree_util.keystr(path), tuple(shape)))
        return jax.device_put(x, repl)

    out = jax.tree_util.tree_map_with_path(place, tree)
    if indivisible:
        if _metrics.enabled():
            _metrics.counter(
                "fsdp_leaves_replicated",
                help="leaves left replicated by dim-0 sharding (dim 0 "
                     "not divisible by the axis size)",
                reason="indivisible",
            ).inc(len(indivisible))
        global _INDIVISIBLE_LOGGED
        if not _INDIVISIBLE_LOGGED:
            _INDIVISIBLE_LOGGED = True
            import logging

            worst = max(indivisible)
            logging.getLogger("horovod_tpu").debug(
                "dim-0 sharding left %d leaves replicated (dim 0 not "
                "divisible by axis size %d); worst: %s shape=%s "
                "(%.1f KiB per chip). Pad dim 0 to a multiple of the "
                "axis size, or shard with fsdp_pack_params (the flat "
                "packing pads internally).",
                len(indivisible), n, worst[1], worst[2], worst[0] / 1024,
            )
    return out


#: one-shot flag for the indivisible-leaf debug log (per process, not per
#: call: zero_shard_opt_state/fsdp_shard_params run every restore)
_INDIVISIBLE_LOGGED = False


def split_transformer_for_pp(model, params, n_stages: int, *,
                             interleaved_v: int = 1):
    """Split a :class:`~horovod_tpu.models.TransformerLM` param tree for
    pipeline parallelism: ``depth`` blocks grouped into stages, with the
    (replicated) embedding and head parts separated.

    ``interleaved_v > 1`` lays out ``n_stages * v`` stages round-robin for
    the interleaved/circular schedule (stacked ``[S, v, ...]``); the GPipe
    default stacks ``[S, ...]``.

    Returns ``{"embed": …, "stages": stacked, "head": …}`` — the input to
    :func:`make_transformer_pp_train_step`.
    """
    n_total = n_stages * interleaved_v
    if model.depth % n_total != 0:
        raise ValueError(
            f"depth {model.depth} not divisible by n_stages*v = {n_total}"
        )
    if model.pos_embedding != "learned":
        raise ValueError(
            "PP transformer currently supports pos_embedding='learned' "
            "(positions resolve at embed time; rope would need per-stage "
            "position plumbing)"
        )
    per = model.depth // n_total
    stage_trees = [
        {f"b{j}": params[f"block{s * per + j}"] for j in range(per)}
        for s in range(n_total)
    ]
    from horovod_tpu.parallel.pipeline import (
        make_interleaved_stage_params, make_stage_params,
    )

    if interleaved_v > 1:
        stacked = make_interleaved_stage_params(stage_trees, n_stages)
    else:
        stacked = make_stage_params(stage_trees)
    embed = {"tok_embed": params["tok_embed"], "pos_embed": params["pos_embed"]}
    head = {"ln_f": params["ln_f"], "lm_head": params["lm_head"]}
    return {"embed": embed, "stages": stacked, "head": head}


def make_transformer_pp_train_step(
    model,
    tx: optax.GradientTransformation,
    *,
    interleaved_v: int = 1,
    axis: Optional[str] = None,
    donate: bool = True,
):
    """Pipeline-parallel causal-LM train step for a real
    :class:`~horovod_tpu.models.TransformerLM` — embeddings, transformer
    blocks, and the LM head all trained (TPU-native extension; the generic
    :func:`make_pp_train_step` pipelines uniform stages only).

    Gradient bookkeeping over the pipe axis:

    - **stages**: each device's grad is for its own stage; the
      psum-replicated loss over-counts by the pipe size — divide by S
      (same recipe as :func:`make_pp_train_step`).
    - **embed**: only stage 0 reads the pipeline input
      (``pipeline_apply`` masks it elsewhere), so the true gradient is the
      ``psum`` over the axis of per-device grads (zero off stage 0).
    - **head**: applied to the already-psum-replicated output identically
      on every device, with no collective between head params and the loss
      — the per-device grad IS the true gradient (``pmean`` only tidies
      fp noise).

    Oracle: ``tests/test_transformer.py::
    test_transformer_pp_train_step_matches_dense`` (loss + every updated
    parameter vs the dense single-device step).

    Params come from :func:`split_transformer_for_pp` (pass the same
    ``interleaved_v``); build ``opt_state`` as
    ``{"embed": tx.init(p["embed"]), "head": tx.init(p["head"]),
    "stages": jax.vmap(tx.init)(p["stages"])}`` (double-vmap when
    interleaved: the stages tree is ``[S, v, ...]``). Tokens/targets are
    ``[n_micro, mb, T]`` replicated. Returns jitted
    ``(params, opt_state, tokens_micro, targets_micro) ->
    (params, opt_state, loss)``.
    """
    from jax import lax

    from horovod_tpu.parallel.mesh import PIPELINE_AXIS
    from horovod_tpu.parallel.pipeline import (
        pipeline_apply, pipeline_apply_interleaved,
    )

    mesh = basics.mesh()
    ax = axis or PIPELINE_AXIS
    n_stages = mesh.shape[ax]
    if model.depth % (n_stages * interleaved_v) != 0:
        raise ValueError(
            f"depth {model.depth} not divisible by n_stages*v = "
            f"{n_stages * interleaved_v}; pass the same interleaved_v used "
            f"in split_transformer_for_pp"
        )
    per = model.depth // (n_stages * interleaved_v)
    apply_fn = (
        pipeline_apply_interleaved if interleaved_v > 1 else pipeline_apply
    )

    import flax.linen as nn

    from horovod_tpu.models.transformer import TransformerBlock

    block = TransformerBlock(
        model.dim, model.heads, model.mlp_ratio, model.dtype,
        model.attention_fn, kv_heads=model.kv_heads,
    )
    # the real flax modules, so LayerNorm/Dense semantics (stat upcasting,
    # dtype handling) can never drift from TransformerLM's own head
    ln_f = nn.LayerNorm(dtype=model.dtype)
    lm_head = nn.Dense(model.vocab, use_bias=False, dtype=model.dtype)

    def embed_fn(ep, tokens):
        # mirror TransformerLM.__call__'s embedding path (learned positions)
        t = tokens.shape[-1]
        x = jnp.take(ep["tok_embed"]["embedding"], tokens, axis=0)
        x = x.astype(model.dtype)
        return x + ep["pos_embed"][:t].astype(model.dtype)

    def stage_fn(sp, h):
        for j in range(per):
            h = block.apply({"params": sp[f"b{j}"]}, h)
        return h

    def head_fn(hp, x):
        x = ln_f.apply({"params": hp["ln_f"]}, x)
        logits = lm_head.apply({"params": hp["lm_head"]}, x)
        return logits.astype(jnp.float32)

    def pp_step(params, opt_state, toks_m, tgts_m):
        local = jax.tree_util.tree_map(lambda p: p[0], params["stages"])
        local_opt = jax.tree_util.tree_map(
            lambda s: s[0], opt_state["stages"])

        def loss_fn(ep, lp, hp):
            h = embed_fn(ep, toks_m)
            out = apply_fn(stage_fn, lp, h, axis_name=ax)
            out = lax.psum(out, ax)
            return token_xent(head_fn(hp, out), tgts_m)

        loss, (g_e, g_s, g_h) = jax.value_and_grad(
            loss_fn, argnums=(0, 1, 2)
        )(params["embed"], local, params["head"])
        S = lax.psum(1, ax)
        g_s = jax.tree_util.tree_map(lambda g: g / S, g_s)
        g_e = jax.tree_util.tree_map(lambda g: lax.psum(g, ax) / S, g_e)
        # no psum sits between head params and the loss (each device
        # applies the head to the already-replicated output), so the
        # per-device grad IS the true gradient; pmean only tidies fp noise
        g_h = jax.tree_util.tree_map(lambda g: lax.pmean(g, ax), g_h)

        u_s, local_opt = tx.update(g_s, local_opt, local)
        local = optax.apply_updates(local, u_s)
        u_e, opt_e = tx.update(g_e, opt_state["embed"], params["embed"])
        embed = optax.apply_updates(params["embed"], u_e)
        u_h, opt_h = tx.update(g_h, opt_state["head"], params["head"])
        head = optax.apply_updates(params["head"], u_h)
        return (
            {
                "embed": embed,
                "stages": jax.tree_util.tree_map(lambda p: p[None], local),
                "head": head,
            },
            {
                "embed": opt_e,
                "stages": jax.tree_util.tree_map(
                    lambda s: s[None], local_opt),
                "head": opt_h,
            },
            loss,
        )

    # pytree-prefix specs: P() covers whole replicated subtrees, P(ax) the
    # stage-stacked ones — static, so shard_map + jit build ONCE here and
    # the training loop hits the jit cache every step
    part_spec = {"embed": P(), "stages": P(ax), "head": P()}
    smapped = _smap(
        pp_step, mesh,
        (part_spec, part_spec, P(), P()),
        (part_spec, part_spec, P()),
    )
    donate_argnums = (0, 1) if donate else ()
    return jax.jit(smapped, donate_argnums=donate_argnums)
