"""Safe subprocess execution with process-tree cleanup.

Reference: ``horovod/run/common/util/safe_shell_exec.py`` — spawn each rank in
its own process group; on interrupt/failure/parent-death, kill the *whole
tree* (GRACEFUL_TERMINATION_TIME grace, then SIGKILL). The reference uses a
middleman process; here a monitor thread + ``os.killpg`` on a
``start_new_session`` child achieves the same tree-kill semantics without the
extra fork.
"""

from __future__ import annotations

import os
import signal
import subprocess
import threading
import time
from typing import Callable, Optional, Sequence

GRACEFUL_TERMINATION_TIME_S = 5  # reference safe_shell_exec.py


def terminate_tree(proc: subprocess.Popen, grace: float = GRACEFUL_TERMINATION_TIME_S):
    """SIGTERM the child's process group, then SIGKILL survivors."""
    # start_new_session made the child its own group leader, so pgid == pid
    # and stays valid for killpg even after the leader is reaped (surviving
    # grandchildren keep the group alive).
    pgid = proc.pid
    try:
        os.killpg(pgid, signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    deadline = time.monotonic() + grace
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            break
        time.sleep(0.05)
    try:
        os.killpg(pgid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass


def execute(
    command: Sequence[str] | str,
    env: Optional[dict] = None,
    stdout_handler: Optional[Callable[[str], None]] = None,
    stderr_handler: Optional[Callable[[str], None]] = None,
    event: Optional[threading.Event] = None,
    shell: bool = False,
) -> int:
    """Run `command` in its own session; if `event` fires first, kill the whole
    process tree and return -SIGTERM (reference ``safe_shell_exec.execute``).

    `stdout_handler`/`stderr_handler` receive decoded lines as they arrive
    (the per-rank prefix tagging lives in the caller, reference
    ``gloo_run.py:189-232``).
    """
    proc = subprocess.Popen(
        command,
        env=env,
        shell=shell,
        stdout=subprocess.PIPE if stdout_handler else None,
        stderr=subprocess.PIPE if stderr_handler else None,
        start_new_session=True,
        text=True if (stdout_handler or stderr_handler) else None,
    )

    pumps = []

    def pump(stream, handler):
        for line in stream:
            handler(line)
        stream.close()

    for stream, handler in (
        (proc.stdout, stdout_handler),
        (proc.stderr, stderr_handler),
    ):
        if stream is not None and handler is not None:
            t = threading.Thread(target=pump, args=(stream, handler), daemon=True)
            t.start()
            pumps.append(t)

    killed = threading.Event()
    watcher = None
    if event is not None:

        def watch():
            while proc.poll() is None:
                if event.wait(0.1):
                    killed.set()
                    terminate_tree(proc)
                    return

        watcher = threading.Thread(target=watch, daemon=True)
        watcher.start()

    proc.wait()
    for t in pumps:
        t.join(timeout=5)
    if watcher is not None:
        watcher.join(timeout=GRACEFUL_TERMINATION_TIME_S + 2)
    # sweep stragglers in the group even on normal exit
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    if killed.is_set():
        return -signal.SIGTERM
    return proc.returncode
