"""``horovodrun``-equivalent launcher for the TPU-native framework.

Reference: ``horovod/run/`` (R1-R8 in SURVEY.md §2.4) — CLI parsing
(``run/runner.py:221-453``), slot allocation (``run/gloo_run.py:54-112``),
env plumbing (``run/common/util/config_parser.py``), rendezvous/KV server
(``run/http/http_server.py``), safe process execution
(``run/common/util/safe_shell_exec.py``), and the programmatic
``horovod.run.run()`` API (``run/runner.py:632-653``).

TPU-native differences:

- one process per *host* (the TPU runtime owns all local chips), not one per
  accelerator; ``-np`` is the number of processes;
- NIC discovery (reference ``run/driver/driver_service.py:128-194``) is
  replaced by TPU topology discovery: JAX's distributed runtime handles
  device wire-up given a coordinator address, so the launcher only picks a
  coordinator host:port and exports it;
- the data plane needs no launcher help at all — XLA collectives ride ICI/DCN;
  the launcher boots (a) ``jax.distributed`` and (b) the native control-plane
  core's TCP coordinator (csrc/), both via environment variables.
"""

from horovod_tpu.run.runner import run, run_commandline, main  # noqa: F401
from horovod_tpu.run.hosts import HostSlots, parse_hosts, allocate  # noqa: F401
