"""Launcher orchestration: CLI parsing, process fan-out, result collection.

Reference: ``horovod/run/runner.py`` (CLI, ``_run``, ``run_controller``,
programmatic ``run()``), ``horovod/run/gloo_run.py`` (per-slot env + spawn +
failure propagation). One process per TPU host; local slots spawn directly,
remote slots over ssh (command construction mirrors
``gloo_run.py:143-163``).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import pickle
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Callable, List, Optional, Sequence

from horovod_tpu.run import config_parser, hosts as hosts_mod
from horovod_tpu.run.hosts import HostSlots
from horovod_tpu.run.rendezvous import (
    ADDRS_ENV,
    KVStoreClient,
    KVStoreServer,
    SECRET_ENV,
    format_endpoints,
    make_secret,
)
from horovod_tpu.run import replication as _replication
from horovod_tpu.run import safe_exec
from horovod_tpu.run.env_util import scrub_plugin_hooks
from horovod_tpu.resilience import retry as _retry
from horovod_tpu.resilience.loop import RESUMABLE_EXIT_CODE
from horovod_tpu.observability import metrics as _metrics


class HostStrikes:
    """Per-host failed-restart strikes with blacklisting (the launcher-level
    analog of the strike-pruning the core's fusion buckets already do for
    absent tensors): a host whose *restarted* workers keep dying again
    stops receiving restarts, so a flapping machine cannot burn the whole
    restart budget. First failures and preemptions never strike — see the
    restart loop in :func:`launch_job`. Limit via
    ``HOROVOD_HOST_STRIKE_LIMIT`` (default 3).

    **Re-admission** (elastic): strikes older than ``decay_s``
    (``HOROVOD_HOST_STRIKE_DECAY``, seconds; default 0 = strikes are
    permanent) are forgotten, so a host blacklisted during a bad stretch —
    a flapping NIC, a kernel that needed a reboot — becomes eligible for
    restarts again once it has stayed quiet for the decay window, instead
    of being dead to the job forever."""

    def __init__(self, limit: Optional[int] = None,
                 decay_s: Optional[float] = None):
        if limit is None:
            limit = int(os.environ.get("HOROVOD_HOST_STRIKE_LIMIT", "3"))
        if decay_s is None:
            decay_s = float(os.environ.get("HOROVOD_HOST_STRIKE_DECAY", "0"))
        self.limit = limit
        self.decay_s = decay_s
        self._strikes: dict = {}  # host -> [monotonic strike times]
        self._lock = threading.Lock()

    def _fresh_locked(self, host: str) -> list:
        times = self._strikes.get(host, [])
        if self.decay_s > 0:
            cutoff = time.monotonic() - self.decay_s
            times = [t for t in times if t > cutoff]
            if times:
                self._strikes[host] = times
            else:
                self._strikes.pop(host, None)
        return times

    def strike(self, host: str) -> int:
        with self._lock:
            times = self._fresh_locked(host)
            times = times + [time.monotonic()]
            self._strikes[host] = times
            return len(times)

    def forgive(self, host: str) -> None:
        """A worker that came back up clears its host's record."""
        with self._lock:
            self._strikes.pop(host, None)

    def blacklisted(self, host: str) -> bool:
        with self._lock:
            return len(self._fresh_locked(host)) >= self.limit


def parse_args(argv: Optional[Sequence[str]] = None):
    """CLI surface (reference ``runner.py:221-453``; flags that configure
    GPU/MPI backends are intentionally absent — XLA is the only data plane)."""
    p = argparse.ArgumentParser(
        prog="hvdrun",
        description="Launch a horovod_tpu training job: one process per TPU "
        "host, wired up via jax.distributed + the native control-plane "
        "coordinator.",
    )
    p.add_argument("-v", "--version", action="store_true", help="print version")
    p.add_argument("-cb", "--check-build", action="store_true",
                   dest="check_build",
                   help="print available frontends/controllers/operations "
                        "and exit (reference horovodrun --check-build)")
    # migration-compat controller flags (reference horovodrun --gloo/--mpi).
    # The single controller here fills the no-MPI role the reference calls
    # gloo mode, so --gloo is an accepted no-op; --mpi errors clearly.
    p.add_argument("--gloo", action="store_true", dest="use_gloo",
                   help="accepted for horovodrun compatibility (the TCP "
                        "controller already fills this role)")
    p.add_argument("--mpi", action="store_true", dest="use_mpi",
                   help="not supported: no MPI exists in this framework")
    p.add_argument("-np", "--num-proc", type=int, dest="np", default=None,
                   help="number of processes (one per TPU host)")
    p.add_argument("-H", "--hosts", dest="hosts", default=None,
                   help="host list, e.g. host1:1,host2:1 (slots per host)")
    p.add_argument("--hostfile", dest="hostfile", default=None,
                   help="hostfile with lines 'hostname slots=N'")
    p.add_argument("--ssh-port", type=int, dest="ssh_port", default=None)
    p.add_argument("--start-timeout", type=int, dest="start_timeout",
                   default=int(os.environ.get("HOROVOD_START_TIMEOUT", "30")))
    p.add_argument("--max-restarts", type=int, dest="max_restarts",
                   default=None,
                   help="restart a failed worker in place up to N times "
                        "(preempted workers exit resumable and resume from "
                        "their emergency checkpoint; default "
                        "HOROVOD_MAX_RESTARTS or 0)")
    p.add_argument("--min-workers", type=int, dest="min_workers",
                   default=None,
                   help="elastic floor: a permanently failed slot no longer "
                        "kills the job while the surviving worker count "
                        "stays >= this (default "
                        "HOROVOD_ELASTIC_MIN_WORKERS, else 0 = rigid: any "
                        "failure kills the job)")
    p.add_argument("--max-workers", type=int, dest="max_workers",
                   default=None,
                   help="elastic ceiling exported to workers as "
                        "HOROVOD_ELASTIC_MAX_WORKERS (bounds in-process "
                        "mesh growth on rejoin; default: the launched slot "
                        "count)")
    p.add_argument("--kv-standbys", type=int, dest="kv_standbys",
                   default=None,
                   help="warm standby KV servers for control-plane HA: "
                        "the launcher's rendezvous store replicates every "
                        "write to them and workers get the full endpoint "
                        "list (HVD_RUN_KV_ADDRS) for automatic failover "
                        "(default HOROVOD_KV_REPLICAS, else 0 = single "
                        "KV server)")
    p.add_argument("--kv-standby-hosts", dest="kv_standby_hosts",
                   default=None,
                   help="comma-separated hosts to run the standbys on "
                        "over ssh (python -m horovod_tpu.run.replication); "
                        "default: in the launcher process — standbys on "
                        "other hosts survive a launcher-host loss")
    p.add_argument("--output-filename", dest="output_filename", default=None,
                   help="per-rank stdout/stderr capture directory "
                        "(reference gloo_run per-rank dirs)")
    p.add_argument("--verbose", action="store_true", dest="verbose")
    p.add_argument("--config-file", dest="config_file", default=None)
    # perf knobs (reference config_parser.py)
    p.add_argument("--fusion-threshold-mb", type=float,
                   dest="fusion_threshold_mb", default=None)
    p.add_argument("--cycle-time-ms", type=float, dest="cycle_time_ms",
                   default=None)
    p.add_argument("--cache-capacity", type=int, dest="cache_capacity",
                   default=None)
    hier_ar = p.add_mutually_exclusive_group()
    hier_ar.add_argument("--hierarchical-allreduce", action="store_true",
                         dest="hierarchical_allreduce", default=None,
                         help="two-level (cross x local) allreduce for "
                              "tuple-axis ops (reference "
                              "HOROVOD_HIERARCHICAL_ALLREDUCE)")
    hier_ar.add_argument("--no-hierarchical-allreduce", action="store_false",
                         dest="hierarchical_allreduce", default=None)
    hier_ag = p.add_mutually_exclusive_group()
    hier_ag.add_argument("--hierarchical-allgather", action="store_true",
                         dest="hierarchical_allgather", default=None,
                         help="two-level (cross x local) allgather")
    hier_ag.add_argument("--no-hierarchical-allgather", action="store_false",
                         dest="hierarchical_allgather", default=None)
    p.add_argument("--native-core", action="store_true", dest="native_core",
                   help="route named async collectives through the native "
                        "control-plane core (fusion/cache/stall/timeline)")
    p.add_argument("--timeline-filename", dest="timeline_filename",
                   default=None)
    p.add_argument("--timeline-mark-cycles", action="store_true",
                   dest="timeline_mark_cycles")
    p.add_argument("--no-stall-check", action="store_true",
                   dest="no_stall_check")
    p.add_argument("--stall-check-warning-time-seconds", type=float,
                   dest="stall_check_warning_time_seconds", default=None)
    p.add_argument("--stall-check-shutdown-time-seconds", type=float,
                   dest="stall_check_shutdown_time_seconds", default=None)
    p.add_argument("--autotune", action="store_true", dest="autotune")
    p.add_argument("--autotune-log-file", dest="autotune_log_file",
                   default=None)
    p.add_argument("--autotune-warmup-samples", type=int,
                   dest="autotune_warmup_samples", default=None)
    p.add_argument("--autotune-steps-per-sample", type=int,
                   dest="autotune_steps_per_sample", default=None)
    p.add_argument("--log-level", dest="log_level", default=None,
                   choices=["TRACE", "DEBUG", "INFO", "WARNING", "ERROR",
                            "FATAL"])
    p.add_argument("--log-hide-timestamp", action="store_true",
                   dest="log_hide_timestamp")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="training command, e.g. python train.py")

    args = p.parse_args(argv)

    if args.config_file:
        # config overrides defaults but not explicit flags
        explicit = _explicit_dests(p, argv if argv is not None else sys.argv[1:])
        cfg = config_parser.parse_config_file(args.config_file)
        config_parser.override_args(args, cfg, explicit)
    config_parser.validate_config_args(args)
    return args


def _explicit_dests(parser: argparse.ArgumentParser, argv) -> set:
    """Dest names the user actually passed on the CLI. Stops at the start of
    the training command so its own flags (which may collide with hvdrun
    option names) are not miscounted."""
    explicit = set()
    opt_to_action = {}
    for action in parser._actions:
        for opt in action.option_strings:
            opt_to_action[opt] = action
    i = 0
    while i < len(argv):
        tok = argv[i]
        if tok == "--":
            break
        key = tok.split("=", 1)[0]
        action = opt_to_action.get(key)
        if action is None:
            break  # first non-hvdrun token = the training command
        explicit.add(action.dest)
        takes_value = (
            action.nargs != 0
            and not isinstance(
                action, (argparse._StoreTrueAction, argparse._StoreFalseAction)
            )
        )
        if takes_value and "=" not in tok:
            i += 1  # skip the option's value token
        i += 1
    return explicit


def _free_port() -> int:
    s = socket.socket()
    s.bind(("0.0.0.0", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _local_ip() -> str:
    return socket.gethostbyname(socket.gethostname())


def _is_local(hostname: str) -> bool:
    return hostname in ("localhost", "127.0.0.1", socket.gethostname(),
                        socket.getfqdn(), _safe_local_ip())


def _safe_local_ip():
    try:
        return _local_ip()
    except OSError:
        return "127.0.0.1"


def build_command_for_slot(
    slot: HostSlots,
    command: Sequence[str],
    env: dict,
    coordinator_addr: str,
    jax_port: int,
    core_port: int,
    ssh_port: Optional[int] = None,
    start_timeout: Optional[int] = None,
) -> tuple:
    """(argv, env) for one slot; remote slots get an ssh wrapper with env
    inlined (reference ``gloo_run.py:143-163`` ssh + exported env)."""
    slot_env = dict(env)
    slot_env.update(hosts_mod.slot_env(slot))
    slot_env["HVD_COORDINATOR_ADDR"] = f"{coordinator_addr}:{jax_port}"
    slot_env["HVD_CORE_COORD_ADDR"] = coordinator_addr
    slot_env["HVD_CORE_COORD_PORT"] = str(core_port)
    if start_timeout is not None:
        # consumed by hvd.init() as jax.distributed initialization_timeout
        slot_env["HVD_START_TIMEOUT"] = str(start_timeout)
    if _is_local(slot.hostname):
        return list(command), slot_env
    exports = " ".join(
        f"{k}={shlex.quote(v)}"
        for k, v in sorted(slot_env.items())
        if k.startswith(("HOROVOD_", "HVD_", "PYTHON", "PATH", "JAX_", "XLA_"))
    )
    ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    remote = f"cd {shlex.quote(os.getcwd())} > /dev/null 2>&1 ; " \
             f"env {exports} {' '.join(shlex.quote(c) for c in command)}"
    return ssh + [slot.hostname, remote], env


def launch_job(
    slots: List[HostSlots],
    command: Sequence[str],
    env: Optional[dict] = None,
    *,
    output_filename: Optional[str] = None,
    verbose: bool = False,
    ssh_port: Optional[int] = None,
    timeout_s: Optional[float] = None,
    start_timeout: Optional[int] = None,
    max_restarts: Optional[int] = None,
    min_workers: Optional[int] = None,
    max_workers: Optional[int] = None,
) -> List[int]:
    """Spawn every slot, stream rank-tagged output, kill all on first
    *unrecoverable* failure (reference ``gloo_run.launch_gloo``: one nonzero
    exit terminates the job, ``gloo_run.py:294-304``). Returns per-rank exit
    codes.

    With ``max_restarts > 0`` (or ``HOROVOD_MAX_RESTARTS``), a slot that
    exits nonzero — a preempted worker exits
    :data:`~horovod_tpu.resilience.loop.RESUMABLE_EXIT_CODE` and resumes
    from its emergency checkpoint — is restarted in place with the shared
    backoff policy (``HOROVOD_RETRY_WORKER_RESTART_*``), bounded per slot
    and per host: a host that keeps striking out is blacklisted
    (:class:`HostStrikes`) and stops receiving restarts.

    Restart-in-place assumes the whole job cycles together (the TPU
    preemption model: every host gets SIGTERM, every rank exits 75, every
    slot restarts into a fresh rendezvous). A single rank of a
    still-running multi-rank job that dies alone cannot re-enter its
    peers' in-flight ``jax.distributed``/coordinator session, so by
    default a lone-crash job still ends via the kill-on-failure path —
    after the restart budget instead of immediately.

    With ``min_workers > 0`` (``--min-workers`` /
    ``HOROVOD_ELASTIC_MIN_WORKERS``) the launcher stops treating a
    permanently failed slot (restarts exhausted or host blacklisted) as
    fatal while the surviving slot count stays >= ``min_workers``: the
    slot is abandoned and the survivors keep running. The *survivors must
    be able to proceed without the dead rank* for this to help: slots
    whose work is independent (one single-controller SPMD process per
    slot — each owns its own mesh and can resize in-process via
    ``horovod_tpu.resilience.elastic``) continue unaffected, while a
    ``jax.distributed`` gang that allreduces with the dead rank will fail
    or stall-shutdown on its next collective and needs a supervisor
    relaunch at the smaller ``-np`` (the in-process mesh re-formation is
    single-controller only). Blacklisted hosts are re-admitted for later
    restarts once their strikes decay (``HOROVOD_HOST_STRIKE_DECAY``)."""
    env = dict(env if env is not None else os.environ)
    if max_restarts is None:
        max_restarts = int(os.environ.get("HOROVOD_MAX_RESTARTS", "0"))
    if min_workers is None:
        min_workers = int(os.environ.get("HOROVOD_ELASTIC_MIN_WORKERS", "0"))
    if min_workers:
        env["HOROVOD_ELASTIC_MIN_WORKERS"] = str(min_workers)
    if max_workers:
        env["HOROVOD_ELASTIC_MAX_WORKERS"] = str(max_workers)
    else:
        # default to the launched slot count, but never clobber an
        # operator-exported cap (symmetric with MIN_WORKERS above)
        env.setdefault("HOROVOD_ELASTIC_MAX_WORKERS", str(len(slots)))
    abandoned = {"n": 0}
    abandon_lock = threading.Lock()
    strikes = HostStrikes()
    # HOROVOD_RETRY_WORKER_RESTART_* tunes the backoff shape only; the
    # restart COUNT is --max-restarts/HOROVOD_MAX_RESTARTS, pinned after
    # the env so a stray MAX_ATTEMPTS override can neither add restarts
    # nor starve the delays() schedule below the restart budget
    restart_policy = dataclasses.replace(
        _retry.policy_from_env(
            "worker_restart", base_delay=0.5, max_delay=10.0,
        ),
        max_attempts=max_restarts + 1,
    )
    env.setdefault("PYTHONUNBUFFERED", "1")
    # CPU-pinned jobs must not inherit sitecustomize TPU-plugin hooks: the
    # hook registers the plugin before JAX_PLATFORMS is consulted and can
    # wedge backend init when the TPU tunnel is unhealthy (see env_util).
    scrub_plugin_hooks(env)
    # The coordinator (jax.distributed + native-core TCP) runs inside the
    # rank-0 *process*, so the address every slot connects to is rank 0's
    # host — loopback only when the whole job is local. (The port is probed
    # free on the launcher; for a remote rank 0 a random high port is chosen,
    # which is free in practice.)
    all_local = all(_is_local(s.hostname) for s in slots)
    if all_local:
        coordinator_addr = "127.0.0.1"
    elif _is_local(slots[0].hostname):
        coordinator_addr = _safe_local_ip()
    else:
        coordinator_addr = slots[0].hostname
    jax_port = _free_port()
    core_port = _free_port()

    stop = threading.Event()
    codes: List[Optional[int]] = [None] * len(slots)
    threads = []
    out_dir = None
    if output_filename:
        out_dir = output_filename
        os.makedirs(out_dir, exist_ok=True)

    def run_slot(i: int, slot: HostSlots):
        argv, slot_env = build_command_for_slot(
            slot, command, env, coordinator_addr, jax_port, core_port,
            ssh_port, start_timeout,
        )
        sinks = []
        if out_dir:
            # "w": fresh files per launch_job invocation; in-job restarts
            # keep appending through these same open handles
            fo = open(os.path.join(out_dir, f"rank.{slot.rank}.out"), "w")
            fe = open(os.path.join(out_dir, f"rank.{slot.rank}.err"), "w")
            sinks = [fo, fe]

            def out_h(line, _f=fo):
                _f.write(line)
                _f.flush()

            def err_h(line, _f=fe):
                _f.write(line)
                _f.flush()
        else:
            def out_h(line, _r=slot.rank):
                sys.stdout.write(f"[{_r}]<stdout> {line}")

            def err_h(line, _r=slot.rank):
                sys.stderr.write(f"[{_r}]<stderr> {line}")

        delays = restart_policy.delays()
        attempt = 0
        while True:
            rc = safe_exec.execute(
                argv, env=slot_env, stdout_handler=out_h,
                stderr_handler=err_h, event=stop,
            )
            if rc == 0:
                strikes.forgive(slot.hostname)
                break
            if stop.is_set():
                break  # killed as part of job teardown, not a failure here
            if rc != RESUMABLE_EXIT_CODE and attempt > 0:
                # only a RESTARTED slot failing again strikes its host:
                # preemptions (exit 75) are the healthy path, and a single
                # correlated crash (one rank dies, every peer's collectives
                # abort nonzero) would otherwise land one strike per slot
                # and insta-blacklist any host running >= limit slots
                strikes.strike(slot.hostname)
            if attempt >= max_restarts:
                break
            if rc != RESUMABLE_EXIT_CODE and strikes.blacklisted(
                slot.hostname
            ):
                sys.stderr.write(
                    f"hvdrun: host {slot.hostname} blacklisted "
                    f"({strikes.limit} failed restarts); not restarting "
                    f"rank {slot.rank}\n"
                )
                break
            attempt += 1
            kind = (
                "preempted (resumable)" if rc == RESUMABLE_EXIT_CODE
                else f"exit {rc}"
            )
            delay = next(delays, restart_policy.max_delay)
            sys.stderr.write(
                f"hvdrun: rank {slot.rank} on {slot.hostname} {kind}; "
                f"restart {attempt}/{max_restarts} in {delay:.1f}s\n"
            )
            if _metrics.enabled():
                _metrics.counter(
                    "resilience_worker_restarts",
                    help="worker processes restarted by the launcher",
                    host=slot.hostname,
                ).inc()
            if stop.wait(delay):
                break
        for f in sinks:
            f.close()
        codes[i] = rc
        if rc != 0 and not stop.is_set():
            if rc != RESUMABLE_EXIT_CODE and min_workers:
                # elastic tolerance: abandon this slot instead of killing
                # the job, as long as the floor holds — the survivors
                # re-form at the smaller world size (preemptions stay on
                # the whole-job path: every rank got SIGTERM anyway)
                with abandon_lock:
                    abandoned["n"] += 1
                    surviving = len(slots) - abandoned["n"]
                if surviving >= min_workers:
                    sys.stderr.write(
                        f"hvdrun: rank {slot.rank} on {slot.hostname} "
                        f"abandoned (exit {rc}); continuing with "
                        f"{surviving} worker(s) >= min-workers "
                        f"{min_workers}\n"
                    )
                    if _metrics.enabled():
                        _metrics.counter(
                            "resilience_elastic_slots_abandoned",
                            help="permanently failed slots tolerated by "
                                 "the elastic floor",
                            host=slot.hostname,
                        ).inc()
                    return
                sys.stderr.write(
                    f"hvdrun: rank {slot.rank} failure drops the job below "
                    f"min-workers {min_workers}; tearing down\n"
                )
            if rc == RESUMABLE_EXIT_CODE:
                # a preempted rank's exit must not SIGKILL its peers out of
                # their own drain-and-checkpoint window (teardown escalates
                # to SIGKILL after ~5s; the drain budget is 30s): in a real
                # preemption every rank got SIGTERM and will exit 75 on its
                # own — give them the drain budget before the kill-all
                grace = float(os.environ.get(
                    "HOROVOD_PREEMPT_DRAIN_TIMEOUT", "30"
                )) + 5.0
                t0 = time.monotonic()
                while time.monotonic() - t0 < grace:
                    if all(c is not None for c in codes):
                        break  # everyone already down on their own
                    if stop.wait(0.1):
                        break
            stop.set()  # kill the rest of the job

    for i, slot in enumerate(slots):
        t = threading.Thread(target=run_slot, args=(i, slot))
        t.start()
        threads.append(t)
    deadline = None if timeout_s is None else time.monotonic() + timeout_s
    for t in threads:
        t.join(
            timeout=None if deadline is None
            else max(0.0, deadline - time.monotonic())
        )
    if any(t.is_alive() for t in threads):
        stop.set()  # job exceeded its deadline: kill every process tree
        for t in threads:
            t.join(timeout=safe_exec.GRACEFUL_TERMINATION_TIME_S + 5)
    return [c if c is not None else -1 for c in codes]


def _check_build_summary() -> str:
    """Availability summary (reference ``check_build``, ``runner.py:115-151``
    — same shape, honest TPU-native content)."""
    import importlib.util

    def have(mod):
        return "X" if importlib.util.find_spec(mod) is not None else " "

    def flag(b):
        return "X" if b else " "

    # degrade to honest blanks (not a traceback) when the package can't
    # import — e.g. no jax in the environment, the one case where the JAX
    # row should read [ ]
    version = "?"
    native = " "
    built = {k: " " for k in ("xla", "nccl", "ddl", "ccl", "mpi", "gloo")}
    try:
        import horovod_tpu
        from horovod_tpu import basics, core

        version = horovod_tpu.__version__
        native = flag(core.library_available())
        built = {
            "xla": flag(basics.xla_built()),
            "nccl": flag(basics.nccl_built()),
            "ddl": flag(basics.ddl_built()),
            "ccl": flag(basics.ccl_built()),
            "mpi": flag(basics.mpi_built()),
            "gloo": flag(basics.gloo_built()),
        }
    except Exception as e:
        import logging

        logging.getLogger("horovod_tpu.run").debug(
            "build-info probe incomplete: %s", e)
    return (
        f"horovod_tpu v{version}:\n\n"
        "Available Frontends:\n"
        f"    [{have('tensorflow')}] TensorFlow\n"
        f"    [{have('torch')}] PyTorch\n"
        f"    [{have('mxnet')}] MXNet\n"
        f"    [{have('keras')}] Keras\n"
        f"    [{have('jax')}] JAX / optax (native)\n\n"
        "Available Controllers:\n"
        f"    [{native}] TCP (native core)\n"
        f"    [{built['mpi']}] MPI\n"
        f"    [{built['gloo']}] Gloo\n\n"
        "Available Tensor Operations:\n"
        f"    [{built['xla']}] XLA (psum/all_gather/ppermute "
        "over ICI/DCN)\n"
        f"    [{built['nccl']}] NCCL\n"
        f"    [{built['ddl']}] DDL\n"
        f"    [{built['ccl']}] CCL\n"
        f"    [{built['mpi']}] MPI\n"
        f"    [{built['gloo']}] Gloo"
    )


def _launch_control_plane(args, env: dict, slots) -> Optional[Callable]:
    """``--kv-standbys``: stand up the HA rendezvous control plane —
    a primary KV server plus N warm standbys (in the launcher process,
    or on ``--kv-standby-hosts`` over ssh), replication attached, the
    full endpoint list exported to workers as ``HVD_RUN_KV_ADDRS`` so
    their clients fail over automatically. Each local standby runs a
    :class:`~horovod_tpu.run.replication.FailoverMonitor`, so a primary
    loss mid-job promotes without operator action. Returns a ``close()``
    callable, or None when no standbys were requested."""
    n = (args.kv_standbys if args.kv_standbys is not None
         else int(os.environ.get(_replication.REPLICAS_ENV, "0")))
    if n <= 0:
        return None
    secret = env.get(SECRET_ENV) or make_secret()
    addr = (
        "127.0.0.1"
        if all(_is_local(s.hostname) for s in slots)
        else _safe_local_ip()
    )
    primary = KVStoreServer(secret=secret)
    primary.start()
    standby_hosts = [
        h.strip() for h in (args.kv_standby_hosts or "").split(",")
        if h.strip()
    ]
    standbys, procs, endpoints = [], [], [(addr, primary.port)]
    local, remote_plan = [], []
    for i in range(n):
        host = standby_hosts[i % len(standby_hosts)] if standby_hosts \
            else None
        if host is None or _is_local(host):
            s = KVStoreServer(secret=secret, role="standby")
            s.start()
            standbys.append(s)
            local.append((i, s))
            endpoints.append((addr, s.port))
        else:
            # remote standby: random high port, same convention as a
            # remote rank-0 coordinator (free in practice)
            port = _free_port()
            remote_plan.append((i, host, port))
            endpoints.append((host, port))
    # remote standbys launch only once the FULL endpoint list is known:
    # every FailoverMonitor needs its election peers (--peers), or on a
    # primary loss each remote standby would promote itself at the same
    # time — the WAL .lock is per-host and cannot arbitrate across hosts
    peers = format_endpoints(endpoints[1:])
    for i, host, port in remote_plan:
        remote = (
            f"env {SECRET_ENV}={shlex.quote(secret)} "
            f"{shlex.quote(sys.executable)} -m "
            f"horovod_tpu.run.replication --role standby "
            f"--port {port} --primary {addr}:{primary.port} "
            f"--peers {shlex.quote(peers)} "
            f"--index {i} --advertise {shlex.quote(host)}"
        )
        ssh = ["ssh", "-o", "StrictHostKeyChecking=no"]
        if args.ssh_port:
            ssh += ["-p", str(args.ssh_port)]
        procs.append(subprocess.Popen(ssh + [host, remote]))
    sender = _replication.ReplicationSender(
        endpoints[1:], secret=secret,
        primary_hint=f"{addr}:{primary.port}")
    primary.attach_replicator(sender)
    monitors = []
    for i, s in local:
        # index by overall standby position (not local-list position) so
        # mixed local/remote deployments keep election precedence unique
        m = _replication.FailoverMonitor(
            s, (addr, primary.port), peers=endpoints[1:], index=i,
            secret=secret)
        m.start()
        monitors.append(m)
    env[SECRET_ENV] = secret
    env["HVD_RUN_KV_ADDR"] = addr
    env["HVD_RUN_KV_PORT"] = str(primary.port)
    env[ADDRS_ENV] = format_endpoints(endpoints)

    def close():
        for m in monitors:
            m.stop()
        sender.close()
        for p in procs:
            p.terminate()
        for s in standbys:
            s.close()
        primary.close()

    return close


def run_commandline(argv: Optional[Sequence[str]] = None) -> int:
    """``hvdrun`` entry point (reference ``run_commandline``)."""
    args = parse_args(argv)
    if args.version:
        import horovod_tpu

        print(horovod_tpu.__version__)
        return 0
    if args.check_build:
        print(_check_build_summary())
        return 0
    if args.use_mpi:
        print(
            "error: --mpi is not supported — this framework has no MPI by "
            "design; the XLA data plane + TCP controller cover that role "
            "(see docs/migrating.md)",
            file=sys.stderr,
        )
        return 2
    if not args.command:
        print("error: no training command given", file=sys.stderr)
        return 2
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    np = args.np or 1
    slots = hosts_mod.get_host_assignments(args.hosts, args.hostfile, np)
    env = dict(os.environ)
    config_parser.set_env_from_args(env, args)
    cp_close = _launch_control_plane(args, env, slots)
    try:
        codes = launch_job(
            slots,
            command,
            env,
            output_filename=args.output_filename,
            verbose=args.verbose,
            ssh_port=args.ssh_port,
            start_timeout=args.start_timeout,
            max_restarts=args.max_restarts,
            min_workers=args.min_workers,
            max_workers=args.max_workers,
        )
    finally:
        if cp_close is not None:
            cp_close()
    min_workers = args.min_workers or int(
        os.environ.get("HOROVOD_ELASTIC_MIN_WORKERS", "0"))
    bad = [(i, c) for i, c in enumerate(codes) if c != 0]
    if (
        bad
        and min_workers
        and len(codes) - len(bad) >= min_workers
        and all(c != RESUMABLE_EXIT_CODE for _, c in bad)
    ):
        print(
            f"hvdrun: {len(bad)}/{len(codes)} slot(s) abandoned; job "
            f"completed elastically with {len(codes) - len(bad)} worker(s)",
            file=sys.stderr,
        )
        return 0
    if bad:
        print(
            f"hvdrun: {len(bad)}/{len(codes)} processes failed: "
            + ", ".join(
                f"rank {i} "
                + ("preempted (restarts exhausted)"
                   if c == RESUMABLE_EXIT_CODE else f"exit {c}")
                for i, c in bad
            ),
            file=sys.stderr,
        )
        # A preempted job is itself resumable: a supervisor that relaunches
        # on EX_TEMPFAIL gets a clean resume from the emergency checkpoints.
        # The first rank to exit 75 triggers the kill-all teardown, so its
        # peers — mid-drain on the same preemption — are reaped as -SIGTERM;
        # count those as preemption, not failure.
        preemptish = all(
            c in (RESUMABLE_EXIT_CODE, -signal.SIGTERM) for _, c in bad
        )
        if preemptish and any(c == RESUMABLE_EXIT_CODE for _, c in bad):
            return RESUMABLE_EXIT_CODE
        return 1
    return 0


def main():
    sys.exit(run_commandline())


# --------------------------------------------------------------------------
# programmatic API: horovod_tpu.run.run(fn, ...) (reference runner.py:632-653,
# 726+: cloudpickled fn shipped via KV store, per-rank results collected)

_WORKER_SNIPPET = """\
import os, pickle, sys
from horovod_tpu.run.rendezvous import kv_client_from_env
timeout = float(os.environ.get("HVD_RUN_TIMEOUT", "300"))
# prefers the HVD_RUN_KV_ADDRS endpoint list (control-plane HA: the client
# fails over to a promoted standby) over the single ADDR/PORT pair
client = kv_client_from_env()
if client is None:
    raise RuntimeError("no KV endpoint in env (HVD_RUN_KV_ADDRS or "
                       "HVD_RUN_KV_ADDR/HVD_RUN_KV_PORT)")
fn, fn_args, fn_kwargs = pickle.loads(client.wait_for("func", timeout=timeout))
rank = int(os.environ["HOROVOD_RANK"])
try:
    result = fn(*fn_args, **fn_kwargs)
    client.put(f"result_{rank}", pickle.dumps(("ok", result)))
except BaseException as e:  # ship the failure back, then fail the rank
    import traceback
    client.put(f"result_{rank}",
               pickle.dumps(("error", f"{e}\\n{traceback.format_exc()}")))
    sys.exit(1)
"""


def run(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    *,
    np: int = 1,
    hosts: Optional[str] = None,
    hostfile: Optional[str] = None,
    env: Optional[dict] = None,
    use_native_core: bool = False,
    verbose: bool = False,
    timeout_s: float = 300.0,
    kv_standbys: int = 0,
) -> list:
    """Run ``fn(*args, **kwargs)`` on `np` launched processes; returns the
    list of per-rank return values, rank-ordered (reference
    ``horovod.run.run``). With ``kv_standbys > 0`` the rendezvous KV gets
    that many warm in-process standbys with replication + failover
    monitors attached, and the workers' clients receive the full
    endpoint list (``HVD_RUN_KV_ADDRS``) — the programmatic spelling of
    ``hvdrun --kv-standbys``."""
    try:
        import cloudpickle as pickler
    except ImportError:  # pragma: no cover
        pickler = pickle
    kwargs = kwargs or {}
    secret = make_secret()
    server = KVStoreServer(secret=secret)
    server.start()
    server.put("func", pickler.dumps((fn, args, kwargs)))
    slots = hosts_mod.get_host_assignments(hosts, hostfile, np)
    job_env = dict(env if env is not None else os.environ)
    kv_addr = (
        "127.0.0.1"
        if all(_is_local(s.hostname) for s in slots)
        else _safe_local_ip()
    )
    job_env["HVD_RUN_KV_ADDR"] = kv_addr
    job_env["HVD_RUN_KV_PORT"] = str(server.port)
    job_env["HVD_RUN_TIMEOUT"] = str(timeout_s)
    job_env[SECRET_ENV] = secret
    standbys, monitors, sender = [], [], None
    if kv_standbys > 0:
        standbys = _replication.spawn_local_standbys(
            kv_standbys, secret=secret)
        endpoints = [(kv_addr, server.port)] + [
            (kv_addr, s.port) for s in standbys]
        sender = _replication.ReplicationSender(
            endpoints[1:], secret=secret,
            primary_hint=f"{kv_addr}:{server.port}")
        server.attach_replicator(sender)
        for i, s in enumerate(standbys):
            m = _replication.FailoverMonitor(
                s, (kv_addr, server.port), peers=endpoints[1:], index=i,
                secret=secret)
            m.start()
            monitors.append(m)
        job_env[ADDRS_ENV] = format_endpoints(endpoints)
    if use_native_core:
        job_env["HOROVOD_NATIVE_CORE"] = "1"

    def _result_store():
        """Where the ranks' results actually landed: the server holding
        the newest primary regime — a standby promoted mid-job (highest
        fencing epoch) outranks the original primary."""
        primaries = [
            s for s in [server] + standbys if s.role == "primary"]
        if not primaries:
            return server
        return max(primaries, key=lambda s: s.fencing_epoch)

    try:
        codes = launch_job(
            slots, [sys.executable, "-c", _WORKER_SNIPPET], job_env,
            verbose=verbose, timeout_s=timeout_s,
        )
        store = _result_store()
        results = []
        for r in range(np):
            blob = store.get(f"result_{r}")
            if blob is None:
                raise RuntimeError(
                    f"rank {r} produced no result (exit code {codes[r]})"
                )
            status, value = pickle.loads(blob)
            if status == "error":
                raise RuntimeError(f"rank {r} failed:\n{value}")
            results.append(value)
        return results
    finally:
        for m in monitors:
            m.stop()
        if sender is not None:
            sender.close()
        for s in standbys:
            s.close()
        server.stop()
