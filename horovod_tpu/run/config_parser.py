"""YAML config file + CLI-flag → environment plumbing.

Reference: ``horovod/run/common/util/config_parser.py`` — a YAML config file
overrides argparse defaults, and ``set_env_from_args`` exports the resulting
knobs as ``HOROVOD_*`` environment variables read once by the core at init
(SURVEY.md §5.6; env catalog ``common/common.h:61-88``).
"""

from __future__ import annotations

from typing import Optional


# config file keys -> argparse dest (reference config_parser.py:2-34)
_PARAMS_SCHEMA = {
    "fusion_threshold_mb": "fusion_threshold_mb",
    "cycle_time_ms": "cycle_time_ms",
    "cache_capacity": "cache_capacity",
    "native_core": "native_core",
    "hierarchical_allreduce": "hierarchical_allreduce",
    "hierarchical_allgather": "hierarchical_allgather",
    "timeline": {
        "filename": "timeline_filename",
        "mark_cycles": "timeline_mark_cycles",
    },
    "stall_check": {
        "disable": "no_stall_check",
        "warning_time_seconds": "stall_check_warning_time_seconds",
        "shutdown_time_seconds": "stall_check_shutdown_time_seconds",
    },
    "autotune": {
        "enable": "autotune",
        "log_file": "autotune_log_file",
        "warmup_samples": "autotune_warmup_samples",
        "steps_per_sample": "autotune_steps_per_sample",
    },
    "library_options": {
        "log_level": "log_level",
        "hide_timestamp": "log_hide_timestamp",
    },
}


def parse_config_file(path: str) -> dict:
    """Load the YAML config into a flat {argparse-dest: value} dict."""
    import yaml

    with open(path) as f:
        data = yaml.safe_load(f) or {}
    flat = {}

    def walk(schema, node, ctx):
        for key, dest in schema.items():
            if key not in node:
                continue
            val = node[key]
            if isinstance(dest, dict):
                if not isinstance(val, dict):
                    raise ValueError(f"config key '{ctx}{key}' must be a mapping")
                walk(dest, val, ctx + key + ".")
            else:
                flat[dest] = val

    walk(_PARAMS_SCHEMA, data, "")
    unknown = set(data) - set(_PARAMS_SCHEMA)
    if unknown:
        raise ValueError(f"unknown config keys: {sorted(unknown)}")
    return flat


def override_args(args, config: dict, explicit_dests: set):
    """Config values override argparse *defaults* but not explicitly-passed
    CLI flags (reference config_parser.py:107-139 override semantics)."""
    for dest, val in config.items():
        if dest not in explicit_dests and hasattr(args, dest):
            setattr(args, dest, val)
    return args


def set_env_from_args(env: dict, args) -> dict:
    """Export knobs as HOROVOD_* env (reference config_parser.py:141-166)."""

    def setif(name, value, transform=str):
        if value is not None:
            env[name] = transform(value)

    if getattr(args, "fusion_threshold_mb", None) is not None:
        env["HOROVOD_FUSION_THRESHOLD"] = str(
            int(args.fusion_threshold_mb * 1024 * 1024)
        )
    setif("HOROVOD_CYCLE_TIME", getattr(args, "cycle_time_ms", None))
    setif("HOROVOD_CACHE_CAPACITY", getattr(args, "cache_capacity", None))
    # tri-state: None = leave unset (ops-layer default off)
    for flag, var in (
        ("hierarchical_allreduce", "HOROVOD_HIERARCHICAL_ALLREDUCE"),
        ("hierarchical_allgather", "HOROVOD_HIERARCHICAL_ALLGATHER"),
    ):
        val = getattr(args, flag, None)
        if val is not None:
            env[var] = "1" if val else "0"
    setif("HOROVOD_TIMELINE", getattr(args, "timeline_filename", None))
    if getattr(args, "timeline_mark_cycles", False):
        env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
    if getattr(args, "no_stall_check", False):
        env["HOROVOD_STALL_CHECK_DISABLE"] = "1"
    else:
        setif(
            "HOROVOD_STALL_CHECK_TIME_SECONDS",
            getattr(args, "stall_check_warning_time_seconds", None),
        )
        setif(
            "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS",
            getattr(args, "stall_check_shutdown_time_seconds", None),
        )
    if getattr(args, "autotune", False):
        env["HOROVOD_AUTOTUNE"] = "1"
        setif("HOROVOD_AUTOTUNE_LOG", getattr(args, "autotune_log_file", None))
        setif(
            "HOROVOD_AUTOTUNE_WARMUP_SAMPLES",
            getattr(args, "autotune_warmup_samples", None),
        )
        setif(
            "HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE",
            getattr(args, "autotune_steps_per_sample", None),
        )
    setif("HOROVOD_LOG_LEVEL", getattr(args, "log_level", None))
    if getattr(args, "log_hide_timestamp", False):
        env["HOROVOD_LOG_HIDE_TIME"] = "1"
    if getattr(args, "native_core", False):
        env["HOROVOD_NATIVE_CORE"] = "1"
    return env


def validate_config_args(args):
    """Sanity checks (reference config_parser.py:168-182)."""
    ft = getattr(args, "fusion_threshold_mb", None)
    if ft is not None and ft < 0:
        raise ValueError("--fusion-threshold-mb must be >= 0")
    ct = getattr(args, "cycle_time_ms", None)
    if ct is not None and ct <= 0:
        raise ValueError("--cycle-time-ms must be > 0")
    cc = getattr(args, "cache_capacity", None)
    if cc is not None and cc < 0:
        raise ValueError("--cache-capacity must be >= 0")
