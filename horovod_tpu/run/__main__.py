"""``python -m horovod_tpu.run`` == ``hvdrun`` (reference ``bin/horovodrun``)."""

from horovod_tpu.run.runner import main

if __name__ == "__main__":
    main()
