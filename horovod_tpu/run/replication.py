"""Control-plane high availability for the rendezvous KV store.

The rendezvous :class:`~horovod_tpu.run.rendezvous.KVStoreServer` carries
everything the fleet coordinates through — elastic membership, the
sanitizer/numerics planes, the weight-publication chain, replica leases,
the rollout decision log — which made the one KV host the last single
point of failure in the system. This module closes it with the classic
production-control-plane shape (ZooKeeper/Raft lineage, scaled down to
the launcher's needs):

- :class:`ReplicationSender` — the primary ships every WAL record to N
  warm standbys *before* acknowledging the mutation (append-before-ack to
  a quorum, ``HOROVOD_KV_REPLICATION_QUORUM`` default 1; endpoints beyond
  the quorum receive the stream asynchronously). The wire format IS the
  WAL record format, torn-tail tolerance included. A standby that cannot
  be reached within ``HOROVOD_KV_REPLICATION_TIMEOUT`` is detached rather
  than stalling the primary; its divergence is visible as
  ``rendezvous_replication_lag_entries``.
- :class:`FailoverMonitor` — lease-based election: each standby probes
  the primary's ``/-/status``; once the lease
  (``HOROVOD_KV_REPLICA_LEASE``) expires it defers to any *ready*
  lower-index standby (lowest-ready wins) and otherwise promotes itself.
- :func:`promote` — the observable promotion wrapper: runs
  ``KVStoreServer.promote()`` (WAL lock acquired atomically, shipped WAL
  replayed with TTL leases re-armed, fencing epoch bumped past everything
  the log has seen), emits the ``FAILOVER`` flight-recorder event, bumps
  ``rendezvous_failovers``, and captures the promoted state's canonical
  bytes + digest so drills can assert zero lost commits.

A deposed primary's late writes are rejected with HTTP 409 — fencing is
enforced on the client-write path AND on the replication stream (see
``rendezvous.KVStoreServer.fence_check`` / ``apply_replicated``). A
standby that answers the stream with 409 deposes the shipping primary
(``KVStoreServer._ship_locked`` consults :attr:`ReplicationSender.fenced`),
so clients still pointed at it get 409 instead of silently-lost acks;
this module only elects and promotes, it never overrides a fence.

Run a control-plane member as a process (drills, remote standby hosts)::

    python -m horovod_tpu.run.replication --role primary \
        --port 7021 --wal /var/run/hvd/kv.wal --replicas host2:7021
    python -m horovod_tpu.run.replication --role standby \
        --port 7021 --wal /var/run/hvd/standby.wal \
        --primary host1:7021 --index 0

stdlib-only.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import queue
import threading
import time
from typing import Callable, Optional

from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.run.rendezvous import (
    _EPOCH_HEADER,
    _HMAC_HEADER,
    _PRIMARY_HEADER,
    _REPL_MODE_HEADER,
    _SEQ_HEADER,
    _digest,
    KVStoreServer,
    REPLICATE_PATH,
    SECRET_ENV,
    STATUS_PATH,
    format_endpoints,
    parse_endpoints,
)

logger = logging.getLogger("horovod_tpu.replication")

#: how many standbys ``horovodrun``/``run()`` should launch (flag
#: ``--kv-standbys`` overrides)
REPLICAS_ENV = "HOROVOD_KV_REPLICAS"

#: standbys that must acknowledge a record before the mutation is acked
#: (append-before-ack); endpoints beyond the quorum stream asynchronously
QUORUM_ENV = "HOROVOD_KV_REPLICATION_QUORUM"

#: primary lease (seconds): a standby promotes only after this long
#: without a healthy ``/-/status`` answer from the primary
LEASE_ENV = "HOROVOD_KV_REPLICA_LEASE"

#: per-shipment socket timeout (seconds); a standby slower than this is
#: detached rather than stalling every primary mutation behind it
TIMEOUT_ENV = "HOROVOD_KV_REPLICATION_TIMEOUT"


def replication_quorum() -> int:
    return int(os.environ.get(QUORUM_ENV, "1"))


def replica_lease() -> float:
    return float(os.environ.get(LEASE_ENV, "5.0"))


def replication_timeout() -> float:
    return float(os.environ.get(TIMEOUT_ENV, "5.0"))


class ReplicationFencedError(RuntimeError):
    """A standby answered the replication stream with 409: it has adopted
    a fencing epoch NEWER than this primary's — this primary is deposed
    and its shipments are the "late writes" the fence exists to stop."""


class _Endpoint:
    __slots__ = ("host", "port", "acked", "detached", "fenced",
                 "fenced_epoch", "queue", "thread")

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = int(port)
        self.acked = 0
        self.detached = False
        self.fenced = False
        self.fenced_epoch = 0  # the newer epoch the 409 answered with
        self.queue: "queue.Queue" = queue.Queue()
        self.thread: Optional[threading.Thread] = None

    def __repr__(self):
        return f"{self.host}:{self.port}"


class ReplicationSender:
    """Ships WAL records from a primary to its standbys.

    :meth:`ship` runs under the primary's store lock (the
    append-before-ack point): every record goes through a per-endpoint
    FIFO queue (strict delivery order per standby, even across sync/async
    reshuffles when a laggard detaches), and the mutation is not
    acknowledged until `quorum` live endpoints have accepted the record —
    the sync wait blocks on those endpoints' queue drains. The remaining
    endpoints receive the same stream asynchronously. ``lag()`` (and the
    ``rendezvous_replication_lag_entries`` gauge) reports the worst
    ``shipped - acked`` gap across non-fenced endpoints, detached ones
    included: a detached standby is an infinitely-lagging one, and the
    gauge is how an operator sees it."""

    def __init__(self, endpoints, secret: Optional[str] = None,
                 quorum: Optional[int] = None,
                 timeout: Optional[float] = None,
                 primary_hint: str = ""):
        self._endpoints = [_Endpoint(h, p) for h, p in endpoints]
        self._secret = secret if secret is not None else os.environ.get(
            SECRET_ENV, "")
        self._quorum = quorum if quorum is not None else replication_quorum()
        self._timeout = (
            timeout if timeout is not None else replication_timeout())
        self._primary_hint = primary_hint
        self._seq = 0
        self._closed = False
        for ep in self._endpoints:
            ep.thread = threading.Thread(
                target=self._drain, args=(ep,),
                name=f"hvd-kv-repl-{ep.host}:{ep.port}", daemon=True)
            ep.thread.start()

    @property
    def seq(self) -> int:
        """Records shipped so far (the stream's sequence counter)."""
        return self._seq

    @property
    def fenced(self) -> bool:
        """True once any standby has fenced this primary's stream."""
        return any(ep.fenced for ep in self._endpoints)

    @property
    def fenced_epoch(self) -> int:
        """Highest fencing epoch any 409 answered the stream with — the
        regime evidence ``KVStoreServer._ship_locked`` deposes on."""
        return max(
            (ep.fenced_epoch for ep in self._endpoints if ep.fenced),
            default=0)

    def endpoints(self) -> list:
        return [(ep.host, ep.port) for ep in self._endpoints]

    def _post(self, ep: _Endpoint, payload: bytes, epoch: int, seq: int,
              mode: str) -> None:
        headers = {
            _EPOCH_HEADER: str(epoch),
            _SEQ_HEADER: str(seq),
            _REPL_MODE_HEADER: mode,
        }
        if self._primary_hint:
            headers[_PRIMARY_HEADER] = self._primary_hint
        if self._secret:
            headers[_HMAC_HEADER] = _digest(self._secret, payload)
        c = http.client.HTTPConnection(
            ep.host, ep.port, timeout=self._timeout)
        try:
            c.request("POST", REPLICATE_PATH, body=payload, headers=headers)
            r = c.getresponse()
            body = r.read()
            if r.status == 409:
                ep.fenced = True
                try:
                    ep.fenced_epoch = max(
                        ep.fenced_epoch,
                        int(r.getheader(_EPOCH_HEADER) or 0))
                except ValueError:
                    pass
                raise ReplicationFencedError(
                    f"standby {ep} fenced this primary: "
                    f"{body.decode('utf-8', 'replace')}")
            if r.status != 200:
                raise RuntimeError(f"replicate to {ep}: HTTP {r.status}")
            ep.acked = max(ep.acked, seq)
        finally:
            c.close()

    def _detach(self, ep: _Endpoint, why: BaseException) -> None:
        logger.warning(
            "replication to standby %s failed (%s); detaching — it will "
            "need a snapshot re-bootstrap to rejoin", ep, why)
        ep.detached = True

    def _drain(self, ep: _Endpoint) -> None:
        while True:
            item = ep.queue.get()
            if item is None:
                return
            data, epoch, seq, done = item
            try:
                if not ep.detached and not ep.fenced:
                    self._post(ep, data, epoch, seq, "append")
                    self._update_lag_gauge()
            except ReplicationFencedError as e:
                logger.warning("replication: %s", e)
            except Exception as e:
                self._detach(ep, e)
            finally:
                # set unconditionally (detached/fenced/failed included)
                # so a sync waiter in ship() never hangs on this record
                done.set()

    def ship(self, data: bytes, epoch: int = 0) -> None:
        """Ship one WAL record. Called under the primary's store lock —
        returning IS the acknowledgement, so the sync quorum blocks
        here. Every record is routed through its endpoint's FIFO queue
        (an endpoint promoted into the sync set after a laggard detaches
        must flush its backlog first — an inline send would overtake the
        queued older records and reorder the stream on that standby);
        "sync" means waiting on the drain thread's completion event,
        walking down the endpoint list until the quorum is met. A fenced
        standby (409) marks this primary deposed-in-fact; the shipment
        is dropped and ``KVStoreServer._ship_locked`` deposes the
        server."""
        if self._closed:
            return
        self._seq += 1
        seq = self._seq
        entries = []
        for ep in self._endpoints:
            if ep.detached or ep.fenced:
                continue
            done = threading.Event()
            depth = ep.queue.qsize()
            ep.queue.put((data, epoch, seq, done))
            entries.append((ep, done, depth))
        synced = 0
        for ep, done, depth in entries:
            if synced >= self._quorum:
                break
            # the drain thread bounds each queued item by the socket
            # timeout, so this wait terminates; an endpoint whose backlog
            # cannot flush in time is a laggard — detach it and walk on
            # to the next endpoint for the quorum
            if not done.wait(self._timeout * (depth + 2)):
                self._detach(ep, TimeoutError(
                    f"backlog of {depth} queued records did not flush "
                    f"within {self._timeout * (depth + 2):.1f}s"))
                continue
            if ep.acked >= seq and not ep.detached and not ep.fenced:
                synced += 1
        self._update_lag_gauge()

    def bootstrap(self, payload: bytes, epoch: int = 0) -> None:
        """Snapshot-bootstrap every attached standby: the payload (the
        primary's canonical state records) REPLACES the standby's state
        and truncates its shipped WAL, after which the append stream is
        exact. Called under the primary's store lock by
        ``KVStoreServer.attach_replicator`` so no mutation can slip
        between the snapshot and the first shipped record."""
        for ep in self._endpoints:
            if ep.detached or ep.fenced:
                continue
            try:
                self._post(ep, payload, epoch, self._seq, "snapshot")
                ep.acked = max(ep.acked, self._seq)
            except Exception as e:
                self._detach(ep, e)
        self._update_lag_gauge()

    def lag(self) -> int:
        """Worst ``shipped - acked`` gap across non-fenced endpoints
        (detached included — that is the divergence the gauge exists to
        surface)."""
        lags = [self._seq - ep.acked
                for ep in self._endpoints if not ep.fenced]
        return max(lags) if lags else 0

    def _update_lag_gauge(self) -> None:
        if _metrics.enabled():
            _metrics.gauge(
                "rendezvous_replication_lag_entries",
                help="worst standby lag behind the primary's WAL stream "
                     "(entries shipped but not acknowledged)",
            ).set(float(self.lag()))

    def close(self) -> None:
        self._closed = True
        for ep in self._endpoints:
            ep.queue.put(None)
        for ep in self._endpoints:
            if ep.thread is not None:
                ep.thread.join(timeout=2)


class PromotionResult:
    """What :func:`promote` hands back: the new regime's epoch plus the
    canonical state bytes/digest at promotion time — the drill's
    zero-lost-commits evidence."""

    __slots__ = ("epoch", "digest", "state")

    def __init__(self, epoch: int, digest: str, state: bytes):
        self.epoch = epoch
        self.digest = digest
        self.state = state


def promote(kv: KVStoreServer, reason: str = "") -> PromotionResult:
    """Promote a standby to primary, observably: run the mechanical
    promotion (``KVStoreServer.promote()``), record the ``FAILOVER``
    flight event, bump ``rendezvous_failovers``, and capture the promoted
    state's canonical bytes + sha256 digest. Raises (naming the lock
    holder) if a live primary still owns the WAL lock — promotion is
    atomic or not at all."""
    epoch = kv.promote()
    state = kv.state_records()
    import hashlib

    digest = hashlib.sha256(state).hexdigest()
    if _metrics.enabled():
        _metrics.counter(
            "rendezvous_failovers",
            help="standby promotions to control-plane primary",
        ).inc()
    try:
        from horovod_tpu.observability import flight as _flight

        _flight.record(
            "failover", epoch=epoch, reason=reason or "promotion",
            digest=digest, keys=len(kv.live_keys()))
    except Exception as e:  # observability must not block the promotion
        logger.debug("FAILOVER flight event skipped: %s", e)
    logger.warning(
        "KV standby promoted to primary (fencing epoch %d, state %s%s)",
        epoch, digest[:12], f"; reason: {reason}" if reason else "")
    return PromotionResult(epoch=epoch, digest=digest, state=state)


def status_of(host: str, port: int, secret: Optional[str] = None,
              timeout: float = 2.0) -> Optional[dict]:
    """One ``GET /-/status`` probe → the status dict, or None when the
    server is unreachable/unhealthy (the monitor's liveness signal)."""
    secret = secret if secret is not None else os.environ.get(
        SECRET_ENV, "")
    headers = {}
    if secret:
        headers[_HMAC_HEADER] = _digest(secret, b"")
    c = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        c.request("GET", STATUS_PATH, headers=headers)
        r = c.getresponse()
        body = r.read()
        if r.status != 200:
            return None
        return json.loads(body)
    except (OSError, http.client.HTTPException, ValueError):
        return None
    finally:
        c.close()


class FailoverMonitor(threading.Thread):
    """Lease-based election, run by each standby.

    Probes the primary's ``/-/status`` every ``poll`` seconds; while the
    primary answers as a primary the lease keeps renewing. Once the lease
    (`lease`, env ``HOROVOD_KV_REPLICA_LEASE``) expires, the election
    rule is *lowest-ready standby wins*: this standby (at `index`) defers
    as long as any lower-index peer still answers its status probe as a
    standby — and promotes itself otherwise. A peer that already answers
    as ``primary`` ends the election (the monitor keeps watching the NEW
    primary). Promotion failure (e.g. a live primary still holds the WAL
    lock — the lease expired on a slow network, not a dead process) logs
    and re-enters the wait instead of split-braining."""

    def __init__(self, kv: KVStoreServer, primary, *, peers=(),
                 index: int = 0, lease: Optional[float] = None,
                 poll: Optional[float] = None,
                 secret: Optional[str] = None,
                 on_promote: Optional[Callable] = None):
        super().__init__(name="hvd-kv-failover", daemon=True)
        self._kv = kv
        self._primary = (primary[0], int(primary[1]))
        self._peers = [(h, int(p)) for h, p in peers]
        self._index = index
        self._lease = lease if lease is not None else replica_lease()
        self._poll = poll if poll is not None else max(self._lease / 4, 0.05)
        self._secret = secret
        self._on_promote = on_promote
        # NOT named _stop: that would shadow threading.Thread's internal
        # _stop() and break join()
        self._halt = threading.Event()
        self.result: Optional[PromotionResult] = None

    def stop(self) -> None:
        self._halt.set()
        self.join(timeout=5)

    def run(self) -> None:
        last_ok = time.monotonic()
        while not self._halt.wait(self._poll):
            st = status_of(*self._primary, secret=self._secret,
                           timeout=max(self._poll, 0.25))
            if st is not None and st.get("role") == "primary":
                last_ok = time.monotonic()
                # track the primary's regime: a standby that has seen
                # epoch N can spot a stale regime the moment it answers
                self._watch_primary(st)
                continue
            if time.monotonic() - last_ok < self._lease:
                continue
            # lease expired — election: lowest READY standby wins
            if self._defer_to_lower_peer():
                continue
            try:
                self.result = promote(
                    self._kv,
                    reason=f"primary {self._primary[0]}:"
                           f"{self._primary[1]} lease expired "
                           f"({self._lease:.2f}s)")
            except RuntimeError as e:
                logger.warning(
                    "promotion deferred: %s (re-entering lease wait)", e)
                last_ok = time.monotonic()
                continue
            if self._on_promote is not None:
                try:
                    self._on_promote(self.result)
                except Exception as e:
                    logger.warning("on_promote callback failed: %s", e)
            return  # this server is the primary now; election is over

    def _watch_primary(self, st: dict) -> None:
        del st  # liveness is the signal; epoch travels in the stream

    def _defer_to_lower_peer(self) -> bool:
        """True when a lower-index peer should win this election: it is
        reachable and still a standby (it will promote), or it already
        promoted (the election is over and we stay a standby)."""
        for i, (host, port) in enumerate(self._peers):
            if i >= self._index:
                continue
            st = status_of(host, port, secret=self._secret,
                           timeout=max(self._poll, 0.25))
            if st is None:
                continue  # that peer is as dead as the primary
            if st.get("role") in ("standby", "primary"):
                return True
        return False


def spawn_local_standbys(n: int, secret: Optional[str] = None,
                         wal_dir: Optional[str] = None) -> list:
    """`n` in-process warm standbys (each with its own shipped-WAL file
    under `wal_dir` when given), started and ready to receive the
    replication stream. The launcher's local spelling of
    ``--kv-standbys``; remote hosts run the CLI below instead."""
    standbys = []
    for i in range(n):
        wal = (os.path.join(wal_dir, f"kv-standby-{i}.wal")
               if wal_dir else None)
        s = KVStoreServer(secret=secret, wal_path=wal, role="standby")
        s.start()
        standbys.append(s)
    return standbys


def main(argv=None) -> int:
    """Run one control-plane member as a process — the remote-standby
    launch target and the SIGKILL-drill victim. Prints
    ``KV <role> ready on port <port>`` once serving."""
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m horovod_tpu.run.replication",
        description="Run a rendezvous KV control-plane member "
                    "(primary or warm standby).")
    p.add_argument("--role", choices=["primary", "standby"], required=True)
    p.add_argument("--port", type=int, default=0,
                   help="bind port (0 = ephemeral)")
    p.add_argument("--wal", default=None, help="write-ahead log path")
    p.add_argument("--replicas", default="",
                   help="primary: standby host:port list to ship to")
    p.add_argument("--quorum", type=int, default=None,
                   help="primary: sync replication quorum")
    p.add_argument("--advertise", default="127.0.0.1",
                   help="host to advertise in redirects/hints")
    p.add_argument("--primary", default=None,
                   help="standby: current primary host:port to monitor")
    p.add_argument("--peers", default="",
                   help="standby: other standbys host:port list "
                        "(election precedence order)")
    p.add_argument("--index", type=int, default=0,
                   help="standby: this standby's election index")
    p.add_argument("--lease", type=float, default=None,
                   help="standby: primary lease seconds")
    args = p.parse_args(argv)

    secret = os.environ.get(SECRET_ENV, "")
    kv = KVStoreServer(port=args.port, secret=secret or None,
                       wal_path=args.wal, role=args.role)
    kv.start()
    print(f"KV {args.role} ready on port {kv.port}", flush=True)

    monitor = None
    sender = None
    if args.role == "primary" and args.replicas:
        sender = ReplicationSender(
            parse_endpoints(args.replicas), secret=secret,
            quorum=args.quorum,
            primary_hint=f"{args.advertise}:{kv.port}")
        kv.attach_replicator(sender)
        logger.info("replicating to %s",
                    format_endpoints(sender.endpoints()))
    if args.role == "standby" and args.primary:
        monitor = FailoverMonitor(
            kv, parse_endpoints(args.primary)[0],
            peers=parse_endpoints(args.peers) if args.peers else (),
            index=args.index, lease=args.lease, secret=secret)
        monitor.start()

    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        if monitor is not None:
            monitor.stop()
        if sender is not None:
            sender.close()
        kv.close()
    return 0


if __name__ == "__main__":  # pragma: no cover - process entry
    raise SystemExit(main())
