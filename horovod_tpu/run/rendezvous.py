"""HTTP rendezvous / key-value store server.

Reference: ``horovod/run/http/http_server.py`` — the launcher runs a small
HTTP KV server; ranks PUT/GET scoped keys during bootstrap, and the
programmatic ``run()`` API ships the pickled function down and results back
through it (``KVStoreServer``, reference ``http_server.py:210-250``).

On TPU the data-plane rendezvous is ``jax.distributed`` (coordinator
address), so this store's remaining jobs are (a) the ``run()`` function/result
shuttle, (b) generic scoped KV for launcher extensions, and (c) the elastic
membership plane: **heartbeat-scoped keys with a TTL**. A key PUT with a TTL
(``put(key, value, ttl=...)`` / the ``X-Hvd-TTL`` header) expires once its
writer stops refreshing it; expiry leaves a *tombstone*, so readers can tell
"never written" (404) from "written by a rank that since died" (410 Gone).
``wait_for`` consults the tombstones and the heartbeat namespace to surface
:class:`DeadRankError` carrying the dead rank's id *immediately* instead of
burning its whole deadline on a key whose writer can never write it.

Values are opaque bytes; a shared-secret HMAC header authenticates requests
(reference ``run/common/util/{secret,network}.py:49-83``).

**Durability** (the serving handoff leans on it): with ``wal_path`` the
server appends every mutation to a write-ahead log *before* acknowledging
it and :meth:`KVStoreServer.restart` / a fresh server on the same path
replays it — a KV restart no longer loses elastic membership or published
weight generations. TTL leases are re-armed for their full duration on
replay (a live writer refreshes them anyway; a dead one re-expires).
``sweep_interval`` arms a background sweep so TTL expiry and tombstone GC
happen on a timer, not only on access — bounding memory on long elastic
runs independent of traffic patterns (``rendezvous_keys_swept``).

**High availability** (:mod:`horovod_tpu.run.replication`): a primary
server ships every WAL record to warm standbys before acknowledging the
mutation (quorum 1 by default), every mutation carries a monotone
**fencing epoch** persisted in the WAL (``fe`` field), and a deposed
primary — one that has seen evidence of a newer epoch — answers every
write with **HTTP 409** instead of silently applying it. Standbys serve
reads, answer writes with a 307 redirect to the primary, and accept the
replication stream on ``/-/replicate``; promotion (``replication.promote``)
acquires the WAL lock, replays the shipped WAL, and re-arms TTL leases
exactly like :meth:`KVStoreServer.restart`. :class:`KVStoreClient` takes a
multi-endpoint list (``HVD_RUN_KV_ADDRS``) and fails over between them
under the existing retry scope without resetting ``wait_for`` deadlines,
echoing the highest fencing epoch it has seen so stale primaries are
detected on read AND fenced on write.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import http.client
import http.server
import json
import os
import logging
import re
import threading
import time
from typing import Optional

from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.resilience import chaos as _chaos, retry as _retry

logger = logging.getLogger("horovod_tpu.rendezvous")

SECRET_ENV = "HVD_RUN_SECRET"
_HMAC_HEADER = "X-Hvd-Digest"
_TTL_HEADER = "X-Hvd-TTL"
_TOMBSTONE_HEADER = "X-Hvd-Tombstone"
#: fencing epoch: echoed on every response; clients send their highest
#: seen value on writes so a deposed primary fences (409) instead of
#: silently applying a stale regime's mutation
_EPOCH_HEADER = "X-Hvd-Fencing-Epoch"
_ROLE_HEADER = "X-Hvd-Role"
#: ``host:port`` hint a standby attaches to its 307 write redirects
_PRIMARY_HEADER = "X-Hvd-Primary"
#: replication stream sequence number (count of records shipped so far)
_SEQ_HEADER = "X-Hvd-Repl-Seq"
#: ``snapshot`` (bootstrap: replace state) or ``append`` (incremental)
_REPL_MODE_HEADER = "X-Hvd-Repl-Mode"

#: reserved routes (``-`` cannot collide with a rank-owned key)
REPLICATE_PATH = "/-/replicate"
STATUS_PATH = "/-/status"

#: multi-endpoint client wiring: comma-separated ``host:port`` list, the
#: primary first then the standbys (``kv_client_from_env`` prefers this
#: over the single-endpoint ``HVD_RUN_KV_ADDR``/``HVD_RUN_KV_PORT`` pair)
ADDRS_ENV = "HVD_RUN_KV_ADDRS"

#: fencing escape hatch: ``HOROVOD_KV_FENCING=0`` disables the 409
#: rejection path (debugging only — a disabled fence means a deposed
#: primary's late writes CAN be applied)
FENCING_ENV = "HOROVOD_KV_FENCING"


def fencing_enabled() -> bool:
    return os.environ.get(FENCING_ENV, "1") != "0"

#: reserved GET path answering the server's ``time.monotonic()`` — the
#: shared reference clock every rank's offset is estimated against
#: (:mod:`horovod_tpu.observability.clock`); ``-`` cannot collide with a
#: rank-owned key (see ``_OWNER_RE``)
CLOCK_PATH = "/-/clock"

#: default TTL for heartbeat-scoped keys (seconds); the elastic layer's
#: failure-detection horizon. Tests use ~0.2s.
HEARTBEAT_TTL_ENV = "HOROVOD_ELASTIC_HEARTBEAT_TTL"

#: background sweep cadence (seconds; 0 = lazy sweep on access only)
SWEEP_INTERVAL_ENV = "HOROVOD_KV_SWEEP_INTERVAL"

#: tombstone retention (seconds) before the background sweep drops them;
#: must comfortably exceed the slowest reader's poll interval — a dropped
#: tombstone makes a dead key look never-written (404 instead of 410)
TOMBSTONE_TTL_ENV = "HOROVOD_KV_TOMBSTONE_TTL"


def default_heartbeat_ttl() -> float:
    return float(os.environ.get(HEARTBEAT_TTL_ENV, "10.0"))


class DeadRankError(RuntimeError):
    """A KV wait cannot complete because the rank that owns the awaited key
    is dead (its heartbeat TTL expired or it was explicitly tombstoned).
    ``rank`` is the dead rank's id (or -1 when unattributable)."""

    def __init__(self, rank: int, key: str = ""):
        self.rank = int(rank)
        self.key = key
        super().__init__(
            f"rank {rank} is dead (heartbeat expired)"
            + (f"; awaited key {key}" if key else "")
        )


class FencedError(RuntimeError):
    """A KV write was rejected with HTTP 409: the target server is deposed
    (a newer fencing epoch exists) and must never silently apply a stale
    regime's mutation. ``epoch`` is the highest epoch the client has
    observed — the regime the write should be re-issued under."""

    def __init__(self, msg: str, epoch: int = -1):
        self.epoch = int(epoch)
        super().__init__(msg)


#: trailing rank id in a scoped key: ``.../ack/3`` or ``.../result_3``
_OWNER_RE = re.compile(r"(?:/|_)(\d+)$")


def _key_owner(key: str) -> Optional[int]:
    m = _OWNER_RE.search(key)
    return int(m.group(1)) if m else None

#: failures worth retrying on the KV path. ``OSError`` deliberately covers
#: the whole startup-race family (ConnectionRefusedError/ResetError, and
#: socket.timeout, all OSError subclasses on py3.10+) — retrying an
#: occasional non-transient OSError is bounded by the policy's deadline,
#: while a missed transient one kills the job. Torn HTTP exchanges surface
#: as ``HTTPException``; chaos injections as ``TransientError``.
TRANSIENT_KV_ERRORS = (
    OSError,
    http.client.HTTPException,
    _retry.TransientError,
)


def make_secret() -> str:
    return os.urandom(16).hex()


def _digest(secret: str, body: bytes) -> str:
    return hmac.new(secret.encode(), body, hashlib.sha256).hexdigest()


class _Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _check_auth(self, body: bytes) -> bool:
        secret = self.server._secret  # type: ignore[attr-defined]
        if not secret:
            return True
        given = self.headers.get(_HMAC_HEADER, "")
        return hmac.compare_digest(given, _digest(secret, body))

    def _reply(self, code: int, body: bytes = b"", headers=None):
        self.send_response(code)
        kv = getattr(self.server, "_kv", None)
        if kv is not None:
            # fencing-epoch echo on EVERY response: readers compare it to
            # the highest epoch they have seen and walk away from a stale
            # primary instead of trusting its pre-failover view
            self.send_header(_EPOCH_HEADER, str(kv.fencing_epoch))
            self.send_header(_ROLE_HEADER, kv.role)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def _gate_mutation(self) -> bool:
        """Standby redirect + fencing check shared by PUT/DELETE. True when
        the mutation may proceed; False after a 307/409 reply."""
        kv = self.server._kv  # type: ignore[attr-defined]
        if kv.role == "standby":
            hint = kv.primary_hint
            self._reply(
                307, b"standby: redirect writes to the primary",
                headers={_PRIMARY_HEADER: hint} if hint else None,
            )
            return False
        code = kv.fence_check(self.headers.get(_EPOCH_HEADER))
        if code is not None:
            self._reply(code, b"write fenced: this server is deposed")
            return False
        return True

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._check_auth(body):
            return self._reply(403)
        if not self._gate_mutation():
            return
        ttl = self.headers.get(_TTL_HEADER)
        self.server._kv.put(  # type: ignore[attr-defined]
            self.path, body, ttl=float(ttl) if ttl is not None else None
        )
        self._reply(200)

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._check_auth(body):
            return self._reply(403)
        if self.path != REPLICATE_PATH:
            return self._reply(404)
        code, reply = self.server._kv.apply_replicated(  # type: ignore[attr-defined]
            body,
            epoch=int(self.headers.get(_EPOCH_HEADER, 0)),
            seq=int(self.headers.get(_SEQ_HEADER, 0)),
            mode=self.headers.get(_REPL_MODE_HEADER, "append"),
            primary=self.headers.get(_PRIMARY_HEADER),
        )
        self._reply(code, reply)

    def do_GET(self):
        if not self._check_auth(b""):
            return self._reply(403)
        if self.path == CLOCK_PATH:
            # read the clock as late as possible: the client's midpoint
            # estimate charges everything between its t0/t1 to the RTT
            return self._reply(200, repr(time.monotonic()).encode())
        if self.path == STATUS_PATH:
            return self._reply(
                200, json.dumps(self.server._kv.status()).encode()  # type: ignore[attr-defined]
            )
        val, dead = self.server._kv._get_with_liveness(self.path)  # type: ignore[attr-defined]
        if val is None:
            if dead:
                owner = _key_owner(self.path)
                return self._reply(
                    410, str(owner if owner is not None else -1).encode()
                )
            return self._reply(404)
        self._reply(200, val)

    def do_DELETE(self):
        if not self._check_auth(b""):
            return self._reply(403)
        if not self._gate_mutation():
            return
        tombstone = self.headers.get(_TOMBSTONE_HEADER) == "1"
        existed = self.server._kv.delete(  # type: ignore[attr-defined]
            self.path, tombstone=tombstone
        )
        self._reply(200 if existed else 404)

    def log_message(self, *a):  # quiet
        pass


class KVStoreServer:
    """Threaded KV server; start/stop + blocking wait for keys.

    Beyond plain KV, keys can carry a **TTL** (heartbeat-scoped keys): an
    expired key is removed from the store and *tombstoned*, so
    :meth:`wait_for` (and the HTTP GET path, which answers 410 Gone) can
    attribute "this key's writer died" instead of timing out. Expiry is
    swept lazily under the store lock on every access; `sweep_interval`
    (env ``HOROVOD_KV_SWEEP_INTERVAL``, 0 = off) additionally arms a
    background timer that sweeps expiry AND drops tombstones older than
    `tombstone_ttl` (env ``HOROVOD_KV_TOMBSTONE_TTL``), so memory stays
    bounded on long runs whose keys nobody reads.

    With `wal_path` every mutation is appended to a write-ahead log before
    it is acknowledged; a fresh server on the same path — or
    :meth:`restart` in place — replays it, so membership and published
    weight generations survive a KV process crash. The log is compacted to
    the live state on every open.

    With ``role="standby"`` the server is a warm replica: it opens the
    shipped WAL **read-only** for replay — no ``.lock`` steal, no
    compaction — serves reads, answers writes with a 307 redirect to the
    primary, and applies the primary's replication stream
    (:meth:`apply_replicated`). Replicated records are persisted to the
    standby's WAL only once it *owns* the ``.lock``; a standby pointed at
    a live primary's WAL path (shared filesystem) keeps the stream in
    memory and lets the primary's own log be the durable copy.
    ``replication.promote`` turns it into the
    primary. Every mutation is stamped with the server's **fencing epoch**
    (persisted in the WAL, so a restarted server keeps its regime);
    evidence of a newer epoch — a client write or a replication record
    carrying one — deposes the server, and a deposed server answers every
    write with HTTP 409, never silently applying it."""

    def __init__(self, port: int = 0, secret: Optional[str] = None,
                 wal_path: Optional[str] = None,
                 sweep_interval: Optional[float] = None,
                 tombstone_ttl: Optional[float] = None,
                 role: str = "primary",
                 fencing_epoch: int = 0):
        if role not in ("primary", "standby"):
            raise ValueError(f"role must be primary|standby, got {role!r}")
        self._store: dict = {}
        self._ttl: dict = {}  # key -> (expiry_monotonic, lease_seconds)
        self._dead: dict = {}  # tombstones: key -> time of death
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._secret = secret or ""
        self._wal_path = wal_path
        self._wal = None
        self._wal_records = 0
        self._role = role
        self._fencing_epoch = int(fencing_epoch)
        self._deposed = False
        self._applied_seq = 0  # replication records applied (standby side)
        self._primary_hint = ""  # host:port the replication stream names
        self._replicator = None  # ReplicationSender (primary side)
        self._sweep_interval = (
            sweep_interval
            if sweep_interval is not None
            else float(os.environ.get(SWEEP_INTERVAL_ENV, "0"))
        )
        self._tombstone_ttl = (
            tombstone_ttl
            if tombstone_ttl is not None
            else float(os.environ.get(TOMBSTONE_TTL_ENV, "300"))
        )
        self._sweep_stop = threading.Event()
        self._sweeper: Optional[threading.Thread] = None
        self._thread: Optional[threading.Thread] = None
        self._wal_lock = None
        if wal_path is not None:
            if role == "primary":
                # exclusive-lock the WAL BEFORE replay/compaction: a second
                # server on the same path (operator error, a restart racing
                # the old process) would otherwise compact the live
                # server's log out from under it — observed as silently
                # truncated generations when the loser's constructor ran
                # before its port bind failed
                self._acquire_wal_lock()
            # a standby replays WITHOUT the lock (read-only open): it must
            # be able to warm up from a shipped WAL while the primary on a
            # shared filesystem still owns the live log
            self._replay_wal()
        if role == "primary":
            self._open_wal()
        # standby: no compaction, no append handle — the shipped WAL is
        # opened for append lazily on the first replicated record, and
        # only after claiming the .lock, so a replica sharing a live
        # primary's path never writes the primary's file
        self._httpd = http.server.ThreadingHTTPServer(("0.0.0.0", port), _Handler)
        self._httpd._secret = self._secret  # type: ignore[attr-defined]
        self._httpd._kv = self  # type: ignore[attr-defined]
        self._start_sweeper()
        self._set_ha_gauges()

    # ------------------------------------------------------ write-ahead log

    def _acquire_wal_lock(self) -> None:
        """Hold ``<wal_path>.lock`` exclusively for this server's lifetime
        (kept across :meth:`restart`, released by :meth:`close`). Raises
        when another live server owns the WAL; the error names the holder
        from the lock file's ``role=... fe=... pid=...`` stamp, so a
        promotion that raced a still-live primary reads as exactly that.
        Idempotent: a standby that already claimed the lock (to persist
        the shipped stream) keeps its handle through promotion."""
        if self._wal_lock is not None:
            return
        try:
            import fcntl
        except ImportError:  # pragma: no cover - non-POSIX
            return
        fd = os.fdopen(
            os.open(self._wal_path + ".lock",
                    os.O_RDWR | os.O_CREAT, 0o644),
            "r+b",
        )
        try:
            fcntl.flock(fd.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            try:
                holder = fd.read(256).decode("utf-8", "replace").strip()
            except Exception:
                holder = ""
            fd.close()
            raise RuntimeError(
                f"WAL {self._wal_path} is locked by another live "
                "KVStoreServer"
                + (f" ({holder})" if holder else "")
                + "; refusing to replay/compact a log that is "
                "still being written"
            ) from None
        self._wal_lock = fd
        self._stamp_wal_lock()

    def _stamp_wal_lock(self) -> None:
        """Write ``role=<role> fe=<epoch> pid=<pid>`` into the held lock
        file — purely diagnostic, read by the loser of a lock race."""
        if self._wal_lock is None:
            return
        try:
            self._wal_lock.seek(0)
            self._wal_lock.truncate()
            self._wal_lock.write(
                f"role={self.role} fe={self._fencing_epoch} "
                f"pid={os.getpid()}\n".encode()
            )
            self._wal_lock.flush()
        except Exception as e:  # diagnostics must never fail serving
            logger.debug("WAL lock stamp failed: %s", e)

    def _release_wal_lock(self) -> None:
        if self._wal_lock is not None:
            try:
                self._wal_lock.close()  # closing drops the flock
            except Exception as e:
                logger.debug("WAL lock release failed: %s", e)
            self._wal_lock = None

    def _replay_wal(self) -> None:
        """Rebuild the in-memory store from the WAL. TTL leases are
        re-armed for their full duration (a live writer's next heartbeat
        refreshes them; a dead writer's lease re-expires and tombstones),
        tombstones are restored as of replay time."""
        if not os.path.exists(self._wal_path):
            return
        now = time.monotonic()
        replayed = 0
        with open(self._wal_path, "rb") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    break  # torn tail write: everything before it is good
                self._apply_record_locked(rec, now)
                replayed += 1
        if replayed and _metrics.enabled():
            _metrics.counter(
                "rendezvous_wal_replayed",
                help="WAL records replayed into a restarted KV store",
            ).inc(replayed)

    def _apply_record_locked(self, rec: dict, now: float) -> None:
        """Apply one WAL/replication record to the in-memory maps; caller
        holds the store lock (or is the single-threaded constructor). A
        record's ``fe`` field raises this server's fencing epoch — replay
        of a WAL written under epoch N restores epoch >= N, so a regime
        survives its server's restart ("fencing epoch pinned")."""
        op, k = rec.get("op"), rec.get("k")
        if op == "put":
            self._store[k] = base64.b64decode(rec["v"])
            if rec.get("ttl") is not None:
                lease = float(rec["ttl"])
                self._ttl[k] = (now + lease, lease)
            else:
                self._ttl.pop(k, None)
            self._dead.pop(k, None)
        elif op == "del":
            self._store.pop(k, None)
            self._ttl.pop(k, None)
            if rec.get("ts"):
                self._dead[k] = now
            else:
                self._dead.pop(k, None)
        elif op == "prune":
            for m in (self._store, self._ttl, self._dead):
                for kk in [kk for kk in m if kk.startswith(k)]:
                    del m[kk]
        # "epoch" records carry only the fe field (compaction marker)
        fe = rec.get("fe")
        if fe is not None and int(fe) > self._fencing_epoch:
            self._fencing_epoch = int(fe)

    def _open_wal(self) -> None:
        """(Re-)open the WAL compacted to the current live state: one put
        per surviving key + one tombstone record per death, instead of the
        full mutation history."""
        if self._wal_path is None:
            return
        tmp = self._wal_path + ".compact"
        with open(tmp, "wb") as f:
            n = 0
            for k, v in self._store.items():
                lease = self._ttl.get(k)
                f.write(_wal_record(
                    "put", k, v, ttl=lease[1] if lease else None))
                n += 1
            for k in self._dead:
                if k not in self._store:
                    f.write(_wal_record("del", k, tombstone=True))
                    n += 1
            if self._fencing_epoch > 0:
                # pin the regime: a fresh server replaying an otherwise
                # empty compacted log must still come up at this epoch
                f.write(_wal_record("epoch", "/", fe=self._fencing_epoch))
                n += 1
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._wal_path)
        self._wal = open(self._wal_path, "ab")
        self._wal_records = n
        self._update_wal_gauge()
        self._stamp_wal_lock()

    def _wal_append_locked(self, data: bytes) -> None:
        """Append one record; caller holds the store lock. A WAL write
        failure is fatal to durability, not to serving — log-and-continue
        would silently lose acknowledged writes, so let it raise."""
        if self._wal is None:
            return
        self._wal.write(data)
        self._wal.flush()
        self._wal_records += 1
        self._update_wal_gauge()

    def _update_wal_gauge(self) -> None:
        if self._wal is not None and _metrics.enabled():
            _metrics.gauge(
                "rendezvous_wal_records",
                help="records in the KV write-ahead log since last compact",
            ).set(self._wal_records)

    # --------------------------------------------------- HA / replication

    @property
    def role(self) -> str:
        """``primary`` / ``standby`` / ``deposed`` (a server that saw
        evidence of a newer fencing epoch and must not apply writes)."""
        return "deposed" if self._deposed else self._role

    @property
    def fencing_epoch(self) -> int:
        return self._fencing_epoch

    @property
    def primary_hint(self) -> str:
        """``host:port`` of the primary as named by the replication
        stream — attached to a standby's 307 write redirects."""
        return self._primary_hint

    @property
    def applied_seq(self) -> int:
        """Highest replication sequence number applied (standby side)."""
        return self._applied_seq

    def _set_ha_gauges(self) -> None:
        if not _metrics.enabled():
            return
        role_code = (
            2.0 if self._deposed
            else (0.0 if self._role == "primary" else 1.0)
        )
        _metrics.gauge(
            "rendezvous_role",
            help="control-plane role of this KV server "
                 "(0=primary, 1=standby, 2=deposed)",
        ).set(role_code)
        _metrics.gauge(
            "rendezvous_fencing_epoch",
            help="highest fencing epoch this KV server has adopted",
        ).set(float(self._fencing_epoch))

    def _depose_locked(self, newer_epoch: int) -> None:
        """Mark this server fenced: epoch `newer_epoch` (> ours) exists,
        so a newer primary was elected while we weren't looking. Every
        subsequent write is answered 409 — the "late writes from a
        deposed primary" hole is closed at the server, not only at the
        clients. Our own epoch is deliberately NOT bumped: readers
        comparing the echoed epoch against the newest they have seen must
        keep detecting this server as stale."""
        if not self._deposed:
            logger.warning(
                "KV server deposed: observed fencing epoch %d > own %d",
                newer_epoch, self._fencing_epoch,
            )
        self._deposed = True

    def fence_check(self, raw_epoch: Optional[str]) -> Optional[int]:
        """Gate one client mutation. `raw_epoch` is the client's echoed
        highest-seen-epoch header (string or None). Returns None when the
        write may proceed, else the HTTP status (409) to answer."""
        if not fencing_enabled():
            return None
        try:
            seen = int(raw_epoch) if raw_epoch else 0
        except (TypeError, ValueError):
            seen = 0
        deposed_now = False
        with self._lock:
            if seen > self._fencing_epoch:
                self._depose_locked(seen)
                deposed_now = True
            fenced = self._deposed
        if deposed_now:
            self._set_ha_gauges()
        return 409 if fenced else None

    def _try_own_wal(self) -> bool:
        """Best-effort exclusive claim on the WAL for standby-side
        persistence of the shipped stream. False when another live server
        owns it — the shared-filesystem configuration, where the standby's
        ``wal_path`` IS the primary's live log: writing there would
        truncate/interleave into a file the primary still appends to, so
        the standby keeps the stream in memory only (the owner's WAL is
        the durable copy, replayed at promotion once the lock is free)."""
        if self._wal_lock is not None:
            return True
        try:
            self._acquire_wal_lock()
            return True
        except RuntimeError:
            return False

    def _standby_wal_append_locked(self, data: bytes) -> None:
        """Persist one replicated record to the shipped WAL. The append
        handle opens lazily on the first record, and ONLY once this
        standby owns the ``.lock`` — a standby sharing the primary's WAL
        path must never write into the live log the primary still owns."""
        if self._wal_path is None:
            return
        if self._wal is None:
            if not self._try_own_wal():
                return
            self._wal = open(self._wal_path, "ab")
        self._wal.write(data)
        self._wal.flush()
        self._wal_records += 1
        self._update_wal_gauge()

    def apply_replicated(self, payload: bytes, *, epoch: int = 0,
                         seq: int = 0, mode: str = "append",
                         primary: Optional[str] = None):
        """Apply a shipped batch of WAL records (the ``/-/replicate``
        POST body). Fencing first: a batch whose epoch is BEHIND this
        server's is a deposed primary's late shipment — rejected with
        409, never applied; a batch AHEAD of a primary's own epoch is
        evidence this server lost an election it never saw — it deposes
        itself. A standby adopts the stream's epoch, applies the records
        under the store lock, and persists them to its shipped WAL.
        ``mode="snapshot"`` (bootstrap) replaces state and truncates the
        shipped WAL first. Returns ``(http_status, body)``."""
        with self._lock:
            if self._role == "primary" or self._deposed:
                if epoch > self._fencing_epoch:
                    self._depose_locked(epoch)
                return 409, (
                    f"not a standby (role={self.role}, "
                    f"fe={self._fencing_epoch})"
                ).encode()
            if fencing_enabled() and epoch < self._fencing_epoch:
                return 409, (
                    f"replication fenced: batch epoch {epoch} is behind "
                    f"fencing epoch {self._fencing_epoch}"
                ).encode()
            if epoch > self._fencing_epoch:
                self._fencing_epoch = epoch
            if primary:
                self._primary_hint = primary
            now = time.monotonic()
            if mode == "snapshot":
                self._store.clear()
                self._ttl.clear()
                self._dead.clear()
                # the snapshot defines the stream position: appends the
                # old stream already delivered are behind it by seq
                self._applied_seq = seq
                if self._wal is not None:
                    self._wal.close()
                    self._wal = None
                if self._wal_path is not None and self._try_own_wal():
                    # the snapshot replaces history: truncate OUR shipped
                    # log (a shared WAL still owned by a live primary is
                    # never touched — see _try_own_wal)
                    self._wal = open(self._wal_path, "wb")
                    self._wal_records = 0
            elif seq and seq <= self._applied_seq:
                # duplicate / reordered shipment (at-least-once delivery):
                # applying it would regress last-write-wins keys to stale
                # values — drop it idempotently
                return 200, str(self._applied_seq).encode()
            applied = 0
            for line in payload.splitlines():
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    break  # torn tail: same tolerance as replay
                self._apply_record_locked(rec, now)
                self._standby_wal_append_locked(line + b"\n")
                applied += 1
            if mode == "snapshot":
                pass  # position pinned to the snapshot's seq above
            elif seq:
                self._applied_seq = max(self._applied_seq, seq)
            else:
                self._applied_seq += applied
            self._cv.notify_all()
            result = 200, str(self._applied_seq).encode()
        self._set_ha_gauges()
        return result

    def _ship_locked(self, data: bytes) -> None:
        """Append-before-ack replication: the record reaches the quorum
        of standbys (or the sender detaches the laggard) before the
        mutation is acknowledged. Caller holds the store lock. A standby
        that fences the stream (409) is proof a newer regime exists —
        this server deposes itself on the spot, so clients still pointed
        here get 409 on their next write instead of HTTP 200 for commits
        the new regime will never see."""
        if self._replicator is None:
            return
        self._replicator.ship(data, epoch=self._fencing_epoch)
        if (fencing_enabled() and not self._deposed
                and self._replicator.fenced):
            self._depose_locked(max(
                self._replicator.fenced_epoch, self._fencing_epoch + 1))
            self._set_ha_gauges()

    def attach_replicator(self, sender) -> None:
        """Wire a :class:`horovod_tpu.run.replication.ReplicationSender`:
        the standbys are bootstrapped with a snapshot of the current
        state under the store lock (no mutation can slip between the
        snapshot and the first shipped record), then every subsequent
        mutation ships before it is acknowledged."""
        with self._lock:
            self._replicator = sender
            sender.bootstrap(
                b"".join(self._state_records_locked()),
                epoch=self._fencing_epoch,
            )

    def _state_records_locked(self) -> list:
        recs = []
        for k in sorted(self._store):
            lease = self._ttl.get(k)
            recs.append(_wal_record(
                "put", k, self._store[k],
                ttl=lease[1] if lease else None))
        for k in sorted(self._dead):
            if k not in self._store:
                recs.append(_wal_record("del", k, tombstone=True))
        return recs

    def state_records(self) -> bytes:
        """Canonical serialization of the live state: sorted puts + sorted
        tombstones, WITHOUT epoch stamps — comparable across regimes. The
        failover drill compares a promoted standby's bytes against what
        the dead primary's WAL replays to; byte identity means zero lost
        commits."""
        with self._lock:
            return b"".join(self._state_records_locked())

    def state_digest(self) -> str:
        return hashlib.sha256(self.state_records()).hexdigest()

    def status(self) -> dict:
        """The ``GET /-/status`` body — what the failover monitor and the
        launcher read to pick a promotion candidate."""
        with self._lock:
            return {
                "role": self.role,
                "fencing_epoch": self._fencing_epoch,
                "applied_seq": self._applied_seq,
                "keys": len(self._store),
                "wal_records": self._wal_records,
                "primary_hint": self._primary_hint,
            }

    def promote(self) -> int:
        """Standby → primary: the :meth:`restart` path wearing a new
        regime. Acquires the WAL ``.lock`` atomically (raises, naming the
        holder, if a live primary still owns it), replays the shipped WAL
        with TTL leases re-armed for their full duration, bumps the
        fencing epoch past everything the log has seen, and starts
        compacting + appending as the new write path. A WAL-less standby
        promotes in place from its replicated in-memory state (leases
        re-armed the same way) instead of clearing it. Returns the new
        fencing epoch. Observability (the FAILOVER flight event and the
        ``rendezvous_failovers`` counter) lives in
        :func:`horovod_tpu.run.replication.promote`, which wraps this."""
        if self.role != "standby":
            raise RuntimeError(
                f"promote(): role is {self.role}, not standby")
        if self._wal is not None:  # the standby's lazy append handle
            self._wal.close()
            self._wal = None
        if self._wal_path is not None:
            self._acquire_wal_lock()
        with self._lock:
            if self._wal_path is not None:
                self._store.clear()
                self._ttl.clear()
                self._dead.clear()
                self._replay_wal()
            else:
                # WAL-less standby (the runner's default local wiring):
                # the replicated in-memory state IS the state — promote
                # in place, re-arming TTL leases for their full duration
                # exactly like a WAL replay would
                now = time.monotonic()
                for k, (_, lease) in list(self._ttl.items()):
                    self._ttl[k] = (now + lease, lease)
            self._fencing_epoch += 1
            self._role = "primary"
            self._deposed = False
            self._primary_hint = ""
            self._open_wal()
            self._cv.notify_all()
        self._set_ha_gauges()
        return self._fencing_epoch

    def kill(self) -> None:
        """Model a SIGKILL of the KV process: drop the socket, the WAL
        append handle, and the ``.lock`` with no graceful teardown and no
        final compaction — durable state is exactly the WAL bytes already
        flushed (the kernel releases a dead process's flock the same
        way). Chaos ``kv_kill_primary_at_step`` drives this in the
        failover drill."""
        self._stop_sweeper()
        try:
            if self._thread is not None:
                self.stop()
            else:
                self._httpd.server_close()
        except Exception as e:
            logger.debug("kill: socket teardown: %s", e)
        if self._wal is not None:
            try:
                self._wal.close()
            except Exception as e:
                logger.debug("kill: wal close: %s", e)
            self._wal = None
        self._release_wal_lock()

    # ------------------------------------------------------------- sweeping

    def _sweep_locked(self):
        """Move TTL-expired keys to the tombstone map. Caller holds the
        store lock."""
        now = time.monotonic()
        expired = [k for k, (t, _) in self._ttl.items() if t <= now]
        for k in expired:
            self._ttl.pop(k, None)
            self._store.pop(k, None)
            self._dead[k] = now
        if expired and _metrics.enabled():
            _metrics.counter(
                "rendezvous_keys_swept",
                help="KV keys reclaimed by the TTL/tombstone sweep",
                kind="expired",
            ).inc(len(expired))

    def _gc_tombstones_locked(self):
        """Drop tombstones past their retention. Timer-only: lazy access
        must never shorten the 410 window readers rely on."""
        if self._tombstone_ttl <= 0:
            return
        horizon = time.monotonic() - self._tombstone_ttl
        stale = [k for k, t in self._dead.items() if t <= horizon]
        for k in stale:
            del self._dead[k]
        if stale and _metrics.enabled():
            _metrics.counter(
                "rendezvous_keys_swept",
                help="KV keys reclaimed by the TTL/tombstone sweep",
                kind="tombstone",
            ).inc(len(stale))

    def _start_sweeper(self) -> None:
        if self._sweep_interval <= 0 or self._sweeper is not None:
            return
        self._sweep_stop.clear()

        def _loop():
            while not self._sweep_stop.wait(self._sweep_interval):
                with self._lock:
                    self._sweep_locked()
                    self._gc_tombstones_locked()
                    self._cv.notify_all()

        self._sweeper = threading.Thread(
            target=_loop, name="hvd-kv-sweep", daemon=True)
        self._sweeper.start()

    def _stop_sweeper(self) -> None:
        if self._sweeper is None:
            return
        self._sweep_stop.set()
        self._sweeper.join(timeout=5)
        self._sweeper = None

    # ------------------------------------------------------------ lifecycle

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
            self._thread = None

    def close(self):
        """Release the bound socket whether or not :meth:`start` ever ran
        (``stop`` would hang waiting on a serve loop that never started).
        Owners that only use the store in-process call this."""
        self._stop_sweeper()
        if self._thread is not None:
            self.stop()
        else:
            self._httpd.server_close()
        if self._wal is not None:
            self._wal.close()
            self._wal = None
        self._release_wal_lock()

    def restart(self, replay: bool = True) -> int:
        """Tear the server down and bring it back up on the SAME port — the
        KV process crash+restart, in place. With a WAL and ``replay=True``
        the store is rebuilt from the log (membership and committed weight
        generations survive); ``replay=False`` models a restart that lost
        its disk: the store comes back empty and the WAL is truncated to
        match. Waiters blocked in :meth:`wait_for` keep their lock/condvar
        (the maps are cleared and repopulated, never replaced) and observe
        the post-restart state on their next wakeup. Returns the port."""
        was_serving = self._thread is not None
        port = self.port
        if was_serving:
            self.stop()
        else:
            self._httpd.server_close()
        if self._wal is not None:
            self._wal.close()
            self._wal = None
        with self._lock:
            self._store.clear()
            self._ttl.clear()
            self._dead.clear()
            if self._wal_path is not None and replay:
                self._replay_wal()
            elif self._wal_path is not None and os.path.exists(self._wal_path):
                os.unlink(self._wal_path)
            self._open_wal()
            self._cv.notify_all()
        self._httpd = http.server.ThreadingHTTPServer(
            ("0.0.0.0", port), _Handler)
        self._httpd._secret = self._secret  # type: ignore[attr-defined]
        self._httpd._kv = self  # type: ignore[attr-defined]
        if was_serving:
            self.start()
        if _metrics.enabled():
            _metrics.counter(
                "rendezvous_restarts",
                help="KV server restarts (crash simulation or operational)",
            ).inc()
        return self.port

    # ------------------------------------------------------------ store ops

    def server_clock(self) -> float:
        """This server's ``time.monotonic()`` — the fleet's reference
        timebase (in-process spelling of the ``GET /-/clock`` route)."""
        return time.monotonic()

    def put(self, key: str, value: bytes, ttl: Optional[float] = None):
        with self._lock:
            k = _norm(key)
            self._store[k] = value
            if ttl is not None:
                self._ttl[k] = (time.monotonic() + ttl, ttl)
            else:
                self._ttl.pop(k, None)
            # a refreshed key is alive again: clear any tombstone
            self._dead.pop(k, None)
            data = _wal_record(
                "put", k, value, ttl=ttl, fe=self._fencing_epoch)
            self._wal_append_locked(data)
            self._ship_locked(data)
            self._cv.notify_all()

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            self._sweep_locked()
            return self._store.get(_norm(key))

    def _get_with_liveness(self, key: str):
        """(value, tombstoned) in one locked read — the HTTP GET path."""
        with self._lock:
            self._sweep_locked()
            k = _norm(key)
            return self._store.get(k), k in self._dead

    def delete(self, key: str, tombstone: bool = False) -> bool:
        """Remove `key`; with ``tombstone=True`` readers see it as dead
        (410 / :class:`DeadRankError`) rather than never-written — the
        explicit-kill analog of a TTL expiry (chaos ``rank_fail`` uses it
        so failure detection needs no real-time sleep)."""
        with self._lock:
            k = _norm(key)
            existed = self._store.pop(k, None) is not None
            self._ttl.pop(k, None)
            if tombstone:
                self._dead[k] = time.monotonic()
            if existed or tombstone:
                data = _wal_record(
                    "del", k, tombstone=tombstone, fe=self._fencing_epoch)
                self._wal_append_locked(data)
                self._ship_locked(data)
            if tombstone:
                self._cv.notify_all()
            return existed

    def prune(self, prefix: str) -> int:
        """Drop every key, TTL record, and tombstone under `prefix`;
        returns how many entries were removed. The elastic coordinator
        uses this to retire prior generations' ack-barrier keys — without
        it the store grows monotonically across membership changes."""
        p = _norm(prefix)
        n = 0
        with self._lock:
            for m in (self._store, self._ttl, self._dead):
                for k in [k for k in m if k.startswith(p)]:
                    del m[k]
                    n += 1
            if n:
                data = _wal_record("prune", p, fe=self._fencing_epoch)
                self._wal_append_locked(data)
                self._ship_locked(data)
        return n

    def dead_keys(self) -> list:
        with self._lock:
            self._sweep_locked()
            return sorted(self._dead)

    def live_keys(self, prefix: str = "/") -> list:
        """Unexpired keys under `prefix` (the heartbeat-liveness query)."""
        with self._lock:
            self._sweep_locked()
            return sorted(
                k for k in self._store if k.startswith(_norm(prefix))
            )

    def wait_for(self, keys, timeout: Optional[float] = None,
                 hb_scope: Optional[str] = None) -> dict:
        """Block until every key in `keys` exists; return {key: value}.

        A missing key whose own tombstone exists — or, with `hb_scope`,
        whose owner rank's heartbeat key ``<hb_scope>/<rank>`` is
        tombstoned — raises :class:`DeadRankError` with the rank id
        immediately: the writer died, no amount of deadline will produce
        the key. TTL expiry is re-swept on every wakeup (bounded poll), so
        a rank dying *mid-wait* also fails fast instead of burning the
        whole deadline."""
        keys = [_norm(k) for k in keys]
        hb_prefix = _norm(hb_scope) if hb_scope else None
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        with self._lock:
            while True:
                self._sweep_locked()
                missing = [k for k in keys if k not in self._store]
                if not missing:
                    return {k: self._store[k] for k in keys}
                for k in missing:
                    owner = _key_owner(k)
                    if k in self._dead:
                        raise DeadRankError(
                            owner if owner is not None else -1, k)
                    if (
                        hb_prefix is not None
                        and owner is not None
                        and f"{hb_prefix}/{owner}" in self._dead
                    ):
                        raise DeadRankError(owner, k)
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"timed out waiting for keys: {missing}")
                # TTL expiry happens without a notify, so the sleep is
                # bounded by the SOONEST expiry; with no TTL'd keys at
                # all the wait is purely notify-driven (no busy-poll)
                poll = (
                    max(
                        min(t for t, _ in self._ttl.values())
                        - time.monotonic(),
                        0.01,
                    )
                    if self._ttl else None
                )
                if remaining is None:
                    wake = poll
                elif poll is None:
                    wake = remaining
                else:
                    wake = min(poll, remaining)
                self._cv.wait(wake)


def _wal_record(op: str, key: str, value: Optional[bytes] = None, *,
                ttl: Optional[float] = None,
                tombstone: bool = False, fe: int = 0) -> bytes:
    rec = {"op": op, "k": key}
    if op == "put":
        rec["v"] = base64.b64encode(value or b"").decode("ascii")
        if ttl is not None:
            rec["ttl"] = ttl
    elif op == "del" and tombstone:
        rec["ts"] = True
    if fe:
        # fencing epoch; omitted at epoch 0 so pre-HA logs stay
        # byte-identical and old readers keep parsing new logs
        rec["fe"] = fe
    return json.dumps(rec).encode() + b"\n"


def _norm(key: str) -> str:
    return key if key.startswith("/") else "/" + key


def parse_endpoints(spec: str) -> list:
    """``host:port,host:port`` → ``[(host, port), ...]``, primary first —
    the ``HVD_RUN_KV_ADDRS`` wire format."""
    eps = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port_s = part.rpartition(":")
        if not host:
            raise ValueError(f"endpoint {part!r} is not host:port")
        eps.append((host, int(port_s)))
    return eps


def format_endpoints(eps) -> str:
    return ",".join(f"{h}:{p}" for h, p in eps)


class KVStoreClient:
    """Client for :class:`KVStoreServer` (reference ``http_client.py``).

    Every request retries transient connection errors with the shared
    backoff policy (``resilience.retry``; env knobs
    ``HOROVOD_RETRY_KV_*``): during bootstrap the ranks race the launcher's
    server startup, and a first-packet ``ConnectionRefusedError`` used to
    fail the whole job. Chaos (``HOROVOD_CHAOS=kv_drop=N``) injects exactly
    that failure on demand so the recovery stays tested.

    **Failover**: with `endpoints` (or ``HVD_RUN_KV_ADDRS``) the client
    holds the whole control plane's address list — primary first, then
    the standbys. A dead endpoint rotates to the next one *inside* the
    existing retry scope: per-request backoff schedules and ``wait_for``
    total deadlines are never reset by a reconnect. The client tracks the
    highest **fencing epoch** any response has echoed and sends it with
    every request, so a deposed primary fences the write (409) instead of
    silently applying it; a 409 on a multi-endpoint client rotates and
    retries (the promoted primary is elsewhere), on a single-endpoint
    client it raises :class:`FencedError`. A standby's 307 write redirect
    is followed to the ``X-Hvd-Primary`` hint."""

    def __init__(self, addr: Optional[str] = None,
                 port: Optional[int] = None,
                 secret: Optional[str] = None,
                 retry_policy: Optional[_retry.RetryPolicy] = None,
                 endpoints: Optional[list] = None):
        if endpoints:
            self._endpoints = [(h, int(p)) for h, p in endpoints]
        elif addr is not None and port is not None:
            self._endpoints = [(addr, int(port))]
        else:
            raise ValueError(
                "KVStoreClient needs addr+port or a non-empty endpoints "
                "list")
        self._active = 0
        self._epoch_seen = 0
        self._failovers = 0
        self._ep_lock = threading.Lock()
        self._secret = secret or os.environ.get(SECRET_ENV, "")
        self._retry = retry_policy or _retry.policy_from_env(
            "kv", max_attempts=6, base_delay=0.05, max_delay=1.0,
            deadline=30.0,
        )
        #: socket timeout per HTTP request; callers operating under a hard
        #: budget (the preemption-drain publish flush) clamp this down so
        #: ONE blocked request cannot exceed their whole window
        self.request_timeout: float = 30.0

    # -------------------------------------------------- endpoint tracking

    @property
    def endpoints(self) -> list:
        return list(self._endpoints)

    @property
    def _addr(self) -> str:
        return self._endpoints[self._active][0]

    @property
    def _port(self) -> int:
        return self._endpoints[self._active][1]

    @property
    def fencing_epoch_seen(self) -> int:
        """Highest fencing epoch any response has echoed to this client."""
        return self._epoch_seen

    @property
    def failovers(self) -> int:
        """Endpoint rotations this client has performed."""
        return self._failovers

    def note_epoch(self, epoch: int) -> None:
        """Pin the newest fencing epoch this client must trust (learned
        out of band, e.g. from a promoted standby's status). Mutations
        echo it, so a stale primary fences instead of applying."""
        with self._ep_lock:
            if int(epoch) > self._epoch_seen:
                self._epoch_seen = int(epoch)

    def _rotate(self) -> None:
        with self._ep_lock:
            if len(self._endpoints) > 1:
                self._active = (self._active + 1) % len(self._endpoints)
                self._failovers += 1

    def _point_at(self, hint: str) -> None:
        """Follow a 307 redirect's ``host:port`` primary hint."""
        try:
            host, _, port_s = hint.rpartition(":")
            ep = (host, int(port_s))
        except (TypeError, ValueError):
            self._rotate()
            return
        with self._ep_lock:
            if ep not in self._endpoints:
                self._endpoints.append(ep)
            if self._endpoints[self._active] != ep:
                self._active = self._endpoints.index(ep)
                self._failovers += 1

    def _on_retry(self, exc: BaseException, attempts: int) -> None:
        """Between retry attempts, walk to the next endpoint — unless the
        failing response already moved us (redirect / stale-epoch)."""
        if len(self._endpoints) > 1 and not getattr(exc, "rotated", False):
            self._rotate()

    def _observe_response(self, resp, method: str, key: str) -> int:
        """Epoch/role bookkeeping for one response; raises to trigger a
        rotation (TransientError with ``rotated=True``) or to fence
        (:class:`FencedError`). Returns the status for normal handling."""
        status = resp.status
        raw = resp.getheader(_EPOCH_HEADER)
        try:
            epoch = int(raw) if raw is not None else None
        except ValueError:
            epoch = None
        if epoch is not None:
            with self._ep_lock:
                if epoch > self._epoch_seen:
                    self._epoch_seen = epoch
                    epoch_stale = False
                else:
                    epoch_stale = epoch < self._epoch_seen
            if epoch_stale and len(self._endpoints) > 1 and status < 300:
                # a pre-failover regime answered: its view predates the
                # newest epoch we have seen — walk away rather than trust
                # a stale primary's reads
                self._rotate()
                err = _retry.TransientError(
                    f"KV endpoint {self._addr}:{self._port} echoes stale "
                    f"fencing epoch {epoch} < {self._epoch_seen}")
                err.rotated = True
                raise err
        if status == 307:
            hint = resp.getheader(_PRIMARY_HEADER)
            if hint:
                self._point_at(hint)
            else:
                self._rotate()
            err = _retry.TransientError(
                f"KV {method} {key}: standby redirected the write to "
                f"the primary ({hint or 'unknown'})")
            err.rotated = True
            raise err
        if status == 409:
            if len(self._endpoints) > 1:
                self._rotate()
                err = _retry.TransientError(
                    f"KV {method} {key}: endpoint is fenced/deposed "
                    f"(epoch seen {self._epoch_seen}); rotating")
                err.rotated = True
                raise err
            raise FencedError(
                f"KV {method} {key} rejected with HTTP 409: the server "
                f"is deposed (a fencing epoch newer than its own "
                f"exists; client has seen {self._epoch_seen})",
                epoch=self._epoch_seen,
            )
        return status

    # ------------------------------------------------------------ requests

    def _conn(self):
        return http.client.HTTPConnection(
            self._addr, self._port, timeout=self.request_timeout)

    def _headers(self, body: bytes = b"", ttl: Optional[float] = None,
                 tombstone: bool = False):
        h = {}
        if self._secret:
            h[_HMAC_HEADER] = _digest(self._secret, body)
        if ttl is not None:
            h[_TTL_HEADER] = str(ttl)
        if tombstone:
            h[_TOMBSTONE_HEADER] = "1"
        if self._epoch_seen > 0:
            # epoch echo: a deposed primary receiving this fences itself
            h[_EPOCH_HEADER] = str(self._epoch_seen)
        return h

    def _request(self, method: str, key: str, body: Optional[bytes] = None,
                 ttl: Optional[float] = None, tombstone: bool = False):
        """One HTTP round trip → (status, body). Chaos drop-injection sits
        in front of the socket so retries see a refused connection exactly
        like the real startup race; ``kv_partition`` blackholes the
        first-listed endpoint (the original primary) for its window."""
        if _chaos.enabled():
            _chaos.inject_failure(
                "kv_drop",
                lambda m: ConnectionRefusedError(m),
            )
            if self._active == 0 and _chaos.kv_partition_active():
                raise ConnectionRefusedError(
                    "chaos kv_partition: primary endpoint unreachable")
        c = self._conn()
        try:
            c.request(
                method, _norm(key), body=body,
                headers=self._headers(body or b"", ttl, tombstone),
            )
            r = c.getresponse()
            data = r.read()
            status = self._observe_response(r, method, key)
            return status, data
        finally:
            c.close()

    def put(self, key: str, value: bytes, ttl: Optional[float] = None):
        status, _ = self._retry.call(
            self._request, "PUT", key, value, ttl=ttl,
            retriable=TRANSIENT_KV_ERRORS, on_retry=self._on_retry,
        )
        if status != 200:
            raise RuntimeError(f"KV put {key} failed: HTTP {status}")

    def heartbeat(self, rank: int, scope: str = "hb",
                  ttl: Optional[float] = None):
        """Refresh this rank's liveness key (``/<scope>/<rank>``) with the
        heartbeat TTL; stop calling it and the server tombstones the rank."""
        self.put(
            f"{scope}/{rank}", b"1",
            ttl=ttl if ttl is not None else default_heartbeat_ttl(),
        )

    def delete(self, key: str, tombstone: bool = False) -> bool:
        """Remove `key` on the server; with ``tombstone=True`` readers see
        it as dead (410) rather than never-written — same contract as the
        server-side :meth:`KVStoreServer.delete`. Returns whether the key
        existed."""
        status, _ = self._retry.call(
            self._request, "DELETE", key, tombstone=tombstone,
            retriable=TRANSIENT_KV_ERRORS, on_retry=self._on_retry,
        )
        if status not in (200, 404):
            raise RuntimeError(f"KV delete {key} failed: HTTP {status}")
        return status == 200

    def server_clock(self) -> float:
        """One ``GET /-/clock`` round trip → the server's monotonic
        seconds. Deliberately NO retry wrapper: a retried probe would fold
        backoff sleeps into the request/response window and blow up the
        midpoint estimate's error bound — the clock layer takes several
        probes and keeps the tightest anyway."""
        status, body = self._request("GET", CLOCK_PATH)
        if status != 200:
            raise RuntimeError(f"KV clock probe failed: HTTP {status}")
        return float(body)

    def get(self, key: str) -> Optional[bytes]:
        status, body = self._retry.call(
            self._request, "GET", key, retriable=TRANSIENT_KV_ERRORS,
            on_retry=self._on_retry,
        )
        if status == 404:
            return None
        if status == 410:
            # tombstoned: the key's writer died (TTL expiry) — classify,
            # same contract as wait_for, instead of an opaque RuntimeError
            try:
                rank = int(body)
            except (TypeError, ValueError):
                rank = -1
            raise DeadRankError(rank, key)
        if status != 200:
            raise RuntimeError(f"KV get {key} failed: HTTP {status}")
        return body

    def wait_for(self, key: str, timeout: float = 60.0,
                 interval: float = 0.1) -> bytes:
        """Block until `key` exists; total deadline = `timeout` seconds.

        The poll interval backs off geometrically from `interval` (capped
        at 2 s) instead of hammering the server at a fixed rate, the final
        sleep is clipped to the remaining budget, and transient connection
        errors *inside* the poll count against the same total deadline
        rather than each spinning up their own retry schedule. An endpoint
        failover mid-wait rotates to the next server but keeps BOTH the
        original deadline and the current geometric poll state — reconnect
        time is charged against the caller's budget, never granted on
        top of it."""
        deadline = time.monotonic() + timeout
        poll = interval
        last_err: Optional[BaseException] = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                status, body = self._request("GET", key)
                if status == 200:
                    return body
                if status == 410:
                    # the key's writer died (TTL expiry/tombstone): fail
                    # fast with the rank id instead of burning the deadline
                    try:
                        rank = int(body)
                    except (TypeError, ValueError):
                        rank = -1
                    raise DeadRankError(rank, key)
                if status != 404:
                    raise RuntimeError(
                        f"KV wait_for {key} failed: HTTP {status}"
                    )
            except TRANSIENT_KV_ERRORS as e:
                last_err = e  # server down/failing over; deadline governs
                if not getattr(e, "rotated", False):
                    self._rotate()
            time.sleep(min(poll, max(0.0, deadline - time.monotonic())))
            poll = min(poll * 1.5, 2.0)
        raise TimeoutError(
            f"timed out after {timeout}s waiting for KV key {key} "
            f"(endpoints {format_endpoints(self._endpoints)})"
            + (f" (last transient error: {last_err!r})" if last_err else "")
        )


class InProcessKVStore:
    """Minimal thread-safe ``put``/``get`` dict — the in-process stand-in
    the observability/analysis planes (schedule sanitizer, flight
    recorder) fall back to when no rendezvous KV is wired up, so
    single-controller runs still get their full publish/cross-check
    paths. TTLs are accepted and ignored: process lifetime bounds the
    data."""

    def __init__(self):
        self._lock = threading.Lock()
        self._d: dict = {}

    def put(self, key: str, value: bytes, ttl: Optional[float] = None):
        del ttl
        with self._lock:
            self._d[key] = value

    def get(self, key: str) -> Optional[bytes]:
        with self._lock:
            return self._d.get(key)


def kv_client_from_env() -> Optional["KVStoreClient"]:
    """:class:`KVStoreClient` built from the launcher env — the shared
    wiring the fleet metrics publisher, the schedule sanitizer, and the
    flight recorder all ride, so each launched worker's records land on
    the real fleet store without explicit configuration. Prefers the
    multi-endpoint ``HVD_RUN_KV_ADDRS`` list (primary + standbys, with
    automatic failover) over the single-endpoint
    ``HVD_RUN_KV_ADDR``/``HVD_RUN_KV_PORT`` pair. None when the env is
    absent or bring-up fails (callers fall back to
    :class:`InProcessKVStore`)."""
    addrs = os.environ.get(ADDRS_ENV)
    if addrs:
        try:
            eps = parse_endpoints(addrs)
            if eps:
                return KVStoreClient(endpoints=eps)
        except Exception as e:
            logger.debug("KV client bring-up from %s failed: %s",
                         ADDRS_ENV, e)
    addr = os.environ.get("HVD_RUN_KV_ADDR")
    port = os.environ.get("HVD_RUN_KV_PORT")
    if not addr or not port:
        return None
    try:
        return KVStoreClient(addr, int(port))
    except Exception as e:
        logger.debug("KV client bring-up from env failed: %s", e)
        return None
