"""HTTP rendezvous / key-value store server.

Reference: ``horovod/run/http/http_server.py`` — the launcher runs a small
HTTP KV server; ranks PUT/GET scoped keys during bootstrap, and the
programmatic ``run()`` API ships the pickled function down and results back
through it (``KVStoreServer``, reference ``http_server.py:210-250``).

On TPU the data-plane rendezvous is ``jax.distributed`` (coordinator
address), so this store's remaining jobs are (a) the ``run()`` function/result
shuttle, (b) generic scoped KV for launcher extensions, and (c) the elastic
membership plane: **heartbeat-scoped keys with a TTL**. A key PUT with a TTL
(``put(key, value, ttl=...)`` / the ``X-Hvd-TTL`` header) expires once its
writer stops refreshing it; expiry leaves a *tombstone*, so readers can tell
"never written" (404) from "written by a rank that since died" (410 Gone).
``wait_for`` consults the tombstones and the heartbeat namespace to surface
:class:`DeadRankError` carrying the dead rank's id *immediately* instead of
burning its whole deadline on a key whose writer can never write it.

Values are opaque bytes; a shared-secret HMAC header authenticates requests
(reference ``run/common/util/{secret,network}.py:49-83``).
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import http.server
import os
import re
import threading
import time
from typing import Optional

from horovod_tpu.resilience import chaos as _chaos, retry as _retry

SECRET_ENV = "HVD_RUN_SECRET"
_HMAC_HEADER = "X-Hvd-Digest"
_TTL_HEADER = "X-Hvd-TTL"

#: default TTL for heartbeat-scoped keys (seconds); the elastic layer's
#: failure-detection horizon. Tests use ~0.2s.
HEARTBEAT_TTL_ENV = "HOROVOD_ELASTIC_HEARTBEAT_TTL"


def default_heartbeat_ttl() -> float:
    return float(os.environ.get(HEARTBEAT_TTL_ENV, "10.0"))


class DeadRankError(RuntimeError):
    """A KV wait cannot complete because the rank that owns the awaited key
    is dead (its heartbeat TTL expired or it was explicitly tombstoned).
    ``rank`` is the dead rank's id (or -1 when unattributable)."""

    def __init__(self, rank: int, key: str = ""):
        self.rank = int(rank)
        self.key = key
        super().__init__(
            f"rank {rank} is dead (heartbeat expired)"
            + (f"; awaited key {key}" if key else "")
        )


#: trailing rank id in a scoped key: ``.../ack/3`` or ``.../result_3``
_OWNER_RE = re.compile(r"(?:/|_)(\d+)$")


def _key_owner(key: str) -> Optional[int]:
    m = _OWNER_RE.search(key)
    return int(m.group(1)) if m else None

#: failures worth retrying on the KV path. ``OSError`` deliberately covers
#: the whole startup-race family (ConnectionRefusedError/ResetError, and
#: socket.timeout, all OSError subclasses on py3.10+) — retrying an
#: occasional non-transient OSError is bounded by the policy's deadline,
#: while a missed transient one kills the job. Torn HTTP exchanges surface
#: as ``HTTPException``; chaos injections as ``TransientError``.
TRANSIENT_KV_ERRORS = (
    OSError,
    http.client.HTTPException,
    _retry.TransientError,
)


def make_secret() -> str:
    return os.urandom(16).hex()


def _digest(secret: str, body: bytes) -> str:
    return hmac.new(secret.encode(), body, hashlib.sha256).hexdigest()


class _Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _check_auth(self, body: bytes) -> bool:
        secret = self.server._secret  # type: ignore[attr-defined]
        if not secret:
            return True
        given = self.headers.get(_HMAC_HEADER, "")
        return hmac.compare_digest(given, _digest(secret, body))

    def _reply(self, code: int, body: bytes = b""):
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._check_auth(body):
            return self._reply(403)
        ttl = self.headers.get(_TTL_HEADER)
        with self.server._lock:  # type: ignore[attr-defined]
            self.server._store[self.path] = body  # type: ignore[attr-defined]
            if ttl is not None:
                self.server._ttl[self.path] = (  # type: ignore[attr-defined]
                    time.monotonic() + float(ttl)
                )
            else:
                self.server._ttl.pop(self.path, None)  # type: ignore[attr-defined]
            # a refreshed key is alive again: clear any tombstone
            self.server._dead.pop(self.path, None)  # type: ignore[attr-defined]
            self.server._cv.notify_all()  # type: ignore[attr-defined]
        self._reply(200)

    def do_GET(self):
        if not self._check_auth(b""):
            return self._reply(403)
        with self.server._lock:  # type: ignore[attr-defined]
            self.server._sweep_locked()  # type: ignore[attr-defined]
            val = self.server._store.get(self.path)  # type: ignore[attr-defined]
            dead = self.path in self.server._dead  # type: ignore[attr-defined]
        if val is None:
            if dead:
                owner = _key_owner(self.path)
                return self._reply(
                    410, str(owner if owner is not None else -1).encode()
                )
            return self._reply(404)
        self._reply(200, val)

    def do_DELETE(self):
        if not self._check_auth(b""):
            return self._reply(403)
        with self.server._lock:  # type: ignore[attr-defined]
            existed = self.server._store.pop(self.path, None)  # type: ignore[attr-defined]
        self._reply(200 if existed is not None else 404)

    def log_message(self, *a):  # quiet
        pass


class KVStoreServer:
    """Threaded KV server; start/stop + blocking wait for keys.

    Beyond plain KV, keys can carry a **TTL** (heartbeat-scoped keys): an
    expired key is removed from the store and *tombstoned*, so
    :meth:`wait_for` (and the HTTP GET path, which answers 410 Gone) can
    attribute "this key's writer died" instead of timing out. Expiry is
    swept lazily under the store lock — no background thread."""

    def __init__(self, port: int = 0, secret: Optional[str] = None):
        self._httpd = http.server.ThreadingHTTPServer(("0.0.0.0", port), _Handler)
        self._httpd._store = {}  # type: ignore[attr-defined]
        self._httpd._ttl = {}  # type: ignore[attr-defined]  # key -> expiry
        self._httpd._dead = {}  # type: ignore[attr-defined]  # tombstones
        self._httpd._lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd._cv = threading.Condition(self._httpd._lock)  # type: ignore[attr-defined]
        self._httpd._secret = secret or ""  # type: ignore[attr-defined]
        self._httpd._sweep_locked = self._sweep_locked  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    def _sweep_locked(self):
        """Move TTL-expired keys to the tombstone map. Caller holds the
        store lock."""
        now = time.monotonic()
        expired = [
            k for k, t in self._httpd._ttl.items() if t <= now  # type: ignore[attr-defined]
        ]
        for k in expired:
            self._httpd._ttl.pop(k, None)  # type: ignore[attr-defined]
            self._httpd._store.pop(k, None)  # type: ignore[attr-defined]
            self._httpd._dead[k] = now  # type: ignore[attr-defined]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def close(self):
        """Release the bound socket whether or not :meth:`start` ever ran
        (``stop`` would hang waiting on a serve loop that never started).
        Owners that only use the store in-process call this."""
        if self._thread is not None:
            self.stop()
        else:
            self._httpd.server_close()

    def put(self, key: str, value: bytes, ttl: Optional[float] = None):
        with self._httpd._lock:  # type: ignore[attr-defined]
            k = _norm(key)
            self._httpd._store[k] = value  # type: ignore[attr-defined]
            if ttl is not None:
                self._httpd._ttl[k] = time.monotonic() + ttl  # type: ignore[attr-defined]
            else:
                self._httpd._ttl.pop(k, None)  # type: ignore[attr-defined]
            self._httpd._dead.pop(k, None)  # type: ignore[attr-defined]
            self._httpd._cv.notify_all()  # type: ignore[attr-defined]

    def get(self, key: str) -> Optional[bytes]:
        with self._httpd._lock:  # type: ignore[attr-defined]
            self._sweep_locked()
            return self._httpd._store.get(_norm(key))  # type: ignore[attr-defined]

    def delete(self, key: str, tombstone: bool = False) -> bool:
        """Remove `key`; with ``tombstone=True`` readers see it as dead
        (410 / :class:`DeadRankError`) rather than never-written — the
        explicit-kill analog of a TTL expiry (chaos ``rank_fail`` uses it
        so failure detection needs no real-time sleep)."""
        with self._httpd._lock:  # type: ignore[attr-defined]
            k = _norm(key)
            existed = self._httpd._store.pop(k, None) is not None  # type: ignore[attr-defined]
            self._httpd._ttl.pop(k, None)  # type: ignore[attr-defined]
            if tombstone:
                self._httpd._dead[k] = time.monotonic()  # type: ignore[attr-defined]
                self._httpd._cv.notify_all()  # type: ignore[attr-defined]
            return existed

    def prune(self, prefix: str) -> int:
        """Drop every key, TTL record, and tombstone under `prefix`;
        returns how many entries were removed. The elastic coordinator
        uses this to retire prior generations' ack-barrier keys — without
        it the store grows monotonically across membership changes."""
        p = _norm(prefix)
        n = 0
        with self._httpd._lock:  # type: ignore[attr-defined]
            for m in (self._httpd._store, self._httpd._ttl,  # type: ignore[attr-defined]
                      self._httpd._dead):  # type: ignore[attr-defined]
                for k in [k for k in m if k.startswith(p)]:
                    del m[k]
                    n += 1
        return n

    def dead_keys(self) -> list:
        with self._httpd._lock:  # type: ignore[attr-defined]
            self._sweep_locked()
            return sorted(self._httpd._dead)  # type: ignore[attr-defined]

    def live_keys(self, prefix: str = "/") -> list:
        """Unexpired keys under `prefix` (the heartbeat-liveness query)."""
        with self._httpd._lock:  # type: ignore[attr-defined]
            self._sweep_locked()
            return sorted(
                k for k in self._httpd._store  # type: ignore[attr-defined]
                if k.startswith(_norm(prefix))
            )

    def wait_for(self, keys, timeout: Optional[float] = None,
                 hb_scope: Optional[str] = None) -> dict:
        """Block until every key in `keys` exists; return {key: value}.

        A missing key whose own tombstone exists — or, with `hb_scope`,
        whose owner rank's heartbeat key ``<hb_scope>/<rank>`` is
        tombstoned — raises :class:`DeadRankError` with the rank id
        immediately: the writer died, no amount of deadline will produce
        the key. TTL expiry is re-swept on every wakeup (bounded poll), so
        a rank dying *mid-wait* also fails fast instead of burning the
        whole deadline."""
        keys = [_norm(k) for k in keys]
        hb_prefix = _norm(hb_scope) if hb_scope else None
        deadline = (
            None if timeout is None else time.monotonic() + timeout
        )
        with self._httpd._lock:  # type: ignore[attr-defined]
            while True:
                self._sweep_locked()
                store = self._httpd._store  # type: ignore[attr-defined]
                dead = self._httpd._dead  # type: ignore[attr-defined]
                missing = [k for k in keys if k not in store]
                if not missing:
                    return {k: store[k] for k in keys}
                for k in missing:
                    owner = _key_owner(k)
                    if k in dead:
                        raise DeadRankError(
                            owner if owner is not None else -1, k)
                    if (
                        hb_prefix is not None
                        and owner is not None
                        and f"{hb_prefix}/{owner}" in dead
                    ):
                        raise DeadRankError(owner, k)
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    raise TimeoutError(
                        f"timed out waiting for keys: {missing}")
                # TTL expiry happens without a notify, so the sleep is
                # bounded by the SOONEST expiry; with no TTL'd keys at
                # all the wait is purely notify-driven (no busy-poll)
                ttls = self._httpd._ttl  # type: ignore[attr-defined]
                poll = (
                    max(min(ttls.values()) - time.monotonic(), 0.01)
                    if ttls else None
                )
                if remaining is None:
                    wake = poll
                elif poll is None:
                    wake = remaining
                else:
                    wake = min(poll, remaining)
                self._httpd._cv.wait(wake)  # type: ignore[attr-defined]


def _norm(key: str) -> str:
    return key if key.startswith("/") else "/" + key


class KVStoreClient:
    """Client for :class:`KVStoreServer` (reference ``http_client.py``).

    Every request retries transient connection errors with the shared
    backoff policy (``resilience.retry``; env knobs
    ``HOROVOD_RETRY_KV_*``): during bootstrap the ranks race the launcher's
    server startup, and a first-packet ``ConnectionRefusedError`` used to
    fail the whole job. Chaos (``HOROVOD_CHAOS=kv_drop=N``) injects exactly
    that failure on demand so the recovery stays tested."""

    def __init__(self, addr: str, port: int, secret: Optional[str] = None,
                 retry_policy: Optional[_retry.RetryPolicy] = None):
        self._addr = addr
        self._port = port
        self._secret = secret or os.environ.get(SECRET_ENV, "")
        self._retry = retry_policy or _retry.policy_from_env(
            "kv", max_attempts=6, base_delay=0.05, max_delay=1.0,
            deadline=30.0,
        )

    def _conn(self):
        return http.client.HTTPConnection(self._addr, self._port, timeout=30)

    def _headers(self, body: bytes = b"", ttl: Optional[float] = None):
        h = {}
        if self._secret:
            h[_HMAC_HEADER] = _digest(self._secret, body)
        if ttl is not None:
            h[_TTL_HEADER] = str(ttl)
        return h

    def _request(self, method: str, key: str, body: Optional[bytes] = None,
                 ttl: Optional[float] = None):
        """One HTTP round trip → (status, body). Chaos drop-injection sits
        in front of the socket so retries see a refused connection exactly
        like the real startup race."""
        if _chaos.enabled():
            _chaos.inject_failure(
                "kv_drop",
                lambda m: ConnectionRefusedError(m),
            )
        c = self._conn()
        try:
            c.request(
                method, _norm(key), body=body,
                headers=self._headers(body or b"", ttl),
            )
            r = c.getresponse()
            return r.status, r.read()
        finally:
            c.close()

    def put(self, key: str, value: bytes, ttl: Optional[float] = None):
        status, _ = self._retry.call(
            self._request, "PUT", key, value, ttl=ttl,
            retriable=TRANSIENT_KV_ERRORS,
        )
        if status != 200:
            raise RuntimeError(f"KV put {key} failed: HTTP {status}")

    def heartbeat(self, rank: int, scope: str = "hb",
                  ttl: Optional[float] = None):
        """Refresh this rank's liveness key (``/<scope>/<rank>``) with the
        heartbeat TTL; stop calling it and the server tombstones the rank."""
        self.put(
            f"{scope}/{rank}", b"1",
            ttl=ttl if ttl is not None else default_heartbeat_ttl(),
        )

    def get(self, key: str) -> Optional[bytes]:
        status, body = self._retry.call(
            self._request, "GET", key, retriable=TRANSIENT_KV_ERRORS
        )
        if status == 404:
            return None
        if status == 410:
            # tombstoned: the key's writer died (TTL expiry) — classify,
            # same contract as wait_for, instead of an opaque RuntimeError
            try:
                rank = int(body)
            except (TypeError, ValueError):
                rank = -1
            raise DeadRankError(rank, key)
        if status != 200:
            raise RuntimeError(f"KV get {key} failed: HTTP {status}")
        return body

    def wait_for(self, key: str, timeout: float = 60.0,
                 interval: float = 0.1) -> bytes:
        """Block until `key` exists; total deadline = `timeout` seconds.

        The poll interval backs off geometrically from `interval` (capped
        at 2 s) instead of hammering the server at a fixed rate, the final
        sleep is clipped to the remaining budget, and transient connection
        errors *inside* the poll count against the same total deadline
        rather than each spinning up their own retry schedule."""
        deadline = time.monotonic() + timeout
        poll = interval
        last_err: Optional[BaseException] = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                status, body = self._request("GET", key)
                if status == 200:
                    return body
                if status == 410:
                    # the key's writer died (TTL expiry/tombstone): fail
                    # fast with the rank id instead of burning the deadline
                    try:
                        rank = int(body)
                    except (TypeError, ValueError):
                        rank = -1
                    raise DeadRankError(rank, key)
                if status != 404:
                    raise RuntimeError(
                        f"KV wait_for {key} failed: HTTP {status}"
                    )
            except TRANSIENT_KV_ERRORS as e:
                last_err = e  # server still starting; the deadline governs
            time.sleep(min(poll, max(0.0, deadline - time.monotonic())))
            poll = min(poll * 1.5, 2.0)
        raise TimeoutError(
            f"timed out after {timeout}s waiting for KV key {key}"
            + (f" (last transient error: {last_err!r})" if last_err else "")
        )
