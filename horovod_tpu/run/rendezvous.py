"""HTTP rendezvous / key-value store server.

Reference: ``horovod/run/http/http_server.py`` — the launcher runs a small
HTTP KV server; ranks PUT/GET scoped keys during bootstrap, and the
programmatic ``run()`` API ships the pickled function down and results back
through it (``KVStoreServer``, reference ``http_server.py:210-250``).

On TPU the data-plane rendezvous is ``jax.distributed`` (coordinator
address), so this store's remaining jobs are (a) the ``run()`` function/result
shuttle and (b) generic scoped KV for launcher extensions. Values are opaque
bytes; a shared-secret HMAC header authenticates requests (reference
``run/common/util/{secret,network}.py:49-83``).
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import http.server
import os
import threading
import time
from typing import Optional

from horovod_tpu.resilience import chaos as _chaos, retry as _retry

SECRET_ENV = "HVD_RUN_SECRET"
_HMAC_HEADER = "X-Hvd-Digest"

#: failures worth retrying on the KV path. ``OSError`` deliberately covers
#: the whole startup-race family (ConnectionRefusedError/ResetError, and
#: socket.timeout, all OSError subclasses on py3.10+) — retrying an
#: occasional non-transient OSError is bounded by the policy's deadline,
#: while a missed transient one kills the job. Torn HTTP exchanges surface
#: as ``HTTPException``; chaos injections as ``TransientError``.
TRANSIENT_KV_ERRORS = (
    OSError,
    http.client.HTTPException,
    _retry.TransientError,
)


def make_secret() -> str:
    return os.urandom(16).hex()


def _digest(secret: str, body: bytes) -> str:
    return hmac.new(secret.encode(), body, hashlib.sha256).hexdigest()


class _Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _check_auth(self, body: bytes) -> bool:
        secret = self.server._secret  # type: ignore[attr-defined]
        if not secret:
            return True
        given = self.headers.get(_HMAC_HEADER, "")
        return hmac.compare_digest(given, _digest(secret, body))

    def _reply(self, code: int, body: bytes = b""):
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._check_auth(body):
            return self._reply(403)
        with self.server._lock:  # type: ignore[attr-defined]
            self.server._store[self.path] = body  # type: ignore[attr-defined]
            self.server._cv.notify_all()  # type: ignore[attr-defined]
        self._reply(200)

    def do_GET(self):
        if not self._check_auth(b""):
            return self._reply(403)
        with self.server._lock:  # type: ignore[attr-defined]
            val = self.server._store.get(self.path)  # type: ignore[attr-defined]
        if val is None:
            return self._reply(404)
        self._reply(200, val)

    def do_DELETE(self):
        if not self._check_auth(b""):
            return self._reply(403)
        with self.server._lock:  # type: ignore[attr-defined]
            existed = self.server._store.pop(self.path, None)  # type: ignore[attr-defined]
        self._reply(200 if existed is not None else 404)

    def log_message(self, *a):  # quiet
        pass


class KVStoreServer:
    """Threaded KV server; start/stop + blocking wait for keys."""

    def __init__(self, port: int = 0, secret: Optional[str] = None):
        self._httpd = http.server.ThreadingHTTPServer(("0.0.0.0", port), _Handler)
        self._httpd._store = {}  # type: ignore[attr-defined]
        self._httpd._lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd._cv = threading.Condition(self._httpd._lock)  # type: ignore[attr-defined]
        self._httpd._secret = secret or ""  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def put(self, key: str, value: bytes):
        with self._httpd._lock:  # type: ignore[attr-defined]
            self._httpd._store[_norm(key)] = value  # type: ignore[attr-defined]
            self._httpd._cv.notify_all()  # type: ignore[attr-defined]

    def get(self, key: str) -> Optional[bytes]:
        with self._httpd._lock:  # type: ignore[attr-defined]
            return self._httpd._store.get(_norm(key))  # type: ignore[attr-defined]

    def wait_for(self, keys, timeout: Optional[float] = None) -> dict:
        """Block until every key in `keys` exists; return {key: value}."""
        keys = [_norm(k) for k in keys]
        with self._httpd._lock:  # type: ignore[attr-defined]
            ok = self._httpd._cv.wait_for(  # type: ignore[attr-defined]
                lambda: all(k in self._httpd._store for k in keys),  # type: ignore[attr-defined]
                timeout=timeout,
            )
            if not ok:
                missing = [k for k in keys if k not in self._httpd._store]  # type: ignore[attr-defined]
                raise TimeoutError(f"timed out waiting for keys: {missing}")
            return {k: self._httpd._store[k] for k in keys}  # type: ignore[attr-defined]


def _norm(key: str) -> str:
    return key if key.startswith("/") else "/" + key


class KVStoreClient:
    """Client for :class:`KVStoreServer` (reference ``http_client.py``).

    Every request retries transient connection errors with the shared
    backoff policy (``resilience.retry``; env knobs
    ``HOROVOD_RETRY_KV_*``): during bootstrap the ranks race the launcher's
    server startup, and a first-packet ``ConnectionRefusedError`` used to
    fail the whole job. Chaos (``HOROVOD_CHAOS=kv_drop=N``) injects exactly
    that failure on demand so the recovery stays tested."""

    def __init__(self, addr: str, port: int, secret: Optional[str] = None,
                 retry_policy: Optional[_retry.RetryPolicy] = None):
        self._addr = addr
        self._port = port
        self._secret = secret or os.environ.get(SECRET_ENV, "")
        self._retry = retry_policy or _retry.policy_from_env(
            "kv", max_attempts=6, base_delay=0.05, max_delay=1.0,
            deadline=30.0,
        )

    def _conn(self):
        return http.client.HTTPConnection(self._addr, self._port, timeout=30)

    def _headers(self, body: bytes = b""):
        h = {}
        if self._secret:
            h[_HMAC_HEADER] = _digest(self._secret, body)
        return h

    def _request(self, method: str, key: str, body: Optional[bytes] = None):
        """One HTTP round trip → (status, body). Chaos drop-injection sits
        in front of the socket so retries see a refused connection exactly
        like the real startup race."""
        if _chaos.enabled():
            _chaos.inject_failure(
                "kv_drop",
                lambda m: ConnectionRefusedError(m),
            )
        c = self._conn()
        try:
            c.request(
                method, _norm(key), body=body,
                headers=self._headers(body or b""),
            )
            r = c.getresponse()
            return r.status, r.read()
        finally:
            c.close()

    def put(self, key: str, value: bytes):
        status, _ = self._retry.call(
            self._request, "PUT", key, value, retriable=TRANSIENT_KV_ERRORS
        )
        if status != 200:
            raise RuntimeError(f"KV put {key} failed: HTTP {status}")

    def get(self, key: str) -> Optional[bytes]:
        status, body = self._retry.call(
            self._request, "GET", key, retriable=TRANSIENT_KV_ERRORS
        )
        if status == 404:
            return None
        if status != 200:
            raise RuntimeError(f"KV get {key} failed: HTTP {status}")
        return body

    def wait_for(self, key: str, timeout: float = 60.0,
                 interval: float = 0.1) -> bytes:
        """Block until `key` exists; total deadline = `timeout` seconds.

        The poll interval backs off geometrically from `interval` (capped
        at 2 s) instead of hammering the server at a fixed rate, the final
        sleep is clipped to the remaining budget, and transient connection
        errors *inside* the poll count against the same total deadline
        rather than each spinning up their own retry schedule."""
        deadline = time.monotonic() + timeout
        poll = interval
        last_err: Optional[BaseException] = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                status, body = self._request("GET", key)
                if status == 200:
                    return body
                if status != 404:
                    raise RuntimeError(
                        f"KV wait_for {key} failed: HTTP {status}"
                    )
            except TRANSIENT_KV_ERRORS as e:
                last_err = e  # server still starting; the deadline governs
            time.sleep(min(poll, max(0.0, deadline - time.monotonic())))
            poll = min(poll * 1.5, 2.0)
        raise TimeoutError(
            f"timed out after {timeout}s waiting for KV key {key}"
            + (f" (last transient error: {last_err!r})" if last_err else "")
        )
