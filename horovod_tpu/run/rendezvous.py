"""HTTP rendezvous / key-value store server.

Reference: ``horovod/run/http/http_server.py`` — the launcher runs a small
HTTP KV server; ranks PUT/GET scoped keys during bootstrap, and the
programmatic ``run()`` API ships the pickled function down and results back
through it (``KVStoreServer``, reference ``http_server.py:210-250``).

On TPU the data-plane rendezvous is ``jax.distributed`` (coordinator
address), so this store's remaining jobs are (a) the ``run()`` function/result
shuttle and (b) generic scoped KV for launcher extensions. Values are opaque
bytes; a shared-secret HMAC header authenticates requests (reference
``run/common/util/{secret,network}.py:49-83``).
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import http.server
import os
import threading
from typing import Optional

SECRET_ENV = "HVD_RUN_SECRET"
_HMAC_HEADER = "X-Hvd-Digest"


def make_secret() -> str:
    return os.urandom(16).hex()


def _digest(secret: str, body: bytes) -> str:
    return hmac.new(secret.encode(), body, hashlib.sha256).hexdigest()


class _Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def _check_auth(self, body: bytes) -> bool:
        secret = self.server._secret  # type: ignore[attr-defined]
        if not secret:
            return True
        given = self.headers.get(_HMAC_HEADER, "")
        return hmac.compare_digest(given, _digest(secret, body))

    def _reply(self, code: int, body: bytes = b""):
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        if not self._check_auth(body):
            return self._reply(403)
        with self.server._lock:  # type: ignore[attr-defined]
            self.server._store[self.path] = body  # type: ignore[attr-defined]
            self.server._cv.notify_all()  # type: ignore[attr-defined]
        self._reply(200)

    def do_GET(self):
        if not self._check_auth(b""):
            return self._reply(403)
        with self.server._lock:  # type: ignore[attr-defined]
            val = self.server._store.get(self.path)  # type: ignore[attr-defined]
        if val is None:
            return self._reply(404)
        self._reply(200, val)

    def do_DELETE(self):
        if not self._check_auth(b""):
            return self._reply(403)
        with self.server._lock:  # type: ignore[attr-defined]
            existed = self.server._store.pop(self.path, None)  # type: ignore[attr-defined]
        self._reply(200 if existed is not None else 404)

    def log_message(self, *a):  # quiet
        pass


class KVStoreServer:
    """Threaded KV server; start/stop + blocking wait for keys."""

    def __init__(self, port: int = 0, secret: Optional[str] = None):
        self._httpd = http.server.ThreadingHTTPServer(("0.0.0.0", port), _Handler)
        self._httpd._store = {}  # type: ignore[attr-defined]
        self._httpd._lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd._cv = threading.Condition(self._httpd._lock)  # type: ignore[attr-defined]
        self._httpd._secret = secret or ""  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def put(self, key: str, value: bytes):
        with self._httpd._lock:  # type: ignore[attr-defined]
            self._httpd._store[_norm(key)] = value  # type: ignore[attr-defined]
            self._httpd._cv.notify_all()  # type: ignore[attr-defined]

    def get(self, key: str) -> Optional[bytes]:
        with self._httpd._lock:  # type: ignore[attr-defined]
            return self._httpd._store.get(_norm(key))  # type: ignore[attr-defined]

    def wait_for(self, keys, timeout: Optional[float] = None) -> dict:
        """Block until every key in `keys` exists; return {key: value}."""
        keys = [_norm(k) for k in keys]
        with self._httpd._lock:  # type: ignore[attr-defined]
            ok = self._httpd._cv.wait_for(  # type: ignore[attr-defined]
                lambda: all(k in self._httpd._store for k in keys),  # type: ignore[attr-defined]
                timeout=timeout,
            )
            if not ok:
                missing = [k for k in keys if k not in self._httpd._store]  # type: ignore[attr-defined]
                raise TimeoutError(f"timed out waiting for keys: {missing}")
            return {k: self._httpd._store[k] for k in keys}  # type: ignore[attr-defined]


def _norm(key: str) -> str:
    return key if key.startswith("/") else "/" + key


class KVStoreClient:
    """Client for :class:`KVStoreServer` (reference ``http_client.py``)."""

    def __init__(self, addr: str, port: int, secret: Optional[str] = None):
        self._addr = addr
        self._port = port
        self._secret = secret or os.environ.get(SECRET_ENV, "")

    def _conn(self):
        return http.client.HTTPConnection(self._addr, self._port, timeout=30)

    def _headers(self, body: bytes = b""):
        h = {}
        if self._secret:
            h[_HMAC_HEADER] = _digest(self._secret, body)
        return h

    def put(self, key: str, value: bytes):
        c = self._conn()
        try:
            c.request("PUT", _norm(key), body=value, headers=self._headers(value))
            r = c.getresponse()
            r.read()
            if r.status != 200:
                raise RuntimeError(f"KV put {key} failed: HTTP {r.status}")
        finally:
            c.close()

    def get(self, key: str) -> Optional[bytes]:
        c = self._conn()
        try:
            c.request("GET", _norm(key), headers=self._headers())
            r = c.getresponse()
            body = r.read()
            if r.status == 404:
                return None
            if r.status != 200:
                raise RuntimeError(f"KV get {key} failed: HTTP {r.status}")
            return body
        finally:
            c.close()

    def wait_for(self, key: str, timeout: float = 60.0, interval: float = 0.1) -> bytes:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = self.get(key)
            if v is not None:
                return v
            time.sleep(interval)
        raise TimeoutError(f"timed out waiting for KV key {key}")
