"""Environment hygiene for spawned worker processes.

Some deployments inject a TPU plugin into every Python process via a
``sitecustomize`` hook on ``PYTHONPATH`` (e.g. ``/root/.axon_site``). The
hook registers the plugin at interpreter startup, *before* ``JAX_PLATFORMS``
is consulted, so a child process pinned to the CPU platform can still block
inside the plugin's backend init when the TPU tunnel is unhealthy. Children
that are explicitly CPU-pinned therefore must not inherit those hook entries.

Reference analog: ``horovod/run/gloo_run.py`` builds each slot's env from an
explicit allow-list rather than inheriting the launcher env wholesale; this
module is the targeted version of that hygiene for the one known-hostile
entry class.
"""

from __future__ import annotations

import os
import signal
import sys

# PYTHONPATH entries containing any of these markers are sitecustomize-style
# plugin hooks that must not leak into CPU-pinned children.
PLUGIN_HOOK_MARKERS = (".axon_site",)


def strip_plugin_hooks(pythonpath: str) -> str:
    """Return `pythonpath` with plugin-hook entries removed."""
    return os.pathsep.join(
        p
        for p in pythonpath.split(os.pathsep)
        if p and not any(m in p for m in PLUGIN_HOOK_MARKERS)
    )


def scrub_plugin_hooks(env: dict, force: bool = False) -> dict:
    """Drop plugin-hook ``PYTHONPATH`` entries from `env`, in place.

    By default only scrubs when the env pins ``JAX_PLATFORMS=cpu`` — a child
    meant to use the real TPU needs the hook to reach it; a CPU-pinned child
    must never touch it. Pass ``force=True`` to scrub unconditionally.
    Returns `env` for chaining.
    """
    if not force and env.get("JAX_PLATFORMS", "").lower() != "cpu":
        return env
    pp = env.get("PYTHONPATH")
    if pp:
        cleaned = strip_plugin_hooks(pp)
        if cleaned:
            env["PYTHONPATH"] = cleaned
        else:
            env.pop("PYTHONPATH", None)
    return env


def install_sigterm_exit() -> None:
    """Convert SIGTERM into ``SystemExit(143)`` so finalizers actually run.

    CPython leaves SIGTERM at the kernel default (immediate termination, no
    ``finally`` blocks, no atexit, no device-client shutdown), so a parent
    watchdog's SIGTERM-before-SIGKILL escalation buys nothing unless the
    child opts in. Benchmark/tool children call this at startup: a
    merely-slow child killed by its watchdog then tears down the JAX client
    cleanly instead of dying mid-device-operation (observed to wedge the
    tunnel TPU for subsequent probes). Only installs on the main thread;
    no-op elsewhere."""
    try:
        signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    except ValueError:  # not the main thread
        pass
