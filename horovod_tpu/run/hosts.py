"""Host parsing and slot allocation.

Reference: ``horovod/run/gloo_run.py:54-112`` (``_allocate``: rank /
local_rank / cross_rank / sizes per slot) and host-list parsing in
``horovod/run/runner.py:551-568`` (``-H h1:4,h2:4`` / ``--hostfile``).
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional


@dataclasses.dataclass
class HostInfo:
    hostname: str
    slots: int


@dataclasses.dataclass
class HostSlots:
    """One launched process (reference ``SlotInfo``)."""

    hostname: str
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """Parse ``h1:4,h2:4`` (slots default to 1)."""
    out: List[HostInfo] = []
    for part in hosts_string.split(","):
        part = part.strip()
        if not part:
            continue
        m = re.match(r"^([\w.\-\[\]:]+?)(?::(\d+))?$", part)
        if m is None:
            raise ValueError(f"bad host spec: {part!r}")
        out.append(HostInfo(m.group(1), int(m.group(2) or 1)))
    return out


def parse_hostfile(path: str) -> List[HostInfo]:
    """Hostfile lines: ``hostname slots=N`` (reference runner.py hostfile
    handling; mpirun-style)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            slots = 1
            for p in parts[1:]:
                if p.startswith("slots="):
                    slots = int(p.split("=", 1)[1])
            out.append(HostInfo(parts[0], slots))
    return out


def allocate(hosts: List[HostInfo], np: int) -> List[HostSlots]:
    """Assign `np` process slots over `hosts` rank-major, computing the
    GLOBAL/LOCAL/CROSS coordinates (reference ``gloo_run.py:54-112``; the
    communicator triple ``common/common.h:111-115``)."""
    total = sum(h.slots for h in hosts)
    if np > total:
        raise ValueError(
            f"requested -np {np} exceeds available slots {total} "
            f"across {len(hosts)} host(s)"
        )
    slots: List[HostSlots] = []
    rank = 0
    for h in hosts:
        for local_rank in range(h.slots):
            if rank >= np:
                break
            slots.append(
                HostSlots(
                    hostname=h.hostname,
                    rank=rank,
                    size=np,
                    local_rank=local_rank,
                    local_size=0,  # filled below
                    cross_rank=0,
                    cross_size=0,
                )
            )
            rank += 1
    # local_size = processes on the same host
    by_host: dict = {}
    for s in slots:
        by_host.setdefault(s.hostname, []).append(s)
    for host_slots in by_host.values():
        for s in host_slots:
            s.local_size = len(host_slots)
    # cross_rank = index of this host among hosts having this local_rank;
    # cross_size = number of such hosts (reference gloo_run.py:95-112)
    by_local_rank: dict = {}
    for s in slots:
        by_local_rank.setdefault(s.local_rank, []).append(s)
    for group in by_local_rank.values():
        group.sort(key=lambda s: s.rank)
        for i, s in enumerate(group):
            s.cross_rank = i
            s.cross_size = len(group)
    return slots


def slot_env(slot: HostSlots) -> dict:
    """Identity env for one process (reference ``gloo_run.py:152-157``
    ``HOROVOD_RANK/SIZE/...``)."""
    return {
        "HOROVOD_RANK": str(slot.rank),
        "HOROVOD_SIZE": str(slot.size),
        "HOROVOD_LOCAL_RANK": str(slot.local_rank),
        "HOROVOD_LOCAL_SIZE": str(slot.local_size),
        "HOROVOD_CROSS_RANK": str(slot.cross_rank),
        "HOROVOD_CROSS_SIZE": str(slot.cross_size),
        # the names horovod_tpu.basics reads for multi-host wire-up
        "HVD_PROCESS_ID": str(slot.rank),
        "HVD_NUM_PROCESSES": str(slot.size),
    }


def hosts_from_scheduler_env(environ=None) -> Optional[List[HostInfo]]:
    """Default host list from a cluster scheduler's environment, the analog
    of the reference's LSF support (``run/util/lsf.py``, ``run/js_run.py``:
    ``horovodrun`` with no ``-H`` inside an LSF allocation reads the job's
    hosts). Recognized:

    - LSF: ``LSB_DJOB_HOSTFILE`` (one hostname per line, one per slot) or
      ``LSB_HOSTS`` (space-separated, repeated per slot);
    - SLURM: ``SLURM_JOB_NODELIST``/``SLURM_NODELIST`` in the simple
      comma/bracket form (``n[1-3],m5``) with ``SLURM_NTASKS_PER_NODE``.
    """
    import collections
    import os

    env = environ if environ is not None else os.environ

    def counted(hostnames) -> List[HostInfo]:
        counts = collections.Counter(hostnames)  # insertion-ordered
        return [HostInfo(h, n) for h, n in counts.items()]

    # LSF: first host is the launch node and runs rank 0. An unreadable
    # hostfile falls through to LSB_HOSTS (same list, env-borne).
    if env.get("LSB_DJOB_HOSTFILE"):
        try:
            with open(env["LSB_DJOB_HOSTFILE"]) as f:
                names = [line.strip() for line in f if line.strip()]
            if names:
                return counted(names)
        except OSError:
            pass
    if env.get("LSB_HOSTS"):
        return counted(env["LSB_HOSTS"].split())

    nodelist = env.get("SLURM_JOB_NODELIST") or env.get("SLURM_NODELIST")
    if nodelist:
        names: List[str] = []
        for part in re.split(r",(?![^\[]*\])", nodelist):
            m = re.match(r"^(.*)\[([\d,\-]+)\]$", part)
            if not m:
                names.append(part)
                continue
            prefix, ranges = m.groups()
            for r in ranges.split(","):
                if "-" in r:
                    lo, hi = r.split("-")
                    width = len(lo)
                    names += [
                        f"{prefix}{i:0{width}d}"
                        for i in range(int(lo), int(hi) + 1)
                    ]
                else:
                    names.append(f"{prefix}{r}")
        # SLURM_TASKS_PER_NODE is always set for a job ("2(x3),1" = 2 tasks
        # on each of 3 nodes, then 1); SLURM_NTASKS_PER_NODE only with an
        # explicit --ntasks-per-node.
        tasks_spec = (env.get("SLURM_NTASKS_PER_NODE")
                      or env.get("SLURM_TASKS_PER_NODE"))
        slot_list: List[int] = []
        if tasks_spec:
            for piece in str(tasks_spec).split(","):
                m = re.match(r"^(\d+)(?:\(x(\d+)\))?$", piece.strip())
                if not m:
                    slot_list = []
                    break
                slot_list += [int(m.group(1))] * int(m.group(2) or 1)
        if len(slot_list) == len(names):
            return [HostInfo(n, s) for n, s in zip(names, slot_list)]
        slots = slot_list[0] if slot_list else 1
        return [HostInfo(n, slots) for n in names]
    return None


def get_host_assignments(
    hosts: Optional[str],
    hostfile: Optional[str],
    np: int,
) -> List[HostSlots]:
    if hosts and hostfile:
        raise ValueError("pass either hosts or hostfile, not both")
    if hostfile:
        infos = parse_hostfile(hostfile)
    elif hosts:
        infos = parse_hosts(hosts)
    else:
        infos = hosts_from_scheduler_env() or [HostInfo("localhost", np)]
    return allocate(infos, np)
