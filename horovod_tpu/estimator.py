"""Estimator workflow: fit a model on a DataFrame with distributed training.

Reference: Horovod's Spark Estimator framework
(``horovod/spark/common/estimator.py``, ``spark/keras/estimator.py``,
``spark/torch/estimator.py``) — prepare the DataFrame into a ``Store``,
launch one training process per worker that reads its shard, wrap the
optimizer in ``DistributedOptimizer``, and return a trained model wrapper
with ``transform()``.

TPU-native re-design: the execution fabric is the framework's own launcher
(:func:`horovod_tpu.run.runner.run` — one process per TPU host) instead of
Spark executors, and the staging format is pandas→parquet. The Spark-facing
veneer lives in :mod:`horovod_tpu.spark` (gated on pyspark); this module is
fully functional without Spark.
"""

from __future__ import annotations

import os
import uuid
from typing import Callable, List, Optional, Sequence

import numpy as np

from horovod_tpu.data.store import LocalStore, Store


def _default_store() -> Store:
    return LocalStore(os.path.join(os.getcwd(), ".hvd_estimator_runs"))


def _maybe_force_platform():
    """Workers honor JAX_PLATFORMS even when a site hook already imported
    jax (config.update works until a backend is initialized)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        try:
            jax.config.update("jax_platforms", plat)
        except Exception as e:  # pragma: no cover - backend already up
            import logging

            logging.getLogger("horovod_tpu").debug(
                "platform pin to %r skipped (backend already up): %s",
                plat, e)


class EstimatorModel:
    """Base trained-model wrapper (reference
    ``spark/common/estimator.py:70-110`` ``HorovodModel``)."""

    def __init__(self, feature_cols, label_cols, output_cols, history):
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.output_cols = list(output_cols)
        self.history_ = history

    def _predict(self, features: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def transform(self, df):
        """Append prediction columns to a pandas DataFrame (reference
        ``HorovodModel.transform``)."""
        feats = df[self.feature_cols].to_numpy(dtype=np.float32)
        preds = np.asarray(self._predict(feats))
        out = df.copy()
        if preds.ndim == 1:
            preds = preds[:, None]
        for i, col in enumerate(self.output_cols):
            if preds.shape[1] == len(self.output_cols):
                out[col] = preds[:, i]
            else:  # one multi-dim output column
                out[col] = list(preds)
        return out


class Estimator:
    """Base estimator (reference ``spark/common/estimator.py:27-68``
    ``HorovodEstimator``): ``fit(df) -> model``.

    Parameters mirror the reference's param set (``spark/common/params.py``):
    feature/label columns, batch size, epochs, validation split, num_proc,
    store, verbosity.
    """

    def __init__(self, *, feature_cols: Sequence[str],
                 label_cols: Sequence[str], batch_size: int = 32,
                 epochs: int = 1, num_proc: int = 1,
                 store: Optional[Store] = None,
                 validation: Optional[float] = None,
                 run_id: Optional[str] = None,
                 env: Optional[dict] = None, verbose: int = 0):
        self.feature_cols = list(feature_cols)
        self.label_cols = list(label_cols)
        self.batch_size = batch_size
        self.epochs = epochs
        self.num_proc = num_proc
        self.store = store or _default_store()
        self.validation = validation
        self.run_id = run_id
        self.env = env
        self.verbose = verbose

    # subclasses provide a picklable train fn + model builder ---------------

    def _make_train_fn(self, run_id: str) -> Callable:
        raise NotImplementedError

    def _make_model(self, remote_result, run_id: str) -> EstimatorModel:
        raise NotImplementedError

    def fit(self, df) -> EstimatorModel:
        """Stage `df`, train on ``num_proc`` processes, return the model
        (reference ``HorovodEstimator.fit``, ``spark/common/estimator.py:27-46``)."""
        run_id = self.run_id or f"run_{uuid.uuid4().hex[:12]}"
        train_df, val_df = self._split(df)
        self.store.write_dataframe(
            train_df, self.store.get_train_data_path(run_id))
        if val_df is not None:
            self.store.write_dataframe(
                val_df, self.store.get_val_data_path(run_id))

        train_fn = self._make_train_fn(run_id)
        if self.num_proc <= 1:
            results = [train_fn()]
        else:
            from horovod_tpu.run import runner

            results = runner.run(
                train_fn, np=self.num_proc,
                env=self._job_env(), verbose=bool(self.verbose),
            )
        # rank 0 carries the authoritative state (reference: rank-0 checkpoint)
        return self._make_model(results[0], run_id)

    def _split(self, df):
        if not self.validation:
            return df, None
        n_val = int(len(df) * self.validation)
        if n_val == 0:
            return df, None
        return df.iloc[:-n_val], df.iloc[-n_val:]

    def _job_env(self) -> dict:
        env = dict(os.environ)
        if self.env:
            env.update(self.env)
        return env

    def _load_shard(self, run_id: str, rank: int, size: int):
        """Worker-side: this rank's rows of the staged train data (reference
        petastorm row-group sharding, ``spark/keras/remote.py:93-178``)."""
        df = self.store.read_dataframe(self.store.get_train_data_path(run_id))
        shard = df.iloc[rank::size]
        x = shard[self.feature_cols].to_numpy(dtype=np.float32)
        y = shard[self.label_cols].to_numpy(dtype=np.float32)
        return x, y


# --------------------------------------------------------------------------
# Keras


class KerasModel(EstimatorModel):
    """Trained Keras model wrapper (reference ``spark/keras/estimator.py``
    ``KerasModel``)."""

    def __init__(self, model_json, weights, **kw):
        super().__init__(**kw)
        self._model_json = model_json
        self._weights = weights
        self._model = None

    @property
    def keras_model(self):
        if self._model is None:
            import keras

            self._model = keras.models.model_from_json(self._model_json)
            self._model.set_weights(self._weights)
        return self._model

    def _predict(self, features):
        return self.keras_model.predict(features, verbose=0)


class KerasEstimator(Estimator):
    """Distributed Keras training on a DataFrame (reference
    ``spark/keras/estimator.py:40-160`` ``KerasEstimator``)."""

    def __init__(self, *, model, optimizer="sgd", loss="mse", metrics=(),
                 **kw):
        super().__init__(**kw)
        self.model = model
        self.optimizer = optimizer
        self.loss = loss
        self.metrics = list(metrics)

    def _make_train_fn(self, run_id: str):
        model_json = self.model.to_json()
        opt = self.optimizer
        if not isinstance(opt, str):
            import keras

            opt = keras.optimizers.serialize(opt)
        loss, metrics = self.loss, self.metrics
        batch_size, epochs, verbose = (
            self.batch_size, self.epochs, self.verbose)
        estimator = self  # bound state is picklable (store paths + cols)

        def train():
            _maybe_force_platform()
            import keras

            import horovod_tpu.keras as hvd

            hvd.init()
            x, y = estimator._load_shard(run_id, hvd.process_rank(),
                                         hvd.process_size())
            model = keras.models.model_from_json(model_json)
            base_opt = (keras.optimizers.get(opt) if isinstance(opt, str)
                        else keras.optimizers.deserialize(opt))
            model.compile(
                optimizer=hvd.DistributedOptimizer(base_opt),
                loss=loss, metrics=metrics or None,
            )
            callbacks = [hvd.BroadcastGlobalVariablesCallback(0),
                         hvd.MetricAverageCallback()]
            hist = model.fit(
                x, y, batch_size=batch_size, epochs=epochs,
                callbacks=callbacks,
                verbose=verbose if hvd.process_rank() == 0 else 0,
            )
            if hvd.process_rank() == 0:
                return {"weights": model.get_weights(),
                        "history": hist.history}
            return None

        return train

    def _make_model(self, result, run_id):
        return KerasModel(
            self.model.to_json(), result["weights"],
            feature_cols=self.feature_cols, label_cols=self.label_cols,
            output_cols=[f"{c}_pred" for c in self.label_cols],
            history=result["history"],
        )


# --------------------------------------------------------------------------
# Torch


class TorchModel(EstimatorModel):
    """Trained torch model wrapper (reference ``spark/torch/estimator.py``
    ``TorchModel``)."""

    def __init__(self, model, **kw):
        super().__init__(**kw)
        self.torch_model = model

    def _predict(self, features):
        import torch

        self.torch_model.eval()
        with torch.no_grad():
            return self.torch_model(
                torch.from_numpy(features)).numpy()


class TorchEstimator(Estimator):
    """Distributed PyTorch training on a DataFrame (reference
    ``spark/torch/estimator.py:36-150`` ``TorchEstimator``)."""

    def __init__(self, *, model, optimizer, loss, **kw):
        super().__init__(**kw)
        self.model = model
        self.optimizer = optimizer  # torch optimizer instance over model params
        self.loss = loss            # callable(output, target)

    def _make_train_fn(self, run_id: str):
        import torch

        model = self.model
        opt_cls = type(self.optimizer)
        opt_defaults = dict(self.optimizer.defaults)
        loss_fn = self.loss
        batch_size, epochs = self.batch_size, self.epochs
        estimator = self

        def train():
            _maybe_force_platform()
            import torch

            import horovod_tpu.torch as hvd

            hvd.init()
            x, y = estimator._load_shard(run_id, hvd.process_rank(),
                                         hvd.process_size())
            local = model
            opt = opt_cls(local.parameters(), **opt_defaults)
            opt = hvd.DistributedOptimizer(
                opt, named_parameters=local.named_parameters())
            hvd.broadcast_parameters(local.state_dict(), root_rank=0)
            hvd.broadcast_optimizer_state(opt, root_rank=0)
            xs, ys = torch.from_numpy(x), torch.from_numpy(y)
            history = []
            for _ in range(epochs):
                perm = torch.randperm(len(xs))
                epoch_loss = 0.0
                nb = 0
                for i in range(0, len(xs), batch_size):
                    idx = perm[i:i + batch_size]
                    opt.zero_grad()
                    out = local(xs[idx])
                    l = loss_fn(out, ys[idx])
                    l.backward()
                    opt.step()
                    epoch_loss += float(l.detach())
                    nb += 1
                history.append(epoch_loss / max(nb, 1))
            if hvd.process_rank() == 0:
                return {"state_dict": local.state_dict(), "history": history}
            return None

        return train

    def _make_model(self, result, run_id):
        self.model.load_state_dict(result["state_dict"])
        return TorchModel(
            self.model,
            feature_cols=self.feature_cols, label_cols=self.label_cols,
            output_cols=[f"{c}_pred" for c in self.label_cols],
            history=result["history"],
        )
