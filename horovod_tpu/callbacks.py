"""Training-loop callbacks — the rebuild of the reference's Keras callback
suite (``horovod/_keras/callbacks.py``), framework-neutral so they serve the
JAX training loops here the way the originals served ``model.fit``.

The reference wires callbacks to a Keras model; here a callback is wired to
any *trainer* object via :meth:`Callback.set_trainer`. The trainer contract is
attribute-based and minimal:

- ``trainer.params`` / ``trainer.opt_state`` — pytrees (broadcast targets)
- ``trainer.lr`` — a float the train step reads each batch (LR callbacks);
  with optax, build the optimizer with ``optax.inject_hyperparams`` and use
  :func:`apply_lr` to push ``trainer.lr`` into the opt state.

Epoch/batch hook names and semantics match Keras
(``on_train_begin/on_epoch_begin/on_batch_begin/.../on_train_end``) so
reference users find the identical surface.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from horovod_tpu import basics
from horovod_tpu.observability import exporters as _exporters, metrics as _metrics
from horovod_tpu.ops import collective as C


class Callback:
    """Base callback (hook surface of ``keras.callbacks.Callback`` as used by
    the reference in ``_keras/callbacks.py``)."""

    trainer: Any = None

    def set_trainer(self, trainer):
        self.trainer = trainer

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_batch_begin(self, batch, logs=None):
        pass

    def on_batch_end(self, batch, logs=None):
        pass


class CallbackList:
    """Dispatch helper a fit loop drives (Keras ``CallbackList`` analog)."""

    def __init__(self, callbacks: List[Callback], trainer=None):
        self.callbacks = list(callbacks)
        if trainer is not None:
            for cb in self.callbacks:
                cb.set_trainer(trainer)

    def __iter__(self):
        return iter(self.callbacks)

    def _fire(self, hook, *args):
        for cb in self.callbacks:
            getattr(cb, hook)(*args)

    def on_train_begin(self, logs=None):
        self._fire("on_train_begin", logs)

    def on_train_end(self, logs=None):
        self._fire("on_train_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._fire("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._fire("on_epoch_end", epoch, logs)

    def on_batch_begin(self, batch, logs=None):
        self._fire("on_batch_begin", batch, logs)

    def on_batch_end(self, batch, logs=None):
        self._fire("on_batch_end", batch, logs)


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast initial parameters and optimizer state from `root_rank` so
    all ranks start identically (reference ``_keras/callbacks.py:22-46``)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        t = self.trainer
        if getattr(t, "params", None) is not None:
            t.params = jax.tree_util.tree_map(
                lambda x: C.broadcast(x, self.root_rank), t.params
            )
        if getattr(t, "opt_state", None) is not None:
            # sharded (ZeRO-1) moment leaves are per-rank state and must
            # not be overwritten with root's shard — route through the
            # sharded-aware broadcast
            from horovod_tpu.optim import broadcast_optimizer_state

            t.opt_state = broadcast_optimizer_state(
                t.opt_state, self.root_rank
            )
        self.broadcast_done = True

    # the reference broadcasts after the first batch (variables exist by
    # then); params exist up-front in JAX, so train_begin also works.
    def on_train_begin(self, logs=None):
        self.on_batch_end(0, logs)


class MetricAverageCallback(Callback):
    """Average epoch metrics over ranks before they are logged/checkpointed
    (reference ``_keras/callbacks.py:48-87``)."""

    def _average(self, logs: Optional[Dict[str, Any]]):
        if not logs:
            return
        for k, v in list(logs.items()):
            if isinstance(v, (int, float, np.floating, np.integer)) or (
                hasattr(v, "shape") and getattr(v, "shape", None) == ()
            ):
                logs[k] = float(
                    np.asarray(C.allreduce(np.asarray(v, np.float64), C.Average))
                )

    def on_epoch_end(self, epoch, logs=None):
        self._average(logs)


class LearningRateScheduleCallback(Callback):
    """Multiply the base LR by ``multiplier(epoch)`` (or a constant) within
    ``[start_epoch, end_epoch)`` (reference ``_keras/callbacks.py:90-152``).

    With ``staircase=True`` the LR changes per epoch; otherwise per batch,
    using fractional epochs (requires ``steps_per_epoch``). When the
    multiplier changes and ``momentum_correction`` is set, SGD-momentum
    buffers are rescaled by ``new_lr/old_lr`` so the effective update
    magnitude is preserved across the LR jump (reference
    ``_keras/callbacks.py:118-136``)."""

    def __init__(self, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None,
                 initial_lr: Optional[float] = None):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.initial_lr = initial_lr
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        self._last_lr = None
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def _in_window(self, epoch) -> bool:
        return epoch >= self.start_epoch and (
            self.end_epoch is None or epoch < self.end_epoch
        )

    def _resolve_initial_lr(self):
        if self.initial_lr is None:
            self.initial_lr = getattr(self.trainer, "lr", None)
        if self.initial_lr is None:
            raise ValueError(
                "initial_lr not given and trainer has no .lr attribute"
            )

    def _set_lr(self, lr: float):
        old = self._last_lr
        self.trainer.lr = lr
        if (
            self.momentum_correction
            and old
            and old > 0
            and not math.isclose(lr, old)
        ):
            scale_momentum(self.trainer, lr / old)
        self._last_lr = lr

    def on_train_begin(self, logs=None):
        self._resolve_initial_lr()
        if self._last_lr is None:
            self._last_lr = self.initial_lr

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase and self._in_window(epoch):
            self._resolve_initial_lr()
            self._set_lr(self.initial_lr * self.multiplier(epoch))

    def on_batch_begin(self, batch, logs=None):
        if self.staircase or not self._in_window(self.current_epoch):
            return
        if self.steps_per_epoch is None:
            raise ValueError(
                "steps_per_epoch is required with staircase=False "
                "(reference _keras/callbacks.py:108-116)"
            )
        self._resolve_initial_lr()
        epoch = self.current_epoch + float(batch) / self.steps_per_epoch
        self._set_lr(self.initial_lr * self.multiplier(epoch))


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Ramp the LR from ``initial_lr / size`` to ``initial_lr`` over the first
    ``warmup_epochs`` — the "gradual warmup" of Goyal et al. the reference
    implements (``_keras/callbacks.py:155-192``):

        lr = initial_lr * (epoch * (size - 1) / warmup_epochs + 1) / size
    """

    def __init__(self, warmup_epochs: int = 5, momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0,
                 initial_lr: Optional[float] = None):
        def multiplier(epoch):
            if warmup_epochs > 0:
                epoch = min(epoch, warmup_epochs)
                return (
                    epoch * (basics.size() - 1) / warmup_epochs + 1
                ) / basics.size()
            return 1.0

        super().__init__(
            multiplier, start_epoch=0, end_epoch=warmup_epochs + 1,
            staircase=False, momentum_correction=momentum_correction,
            steps_per_epoch=steps_per_epoch, initial_lr=initial_lr,
        )
        self.verbose = verbose
        self.warmup_epochs = warmup_epochs

    def on_epoch_end(self, epoch, logs=None):
        if epoch == self.warmup_epochs - 1 and self.verbose:
            print(
                f"Epoch {epoch + 1}: finished gradual learning rate warmup to "
                f"{self.trainer.lr}."
            )


class MetricsCallback(Callback):
    """Log (or dump) the metrics-registry snapshot every ``every_n_steps``
    batches — the fit-loop surface of :mod:`horovod_tpu.observability`.

    Also records the fit loop's own cadence under distinct names
    (``fit_batch_seconds`` histogram, ``fit_batches`` counter,
    ``fit_examples`` when the trainer exposes ``global_batch_size``) so it
    composes with the step-level ``train_*`` metrics from
    ``make_*_train_step`` without double counting.

    Args:
      every_n_steps: emit cadence in batches (0 = only at train end).
      dump_path: when set, write the JSON snapshot there (atomic replace)
        instead of printing the summary.
      printer: summary sink (default ``print``); only process rank 0 emits,
        mirroring the reference's coordinator-only Timeline.
    """

    def __init__(self, every_n_steps: int = 100,
                 dump_path: Optional[str] = None,
                 printer: Callable[[str], Any] = print):
        self.every_n_steps = every_n_steps
        self.dump_path = dump_path
        self.printer = printer
        self._seen = 0
        self._t0 = None

    def _emitting_rank(self) -> bool:
        try:
            return basics.process_rank() == 0
        except RuntimeError:
            return True

    def _emit(self):
        if not self._emitting_rank():
            return
        _exporters.emit_snapshot(
            self.dump_path, self.printer,
            header=f"horovod_tpu metrics @ batch {self._seen}:\n",
        )

    def on_batch_begin(self, batch, logs=None):
        import time

        self._t0 = time.perf_counter()

    def on_batch_end(self, batch, logs=None):
        import time

        self._seen += 1
        if _metrics.enabled():
            if self._t0 is not None:
                _metrics.histogram(
                    "fit_batch_seconds", help="fit-loop batch wall time"
                ).observe(time.perf_counter() - self._t0)
            _metrics.counter("fit_batches", help="fit batches run").inc()
            examples = getattr(self.trainer, "global_batch_size", None)
            if examples:
                _metrics.counter(
                    "fit_examples", help="examples seen by the fit loop"
                ).inc(examples)
        if self.every_n_steps and self._seen % self.every_n_steps == 0:
            self._emit()

    def on_train_end(self, logs=None):
        self._emit()


class HealthCallback(Callback):
    """Surface the resilience health state machine inside the fit loop.

    Every batch end feeds a progress beat into the monitor (a completed
    batch *is* forward progress — this walks SUSPECT/DEGRADED back toward
    HEALTHY) and, on a state change, logs the transition with its reason.
    With ``abort_on`` set (default ``FATAL``), reaching that severity raises
    ``RuntimeError`` at the batch boundary so a poisoned run stops at a
    clean step edge instead of hanging in the next collective.
    """

    def __init__(self, printer: Callable[[str], Any] = None,
                 abort_on=None):
        from horovod_tpu.resilience import health as _health

        self._health = _health
        self.printer = printer
        self.abort_on = (
            _health.HealthState.FATAL if abort_on is None else abort_on
        )
        self._last = _health.health_state()

    def _say(self, msg: str) -> None:
        if self.printer is not None:
            self.printer(msg)
        else:
            import logging

            logging.getLogger("horovod_tpu.resilience").warning("%s", msg)

    def on_batch_end(self, batch, logs=None):
        # read (and possibly abort on) the state the batch produced BEFORE
        # feeding the progress beat — beat() walks SUSPECT back to HEALTHY,
        # which would make abort_on=SUSPECT unreachable
        state = self._health.health_state()
        if state != self._last:
            self._say(
                f"health: {self._last.name} -> {state.name} at batch "
                f"{batch} ({self._health.MONITOR.reason()})"
            )
            self._last = state
        if state >= self.abort_on:
            raise RuntimeError(
                f"health state {state.name} reached at batch {batch}: "
                f"{self._health.MONITOR.reason()}"
            )
        self._health.beat()


class PublishCallback(Callback):
    """Stream consolidated weights to a serving fleet from inside a fit
    loop: every `every` completed batches (and once more at train end),
    rank 0 publishes ``trainer.params`` through a
    :class:`horovod_tpu.serving.WeightPublisher`. Publication failures are
    logged and swallowed — the staleness contract on the subscriber side
    covers the gap; training never dies because the serving KV is down.

    For ``resilience.run``/``elastic.run`` loops use the
    ``publisher=``/``publish_every=`` arguments instead (they publish the
    committed, reshard-safe snapshot)."""

    def __init__(self, publisher, every: int = 100):
        if every < 1:
            raise ValueError(f"publish cadence must be >= 1, got {every}")
        self.publisher = publisher
        self.every = every
        self._seen = 0
        self._published_at = -1

    def _publish(self, batch: int) -> None:
        if basics.is_initialized() and basics.process_rank() != 0:
            return  # one writer, same as checkpointing
        params = getattr(self.trainer, "params", None)
        if params is None:
            return
        from horovod_tpu import serving as _serving

        try:
            self.publisher.publish({"params": params}, batch)
            self._published_at = batch
        except _serving.PublishError as e:
            import logging

            logging.getLogger("horovod_tpu.serving").warning(
                "weight publication at batch %d failed: %s", batch, e)

    def on_batch_end(self, batch, logs=None):
        self._seen = batch + 1
        if (batch + 1) % self.every == 0:
            self._publish(batch + 1)

    def on_train_end(self, logs=None):
        # the final weights are the ones a serving fleet actually wants
        if self._seen and self._published_at != self._seen:
            self._publish(self._seen)


# --------------------------------------------------------------------- optax


def apply_lr(opt_state, lr: float):
    """Push a callback-adjusted LR into an ``optax.inject_hyperparams`` opt
    state; returns the updated state. Use in the fit loop each step:
    ``opt_state = apply_lr(opt_state, trainer.lr)``."""
    hp = getattr(opt_state, "hyperparams", None)
    if hp is None or "learning_rate" not in hp:
        raise ValueError(
            "opt_state has no injected 'learning_rate' hyperparameter; build "
            "the optimizer with optax.inject_hyperparams(optax.sgd)(...)"
        )
    hp["learning_rate"] = jax.numpy.asarray(
        lr, dtype=jax.numpy.asarray(hp["learning_rate"]).dtype
    )
    return opt_state


def scale_momentum(trainer, factor: float):
    """Rescale SGD momentum buffers by `factor` (= new_lr/old_lr) — the
    reference's momentum-correction trick applied to ``optax.trace`` state
    (reference ``_keras/callbacks.py:118-136``)."""
    import optax

    opt_state = getattr(trainer, "opt_state", None)
    if opt_state is None:
        return

    def rescale(state):
        if isinstance(state, optax.TraceState):
            return optax.TraceState(
                trace=jax.tree_util.tree_map(lambda t: t * factor, state.trace)
            )
        return state

    trainer.opt_state = jax.tree_util.tree_map(
        rescale,
        opt_state,
        is_leaf=lambda s: isinstance(s, optax.TraceState),
    )
