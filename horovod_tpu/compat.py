"""Supported framework version floors.

The reference maintains a 22-image CI version matrix
(``.buildkite/gen-pipeline.sh:10-33``) spanning TF/torch/mxnet generations;
this environment has exactly one pin of each framework, so the matrix
collapses to a single *testable floor*: the oldest versions whose APIs this
package actually relies on. ``check_versions()`` runs at ``hvd.init()`` and
warns (never raises — an untested-but-newer stack should not be bricked) when
an installed framework is below its floor; ``tests/test_compat.py`` asserts
the floors against the live environment so a pin downgrade fails the suite.
"""

from __future__ import annotations

import warnings
from typing import Dict, List, Tuple

#: minimum supported versions and the API each floor is anchored to.
MIN_VERSIONS: Dict[str, Tuple[str, str]] = {
    # jax.shard_map at top level + NamedSharding/PartitionSpec semantics
    "jax": ("0.7.0", "top-level shard_map, check_vma flag"),
    # flax.linen with mutable batch_stats collections as used by models/
    "flax": ("0.8.0", "linen mutable collections"),
    # optax.GradientTransformation signature incl. params arg in update
    "optax": ("0.2.0", "GradientTransformation.update(params=...)"),
    # Keras 3 (keras.ops, .variables on optimizers) — TF 2.16 ships it
    "tensorflow": ("2.16.0", "Keras 3 optimizer/callback surface"),
    # torch.func-era autograd Functions + dlpack stability
    "torch": ("2.0.0", "autograd.Function with setup_context-free API"),
    "numpy": ("1.24.0", "dtype promotion rules the oracles assume"),
}


def _parse(v: str) -> List[int]:
    parts = []
    for tok in v.split("+")[0].split(".")[:3]:
        num = ""
        for ch in tok:
            if ch.isdigit():
                num += ch
            else:
                break
        parts.append(int(num or 0))
    while len(parts) < 3:
        parts.append(0)
    return parts


def check_versions(frameworks: Dict[str, str]) -> List[str]:
    """Return a list of floor violations for the given {name: version}."""
    problems = []
    for name, version in frameworks.items():
        if name not in MIN_VERSIONS or version is None:
            continue
        floor, why = MIN_VERSIONS[name]
        if _parse(version) < _parse(floor):
            problems.append(
                f"{name} {version} is below the supported floor {floor} "
                f"({why})"
            )
    return problems


def installed_versions() -> Dict[str, str]:
    """Versions of the already-imported frameworks (never imports anything:
    init must not drag torch/TF into a jax-only process)."""
    import sys

    out = {}
    for name in MIN_VERSIONS:
        mod = sys.modules.get(name)
        v = getattr(mod, "__version__", None) if mod is not None else None
        if v is not None:
            out[name] = v
    return out


def warn_if_unsupported() -> None:
    for msg in check_versions(installed_versions()):
        warnings.warn(f"horovod_tpu: {msg}", RuntimeWarning, stacklevel=3)
