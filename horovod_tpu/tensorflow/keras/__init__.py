"""``horovod_tpu.tensorflow.keras`` — import-path parity with the reference's
``horovod.tensorflow.keras`` (``horovod/tensorflow/keras/__init__.py``).

Keras 3 unified the standalone-keras and tf.keras stacks, so this module and
:mod:`horovod_tpu.keras` are the same implementation (the reference maintains
two parallel stacks over a shared ``_keras`` impl; here the shared impl IS
the module)."""

from horovod_tpu.keras import *  # noqa: F401,F403
from horovod_tpu.keras import (  # noqa: F401
    DistributedOptimizer,
    create_distributed_optimizer,
    load_model,
    callbacks,
)
