"""Gradient compression for TensorFlow tensors (reference
``horovod/tensorflow/compression.py``): cast floats to 16 bits before the
collective, cast back after. As in :mod:`horovod_tpu.compression`, the 16-bit
wire type is bfloat16 — TPU-native, same 2-byte footprint as the reference's
float16, no overflow scaling needed."""

from __future__ import annotations

import tensorflow as tf


class Compressor:
    """Interface for compressing and decompressing a given tensor
    (reference ``tensorflow/compression.py:22-33``)."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context_for_decompress)."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Compress floating-point gradients to 16 bits for the collective
    (reference ``tensorflow/compression.py:45-65``)."""

    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating:
            return tf.cast(tensor, tf.bfloat16), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tf.cast(tensor, ctx) if ctx is not None else tensor


class Compression:
    """Optional gradient compression algorithms used during allreduce
    (reference ``tensorflow/compression.py:68-75``)."""

    none = NoneCompressor
    fp16 = FP16Compressor
