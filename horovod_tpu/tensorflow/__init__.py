"""TensorFlow frontend: ``import horovod_tpu.tensorflow as hvd``.

Reference parity with ``horovod/tensorflow/__init__.py`` (0.19.2):
``allreduce`` with IndexedSlices→allgather handling and Average/Sum/Adasum
ops (reference ``tensorflow/__init__.py:43-122``), ``broadcast_variables``
(``:126-152``), ``DistributedGradientTape`` (``:478-535``), and a
``DistributedOptimizer`` for Keras optimizers (``:270-315`` /
``_keras/__init__.py:20-78``). TF1-style ``BroadcastGlobalVariablesHook`` and
``tf.compat.v1.train.Optimizer`` wrapping are out of scope — the rebuild
targets TF2/Keras-3 eager+``tf.function``, the configuration the reference's
own benchmark path uses (SURVEY.md §3.2).

Execution: collectives bridge to the TPU-native engine (XLA collectives over
the device mesh in-process; cross-process host path under ``hvdrun``) — TF
never talks to NCCL/MPI here.
"""

from __future__ import annotations

import tensorflow as tf

from horovod_tpu.basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, process_rank, process_size, is_homogeneous,
    mpi_threads_supported, mpi_enabled, gloo_enabled,
    num_rank_is_power_2, gpu_available,
    nccl_built, mpi_built, gloo_built, ccl_built,
    ddl_built, xla_built,
)
from horovod_tpu.tensorflow.compression import Compression  # noqa: F401
from horovod_tpu.tensorflow import mpi_ops
from horovod_tpu.tensorflow.mpi_ops import (  # noqa: F401
    Adasum, Average, ReduceOp, Sum,
    allgather, alltoall, broadcast, join,
)
from horovod_tpu.ops.collective import (  # noqa: F401
    allgather_object, broadcast_object,
)


def allreduce(tensor, op=Average, *, name=None, compression=Compression.none,
              sparse_as_dense: bool = False,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """Allreduce with the reference's full tensor handling
    (``tensorflow/__init__.py:43-122``): ``tf.IndexedSlices`` gradients become
    an allgather of values and indices (a distributed concatenation of the
    sparse updates) unless ``sparse_as_dense`` densifies them first;
    dense tensors are compressed, reduced, and decompressed."""
    if isinstance(tensor, tf.IndexedSlices):
        if sparse_as_dense:
            tensor = tf.convert_to_tensor(tensor)
        else:
            if op != Average and op != Sum:
                raise NotImplementedError(
                    "IndexedSlices allreduce supports Average and Sum only "
                    "(reference tensorflow/__init__.py:74-77)"
                )
            values = mpi_ops.allgather(tensor.values, name=name)
            indices = mpi_ops.allgather(
                tf.cast(tensor.indices, tf.int32),
                name=None if name is None else name + ".indices",
            )
            if op == Average:
                values = tf.cast(values, tensor.values.dtype) / size()
            return tf.IndexedSlices(
                values, tf.cast(indices, tensor.indices.dtype),
                dense_shape=tensor.dense_shape,
            )
    tensor_compressed, ctx = compression.compress(tensor)
    summed = mpi_ops.allreduce(
        tensor_compressed, op, name=name,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
    )
    return compression.decompress(summed, ctx)


def broadcast_variables(variables, root_rank: int = 0):
    """Assign every variable its root-rank value — the start-of-training /
    post-restore sync (reference ``tensorflow/__init__.py:126-152``)."""
    for var in variables:
        var.assign(mpi_ops.broadcast(tf.convert_to_tensor(var), root_rank))


class DistributedGradientTape:
    """Wrap ``tf.GradientTape`` so ``gradient()`` allreduces the gradients
    (reference ``tensorflow/__init__.py:478-535``)."""

    def __init__(self, gradtape, *, device_dense="", device_sparse="",
                 compression=Compression.none, sparse_as_dense=False,
                 op=Average):
        if not isinstance(gradtape, tf.GradientTape):
            raise ValueError("DistributedGradientTape wraps a tf.GradientTape")
        self._tape = gradtape
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense
        self._op = op

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, item):
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        return self._allreduce_grads(grads)

    def _allreduce_grads(self, grads):
        """Per-gradient allreduce (reference ``_make_allreduce_grads_fn``,
        ``tensorflow/__init__.py:234-255``)."""
        return tf.nest.map_structure(
            lambda g: g if g is None else allreduce(
                g, self._op, compression=self._compression,
                sparse_as_dense=self._sparse_as_dense,
            ),
            grads,
        )


def DistributedOptimizer(optimizer, *, compression=Compression.none,
                         sparse_as_dense=False, op=Average,
                         backward_passes_per_step: int = 1):
    """Wrap a Keras optimizer so gradient application first averages the
    gradients across ranks (reference ``tensorflow/__init__.py:270-315``;
    Keras path ``_keras/__init__.py:20-78``). ``op=Adasum`` selects the
    delta-style ``_AdasumOptimizerMixin`` subclass (reference
    ``tensorflow/__init__.py:317-411`` semantics, Keras-3 API) so the result
    stays a real Keras optimizer usable with ``model.compile``."""
    from horovod_tpu.keras import (
        create_distributed_optimizer as _create,
    )

    return _create(
        optimizer, compression=compression, sparse_as_dense=sparse_as_dense,
        op=op, backward_passes_per_step=backward_passes_per_step,
    )
