"""Functional collective ops on ``tf.Tensor`` values.

The TensorFlow face of the TPU-native collective engine (reference
``horovod/tensorflow/mpi_ops.py``). The reference registers custom TF kernels
that operate in-graph on device buffers (``tensorflow/mpi_ops.cc:286-473``);
here eager tensors cross the TF<->JAX boundary zero-copy via the dlpack
protocol (both runtimes implement ``__dlpack__``; the buffer is shared, not
copied), the collective executes as an XLA collective over the device mesh
(or the cross-process host path under ``hvdrun``), and the result returns to
TF the same way. Gradients are registered the same way the reference does
(``tensorflow/mpi_ops.py:110-201``): grad of allreduce is allreduce, grad of
allgather is a reduce-then-slice, grad of broadcast is allreduce with the
non-root contributions zeroed.

Inside ``tf.function`` graphs the bridge rides ``tf.py_function`` — the analog
of the reference's AsyncOpKernel boundary into the background thread.
``examples/tensorflow2_dlpack_microbench.py`` documents the per-collective
overhead of the dlpack path vs a forced host copy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import tensorflow as tf

from horovod_tpu import basics
from horovod_tpu.ops import collective as C
from horovod_tpu.ops.collective import Adasum, Average, ReduceOp, Sum

__all__ = [
    "Average", "Sum", "Adasum", "ReduceOp",
    "allreduce", "allgather", "broadcast", "alltoall",
    "join", "size", "rank", "local_size", "local_rank",
]

size = basics.size
rank = basics.rank
local_size = basics.local_size
local_rank = basics.local_rank


def _np(t) -> np.ndarray:
    return np.asarray(t)


def _dlpack_ok() -> bool:
    """dlpack imports commit the array to one device; that placement is only
    usable when the mesh is single-chip (the one-process-per-host TF
    deployment) or multi-process (the hostlocal path re-stages host-side
    regardless). A single-process multi-chip mesh needs an uncommitted host
    array so the eager shard_map can place it."""
    if not basics.is_initialized():
        return False
    if basics.process_size() > 1:
        return True
    return basics.mesh().devices.size == 1


def _tf_to_jax(t):
    """Zero-copy TF->JAX via the dlpack protocol (the cross-runtime analog
    of the reference's in-graph kernels reading device buffers directly,
    ``tensorflow/mpi_ops.cc:286-473``). Host-copy fallback for dtypes the
    protocol or the x64-disabled jax config cannot carry (bool, 64-bit) and
    for mesh layouts that need uncommitted inputs (see ``_dlpack_ok``)."""
    if t.dtype in (tf.bool, tf.int64, tf.uint64, tf.float64) or not _dlpack_ok():
        return jnp.asarray(np.asarray(t))
    try:
        return jax.dlpack.from_dlpack(t)
    except Exception:
        return jnp.asarray(np.asarray(t))


def _jax_to_tf(a):
    """Zero-copy JAX->TF; falls back to a host copy for arrays dlpack cannot
    export (multi-device/replicated arrays on a >1-chip mesh, bool)."""
    try:
        return tf.experimental.dlpack.from_dlpack(a.__dlpack__())
    except Exception:
        return tf.convert_to_tensor(np.asarray(a))


def _bridge(fn, inputs, out_dtype, out_shape=None):
    """Run jax-level `fn` on TF `inputs`; graph-safe via tf.py_function.

    Eager: dlpack in, dlpack out — no host round trip on a single-chip mesh.
    ``tf.py_function`` has no XLA kernel, so a multi-process graph containing
    this bridge cannot be compiled with ``jit_compile=True`` — the same
    limitation the reference's host-side enqueue boundary has; compile the
    step with ``jit_compile=False`` under ``hvdrun``. Single-process graphs
    never reach here (see ``_single_process_graph``)."""
    if tf.executing_eagerly():
        return _jax_to_tf(fn(*[_tf_to_jax(t) for t in inputs]))
    out = tf.py_function(
        lambda *ts: tf.convert_to_tensor(
            np.asarray(fn(*[t.numpy() for t in ts]))
        ),
        inputs,
        Tout=out_dtype,
    )
    if out_shape is not None:
        out.set_shape(out_shape)
    return out


def _single_process_graph() -> bool:
    """In a single-process graph the collectives on (replicated) TF tensors
    reduce to pure TF math — scale / tile / identity — which keeps the traced
    step XLA-compilable (``jit_compile=True``) with no host round-trip."""
    return not tf.executing_eagerly() and basics.process_size() == 1


def _allreduce_raw(tensor, op, name, prescale_factor=1.0, postscale_factor=1.0):
    if _single_process_graph():
        n = basics.size()
        t = tensor * prescale_factor if prescale_factor != 1.0 else tensor
        if op == Sum:
            out = t * tf.cast(n, t.dtype) if t.dtype.is_floating else t * n
        else:  # Average / Adasum of identical replicas is the identity
            out = t
        return out * postscale_factor if postscale_factor != 1.0 else out
    return _bridge(
        lambda a: C.allreduce(a, op, name=name,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor),
        [tensor], tensor.dtype, tensor.shape,
    )


def allreduce(tensor, op: ReduceOp = Average, *, name=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """Sum/average `tensor` across ranks, differentiably (reference
    ``tensorflow/mpi_ops.py:66-107`` + grad ``:110-143``)."""
    op = ReduceOp(op)

    @tf.custom_gradient
    def _fn(t):
        out = _allreduce_raw(t, op, name, prescale_factor, postscale_factor)

        def grad(dy):
            return _allreduce_raw(dy, op, None, prescale_factor,
                                  postscale_factor)

        return out, grad

    return _fn(tensor)


def allgather(tensor, *, name=None):
    """Concatenate `tensor` from all ranks on dimension 0, differentiably
    (reference ``tensorflow/mpi_ops.py:145-167``; grad splits the upstream
    gradient by rank and allreduce-sums each piece, ``:110-139``)."""
    n = basics.size()

    @tf.custom_gradient
    def _fn(t):
        if _single_process_graph():
            out = tf.tile(t, [n] + [1] * (len(t.shape) - 1))
        else:
            out = _bridge(
                lambda a: C.allgather(a, name=name), [t], t.dtype,
            )

        def grad(dy):
            # sum the gathered gradient across ranks, then take this rank's
            # slice (reference HorovodAllgatherGrad, mpi_ops.py:118-139)
            summed = _allreduce_raw(dy, Sum, None)
            dim0 = tf.shape(summed)[0] // n
            return summed[basics.rank() * dim0:(basics.rank() + 1) * dim0]

        return out, grad

    return _fn(tensor)


def broadcast(tensor, root_rank: int = 0, *, name=None):
    """Broadcast `tensor` from `root_rank` to all ranks, differentiably
    (reference ``tensorflow/mpi_ops.py:169-201``; grad allreduces and zeroes
    on non-root ranks, ``:174-189``)."""

    @tf.custom_gradient
    def _fn(t):
        if _single_process_graph():
            out = tf.identity(t)
        else:
            out = _bridge(
                lambda a: C.broadcast(a, root_rank, name=name),
                [t], t.dtype, t.shape,
            )

        def grad(dy):
            g = _allreduce_raw(dy, Sum, None)
            if basics.rank() != root_rank:
                g = tf.zeros_like(g)
            return g

        return out, grad

    return _fn(tensor)


def alltoall(tensor, *, name=None):
    """Even all-to-all scatter/gather over dimension 0 (first-class on TPU:
    ``lax.all_to_all`` rides ICI; see ``horovod_tpu/ops/collective.py``)."""
    return _bridge(
        lambda a: C.alltoall(a, name=name), [tensor], tensor.dtype,
    )


def join() -> int:
    """Uneven-data join (reference ``torch/mpi_ops.py:511-524``; TF gained
    join upstream post-0.19)."""
    return C.join()
