"""Elastic world-size training: survive rank loss/join without a restart.

The launcher's restart-in-place story (PR 2) covers whole-job cycles — every
rank preempted, every slot relaunched into a fresh rendezvous. What it could
not do (``run/runner.py`` said so outright) is *re-form the job at a new
world size* when one rank dies while its peers are still healthy. Elastic
Horovod and TorchElastic showed that preemption-heavy fleets need exactly
that; this module assembles it from the pieces the previous PRs built:

- **membership** rides the rendezvous KV server's heartbeat-scoped TTL keys
  (:class:`~horovod_tpu.run.rendezvous.KVStoreServer`): each rank refreshes
  ``/elastic/hb/<rank>``; a rank that stops (death, preemption) tombstones
  on TTL expiry and readers get
  :class:`~horovod_tpu.run.rendezvous.DeadRankError` instead of a burned
  deadline.
- **epochs** are generation numbers: every membership change bumps the
  generation, publishes the new member list, and fences on a per-generation
  ack barrier (:meth:`ElasticCoordinator.await_acks`) so no rank trains
  under a stale mesh.
- **re-formation** uses the now-idempotent ``hvd.shutdown() → hvd.init()``
  cycle (stale eager-kernel caches are dropped with the old mesh) to build
  a fresh mesh over the surviving ranks' devices — no process relaunch.
- **state** rolls back to the last *committed* step via an in-memory,
  host-offloaded snapshot (:func:`horovod_tpu.training.host_snapshot`) —
  a rank that died mid-step leaves the survivors' in-flight step
  unreproducible at the new size, so the resize replays from the snapshot —
  and the ZeRO-1 optimizer state is re-packed for the new world size with
  :func:`horovod_tpu.checkpoint.consolidate_opt_state`.
- **determinism**: the chaos charges ``rank_fail=N`` /
  ``rank_fail_at_step=K`` / ``rank_join_at_step=K`` drive the whole path on
  the 8-device CPU mesh in tier-1 (``tests/test_elastic.py``), including
  the pinned acceptance trajectory: shrink 8→6, allclose against a fresh
  6-rank run from the same snapshot, grow back 6→8.

Scope: the in-process resize is single-controller SPMD (one process owns
the mesh). Multi-controller jobs get elasticity at the launcher level
(``hvdrun --min-workers/--max-workers``): a permanently lost slot no longer
kills the job while the survivor count stays ≥ ``--min-workers``, and a
blacklisted host is re-admitted after ``HOROVOD_HOST_STRIKE_DECAY``.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Callable, Iterable, List, Optional, Sequence

from horovod_tpu.observability import clock as _obs_clock
from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.observability import straggler as _straggler
from horovod_tpu.resilience import chaos as _chaos, health as _health
from horovod_tpu.resilience import loop as _loop

__all__ = [
    "ElasticCoordinator",
    "ElasticRun",
    "WorldChanged",
    "WorldTooSmall",
    "run",
]

logger = logging.getLogger("horovod_tpu.resilience.elastic")

MIN_WORKERS_ENV = "HOROVOD_ELASTIC_MIN_WORKERS"
MAX_WORKERS_ENV = "HOROVOD_ELASTIC_MAX_WORKERS"

#: seconds the generation ack barrier waits before declaring the epoch dead
BARRIER_TIMEOUT_ENV = "HOROVOD_ELASTIC_BARRIER_TIMEOUT"


class WorldChanged(Exception):
    """Internal control flow: membership changed at `step`'s boundary; the
    elastic driver unwinds the inner training segment, re-forms the mesh
    over `alive`, and resumes. ``lost``/``joined`` carry the delta."""

    def __init__(self, step: int, alive: Sequence[int],
                 lost: Sequence[int] = (), joined: Sequence[int] = ()):
        self.step = step
        self.alive = tuple(alive)
        self.lost = tuple(lost)
        self.joined = tuple(joined)
        super().__init__(
            f"membership changed at step {step}: alive={list(alive)} "
            f"lost={list(lost)} joined={list(joined)}"
        )


class WorldTooSmall(RuntimeError):
    """Surviving ranks fell below ``min_workers``; the job cannot re-form.
    The driver wrote an emergency checkpoint (when a ``checkpoint_dir`` was
    given) before raising, so a relaunch resumes cleanly."""

    def __init__(self, alive: int, min_workers: int, step: int):
        self.alive = alive
        self.min_workers = min_workers
        self.step = step
        super().__init__(
            f"only {alive} rank(s) alive at step {step}, below "
            f"min_workers={min_workers}"
        )


class ElasticCoordinator:
    """Membership over the rendezvous KV plane: heartbeats, liveness,
    generation-numbered epochs with an ack barrier.

    Keys (all under ``/<scope>``):

    - ``/hb/<rank>`` — TTL'd heartbeat; expiry (or an explicit
      :meth:`mark_dead`) tombstones the rank.
    - ``/gen`` — the current epoch record: ``{"generation": G, "ranks":
      [...]}``; every resize rewrites it.
    - ``/ack/<G>/<rank>`` — the epoch barrier: a member acks generation G
      once it has re-formed; :meth:`await_acks` blocks for the full set and
      fails fast with :class:`DeadRankError` when a member dies
      mid-barrier instead of burning the deadline.

    Pass a started :class:`~horovod_tpu.run.rendezvous.KVStoreServer` to
    share the launcher's store; by default the coordinator owns a private,
    non-serving store (direct method calls — the single-controller case).
    """

    def __init__(self, server=None, *, ttl: Optional[float] = None,
                 scope: str = "elastic"):
        from horovod_tpu.run import rendezvous as _rdv

        self._rdv = _rdv
        self._own = server is None
        self._server = server if server is not None else _rdv.KVStoreServer()
        self._scope = "/" + scope.strip("/")
        self._ttl = ttl if ttl is not None else _rdv.default_heartbeat_ttl()
        self._generation = 0

    # ------------------------------------------------------------ liveness

    @property
    def server(self):
        return self._server

    @property
    def generation(self) -> int:
        return self._generation

    def _hb_key(self, rank: int) -> str:
        return f"{self._scope}/hb/{rank}"

    def heartbeat(self, rank: int) -> None:
        """Refresh `rank`'s liveness (also re-admits a tombstoned rank —
        the rejoin signal)."""
        self._server.put(self._hb_key(rank), b"1", ttl=self._ttl)

    def heartbeat_all(self, ranks: Iterable[int]) -> None:
        for r in ranks:
            self.heartbeat(r)

    def mark_dead(self, rank: int) -> None:
        """Explicitly tombstone `rank` (deterministic kill: the chaos path
        and controlled drains use this instead of waiting out the TTL)."""
        self._server.delete(self._hb_key(rank), tombstone=True)

    def alive(self) -> List[int]:
        """Ranks with unexpired heartbeats, ascending."""
        prefix = f"{self._scope}/hb/"
        out = []
        for k in self._server.live_keys(prefix):
            try:
                out.append(int(k[len(prefix):]))
            except ValueError:
                continue
        return sorted(out)

    # -------------------------------------------------------------- epochs

    def begin_generation(self, ranks: Sequence[int]) -> int:
        """Open a new epoch over `ranks`; returns its generation number.
        Mirrored into ``resilience_elastic_generation`` /
        ``resilience_elastic_world_size`` so the transition is observable
        from the metrics endpoint alone. Prior generations' ack-barrier
        keys are retired — every barrier on generation G has resolved
        before G+1 opens, and without the prune the store would grow by
        one key per member per resize forever."""
        if self._generation and hasattr(self._server, "prune"):
            self._server.prune(f"{self._scope}/ack/")
        self._generation += 1
        record = {"generation": self._generation, "ranks": sorted(ranks)}
        self._server.put(
            f"{self._scope}/gen", json.dumps(record).encode())
        if _metrics.enabled():
            _metrics.gauge(
                "resilience_elastic_generation",
                help="current elastic membership epoch",
            ).set(self._generation)
            _metrics.gauge(
                "resilience_elastic_world_size",
                help="ranks in the current elastic epoch",
            ).set(len(record["ranks"]))
        return self._generation

    def membership(self) -> Optional[dict]:
        """The current epoch record, or None before the first epoch."""
        blob = self._server.get(f"{self._scope}/gen")
        return None if blob is None else json.loads(blob)

    def ack(self, generation: int, rank: int) -> None:
        self._server.put(f"{self._scope}/ack/{generation}/{rank}", b"1")

    def await_acks(self, generation: int, ranks: Sequence[int],
                   timeout: Optional[float] = None) -> None:
        """Epoch barrier: block until every rank in `ranks` acked
        `generation`. A member dying mid-barrier raises
        :class:`~horovod_tpu.run.rendezvous.DeadRankError` with its rank id
        immediately (heartbeat-scoped fast-fail), so the caller can drop it
        and open the next epoch rather than waiting out the deadline."""
        if timeout is None:
            timeout = float(os.environ.get(BARRIER_TIMEOUT_ENV, "60"))
        self._server.wait_for(
            [f"{self._scope}/ack/{generation}/{r}" for r in ranks],
            timeout=timeout,
            hb_scope=f"{self._scope}/hb",
        )

    def close(self) -> None:
        if self._own:
            try:
                self._server.close()
            except Exception as e:
                logger.debug("KV server close failed: %s", e)


def _default_reshard(state: Any, new_size: int) -> Any:
    """Re-pack a state pytree for `new_size` ranks: a dict carrying
    ``params`` + ``opt_state`` gets its optimizer state consolidated
    (ZeRO-1 ``[N, shard]`` leaves re-packed, EF residual mass preserved;
    plain states pass through untouched — ``consolidate_opt_state`` is safe
    on any optimizer state). Everything else is returned as-is: replicated
    DP state is world-size-independent by construction."""
    if isinstance(state, dict) and "opt_state" in state and "params" in state:
        from horovod_tpu import checkpoint as _checkpoint

        out = dict(state)
        out["opt_state"] = _checkpoint.consolidate_opt_state(
            out["opt_state"], out["params"], to_size=new_size)
        return out
    return state


class ElasticRun:
    """The elastic driver: wraps :func:`horovod_tpu.resilience.run` in
    membership epochs. Each epoch trains under one world size; a membership
    change unwinds the inner loop, re-forms the mesh, reshards state, and
    re-enters. See :func:`run` for the functional spelling and argument
    docs."""

    def __init__(
        self,
        step_builder: Callable[[int], Callable[[Any, int], Any]],
        *,
        min_workers: Optional[int] = None,
        max_workers: Optional[int] = None,
        snapshot_every: int = 1,
        reshard_fn: Optional[Callable[[Any, int], Any]] = None,
        coordinator: Optional[ElasticCoordinator] = None,
        devices: Optional[Sequence] = None,
        publisher=None,
        publish_every: int = 0,
    ):
        if min_workers is None:
            min_workers = int(os.environ.get(MIN_WORKERS_ENV, "1"))
        self._step_builder = step_builder
        self._min_workers = max(1, min_workers)
        self._max_workers = max_workers
        self._snapshot_every = max(1, snapshot_every)
        self._reshard = reshard_fn or _default_reshard
        self._coord = coordinator
        self._own_coord = coordinator is None
        self._devices = list(devices) if devices is not None else None
        self._publisher = publisher
        self._publish_every = max(0, publish_every)
        self._alive: List[int] = []
        self._failed: List[int] = []
        self._committed_step = 0
        self._committed: Any = None
        #: input-pipeline cursors snapshotted WITH the committed state: a
        #: rollback that rewinds the weights must rewind the sample
        #: stream to the same boundary or the replay consumes the wrong
        #: batches (docs/data.md)
        self._committed_cursors: dict = {}
        self._published_step: Optional[int] = None
        self._has_guard: Optional[bool] = None  # lazily probed once
        #: (step, staged verdict) read one boundary late on non-commit
        #: steps — the guard's observability without fencing every step
        self._staged: Optional[tuple] = None
        self._numerics_rollbacks = 0
        self._recovering_until: Optional[int] = None
        self._warned_unevictable: set = set()

    # ----------------------------------------------------------- internals

    def _form(self, ranks: Sequence[int]) -> None:
        """(Re-)build the mesh over `ranks`' devices on this live process —
        the no-relaunch membership change. Rank r keeps device r, so a
        survivor's device assignment is stable across generations."""
        from horovod_tpu import basics

        if basics.is_initialized():
            if basics.process_size() > 1:
                raise NotImplementedError(
                    "in-process elastic resize is single-controller only; "
                    "multi-process jobs are resized at the launcher "
                    "(hvdrun --min-workers/--max-workers)"
                )
            basics.shutdown()
        basics.init(devices=[self._devices[r] for r in ranks])

    def _poll_membership(self, step: int) -> None:
        """Step-boundary membership sweep: refresh survivors' heartbeats,
        fire any armed chaos charges, and compare the KV liveness view with
        the current epoch. Raises :class:`WorldChanged` on a delta."""
        coord = self._coord
        coord.heartbeat_all(self._alive)
        # quarantine eviction: a rank the numerics cross-check flagged as
        # publishing corrupt gradient fingerprints is tombstoned here —
        # the same 8→7 shrink path a dead rank takes (never rank 0, the
        # driver). Lazy import: this module must stay stdlib at import.
        from horovod_tpu.resilience import numerics as _numerics

        unevictable = []
        retry = []
        for r in _numerics.take_corrupt_ranks():
            if r == 0:
                # the driver cannot tombstone itself — but the publish
                # gate must STAY closed, so the verdict goes back in the
                # quarantine set instead of silently draining
                unevictable.append(r)
            elif r in self._alive:
                logger.warning(
                    "elastic: evicting numerically corrupt rank %d", r)
                try:
                    coord.mark_dead(r)
                except Exception as e:
                    # a transient KV error must NOT lose the verdict: the
                    # publish gate keys on quarantine_pending(), so a
                    # drained-but-unevicted rank would re-open publication
                    # from a fleet that still contains it. Requeue and
                    # retry at the next boundary sweep.
                    retry.append(r)
                    logger.warning(
                        "elastic: eviction of corrupt rank %d failed "
                        "(%s); requeued for the next sweep", r, e)
            # a rank no longer alive was already evicted/dead: drop it
        if retry:
            _numerics.requeue_corrupt_ranks(retry)
        if unevictable:
            _numerics.requeue_corrupt_ranks(unevictable)
            for r in set(unevictable) - self._warned_unevictable:
                self._warned_unevictable.add(r)
                logger.error(
                    "elastic: rank %d flagged numerically corrupt but "
                    "cannot be evicted (single-controller driver); "
                    "weight publication stays gated until "
                    "numerics.clear_quarantine()", r)
        # hung-rank eviction (HOROVOD_HANG_EVICT=1): a rank the hang
        # diagnosis named missing is tombstoned like a corrupt one — the
        # survivors re-form smaller instead of waiting forever
        from horovod_tpu.observability import flight as _flight

        hung_retry = []
        for r in _flight.take_hung_ranks():
            if r != 0 and r in self._alive:
                logger.warning("elastic: evicting hung rank %d", r)
                try:
                    coord.mark_dead(r)
                except Exception as e:
                    # a transient KV error must NOT lose the verdict: the
                    # watchdog will not re-derive it for the same stall
                    # (one firing per episode), so requeue for the next
                    # sweep — the corrupt-rank convention above
                    hung_retry.append(r)
                    logger.warning(
                        "elastic: eviction of hung rank %d failed (%s); "
                        "requeued for the next sweep", r, e)
        if hung_retry:
            _flight.requeue_hung_ranks(hung_retry)
        if _chaos.enabled():
            n_fail = _chaos.take_rank_fail(step)
            if n_fail:
                # highest ranks first, never rank 0 (the driver)
                victims = [r for r in sorted(self._alive) if r != 0][-n_fail:]
                for r in victims:
                    coord.mark_dead(r)
            # check _failed FIRST: take_rank_join pops the charge, and a
            # join armed at/before the fail step must stay armed until
            # there is actually someone to re-admit
            if self._failed and _chaos.take_rank_join(step):
                for r in self._failed:
                    coord.heartbeat(r)  # rejoin = heartbeat resumes
        alive = coord.alive()
        # a heartbeat from a rank this controller has no device for (a
        # shared store serving several parties, a stray key) must be
        # ignored, not crash _form with an IndexError later
        known = [r for r in alive if 0 <= r < len(self._devices)]
        if len(known) < len(alive):
            logger.warning(
                "elastic: ignoring heartbeats for unknown ranks %s "
                "(have %d devices)",
                sorted(set(alive) - set(known)), len(self._devices),
            )
        alive = known
        if self._max_workers is not None:
            alive = alive[: self._max_workers]
        if set(alive) != set(self._alive):
            lost = sorted(set(self._alive) - set(alive))
            joined = sorted(set(alive) - set(self._alive))
            for r in lost:
                _health.record_rank_lost(r)
            raise WorldChanged(step, alive, lost, joined)

    def _sync_observability(self, gen: int) -> None:
        """Re-anchor the fleet-observability layer on an epoch boundary:
        collective correlation keys carry the new generation (keys never
        collide across epochs) and the clock offset vs the KV server is
        re-estimated — a resize is exactly when the host set (and with it
        the skew picture) may have changed. Best-effort: observability
        must never fail a resize."""
        _straggler.set_generation(gen)
        try:
            from horovod_tpu.observability import flight as _flight

            _flight.record(
                "epoch", generation=int(gen), alive=list(self._alive),
            )
        except Exception as e:
            logger.debug("flight epoch event skipped: %s", e)
        try:
            from horovod_tpu import basics as _basics

            rank = (
                _basics.process_rank() if _basics.is_initialized() else 0
            )
            _obs_clock.refresh_from_kv(
                self._coord.server, rank=rank, generation=gen)
        except Exception as e:
            logger.debug("post-resize clock re-sync failed: %s", e)

    def _commit(self, step: int, state: Any) -> None:
        from horovod_tpu.training import host_snapshot

        self._committed_step = step
        self._committed = host_snapshot(state)
        try:
            from horovod_tpu.data import sampler as _data_sampler

            self._committed_cursors = _data_sampler.export_state()
        except Exception as e:
            logger.debug("loader cursor commit skipped: %s", e)

    def _restore_cursors(self) -> None:
        """Rewind every registered loader to the committed boundary (the
        state just rolled back there). Best-effort: a run without a
        registered loader has nothing to rewind."""
        try:
            from horovod_tpu.data import sampler as _data_sampler

            _data_sampler.restore_state(self._committed_cursors)
        except Exception as e:
            logger.debug("loader cursor rollback skipped: %s", e)

    def _wrap(self, step_fn):
        def wrapped(state, step):
            from horovod_tpu.resilience import numerics as _numerics

            # this wrapper owns the fingerprint boundary (authoritative
            # step numbering across resizes/rollbacks); the generic
            # InstrumentedStep hook inside step_fn stands down
            _numerics.claim_boundary()
            self._poll_membership(step)
            out = step_fn(state, step)
            # numerics policy: read the guard verdict carried in the
            # state (probed once — states without a guard never pay the
            # boundary sync), publish/cross-check the fingerprint, and
            # escalate a bad streak to a rollback
            if self._has_guard is None:
                self._has_guard = bool(_numerics.find_guard_states(out))
            v = None
            if self._has_guard:
                committing = (step + 1) % self._snapshot_every == 0
                if _numerics.fingerprint_enabled() or committing:
                    # exact (synchronous) read: the per-step fingerprint
                    # plane needs THIS step's record, and a commit must
                    # be gated on THIS step's verdict (never snapshot
                    # mid-incident). Drain any staged verdict first so
                    # its chaos accounting and gauges are not lost.
                    if self._staged is not None:
                        _numerics.note_step_staged(*self._staged)
                        self._staged = None
                    v = _numerics.note_step(step, out)
                else:
                    # lagged read, one boundary late: fence on the
                    # PREVIOUS step's staged scalars while this step
                    # still runs in the background — a synchronous read
                    # here blocks the host on every step's completion
                    # and destroys async-dispatch pipelining in the hot
                    # loop. The rollback policy already tolerates
                    # MAX_BAD steps of latency, so a one-step-late
                    # verdict is safe.
                    if self._staged is not None:
                        v = _numerics.note_step_staged(*self._staged)
                    self._staged = (step, _numerics.stage_verdict(out))
            if _numerics.fingerprint_enabled():
                _numerics.boundary(step)
            if v is not None and v["bad_streak"] >= \
                    _numerics.max_consecutive_bad():
                raise _numerics.NumericsRollback(step, v["bad_streak"])
            bad_now = v is not None and v["bad_streak"] > 0
            if (step + 1) % self._snapshot_every == 0 and not bad_now:
                # never commit a mid-incident snapshot: rolling back to a
                # state whose guard already counts a bad streak would
                # re-trigger the rollback it is recovering from
                self._commit(step + 1, out)
                if (
                    self._recovering_until is not None
                    and step + 1 > self._recovering_until
                ):
                    # sound progress COMMITTED past the incident that
                    # forced the last rollback: the budget guards against
                    # rollbacks *without* progress, so it resets here —
                    # isolated transient incidents days apart must not
                    # accumulate into a FATAL
                    self._numerics_rollbacks = 0
                    self._recovering_until = None
            self._maybe_publish(step + 1)
            return out

        return wrapped

    def _maybe_publish(self, step: int) -> None:
        """Publish the COMMITTED snapshot on the publish cadence — the
        consolidated state (host-offloaded, reshard-safe), not the live
        device tree, so a publication is always replayable after a resize.
        A fence abort here means a concurrent party resized under us; the
        resize path republishes, so it is not an error."""
        if self._publisher is None or self._publish_every <= 0:
            return
        if step % self._publish_every or self._committed is None:
            return
        if self._committed_step == self._published_step:
            # snapshot_every > publish_every: the committed tree has not
            # moved since the last publication — re-publishing it would
            # mint identical generations and reset subscriber staleness
            # for weights that never changed
            return
        from horovod_tpu import serving as _serving

        try:
            self._publisher.publish(self._committed, self._committed_step)
            self._published_step = self._committed_step
        except _serving.PublishAborted as e:
            logger.warning("publication fenced off mid-resize: %s", e)
        except _serving.PublishError as e:
            logger.warning(
                "weight publication at step %d failed: %s", step, e)

    def _resize(self, wc: WorldChanged):
        """Handle one membership change: rollback to the last committed
        snapshot, mesh re-formation, state reshard, epoch barrier. Returns
        ``(state, next_step)``.

        Both directions resume from the committed snapshot: on a loss the
        interrupted step is unreproducible at the old size, and on a join
        the snapshot IS the boundary state (with ``snapshot_every=1``
        nothing is replayed) — the one source of truth keeps the
        post-resize trajectory bit-deterministic."""
        t0 = time.monotonic()
        alive = list(wc.alive)
        if len(alive) < self._min_workers:
            raise WorldTooSmall(len(alive), self._min_workers, wc.step)
        state = self._committed
        next_step = self._committed_step
        if wc.lost:
            self._failed = sorted(set(self._failed) | set(wc.lost))
        if wc.joined:
            self._failed = [r for r in self._failed if r not in wc.joined]
        if _metrics.enabled() and wc.step > next_step:
            _metrics.counter(
                "resilience_elastic_rollback_steps",
                help="steps replayed after rolling back to the last "
                     "committed snapshot",
            ).inc(wc.step - next_step)
        old_size = len(self._alive)
        self._alive = alive
        self._form(alive)
        state = self._reshard(state, len(alive))
        # the sample stream rolls back WITH the state, and the loaders
        # are fenced on the same generation as the mesh: the survivors
        # repartition the remaining epoch under the new world size with
        # no sample dropped and none double-visited (docs/data.md)
        self._restore_cursors()
        gen = self._coord.begin_generation(alive)
        for r in alive:
            self._coord.ack(gen, r)
        self._coord.await_acks(gen, alive)
        try:
            from horovod_tpu.data import sampler as _data_sampler

            _data_sampler.generation_fence(gen, len(alive))
        except Exception as e:
            logger.debug("loader generation fence skipped: %s", e)
        self._sync_observability(gen)
        dt = time.monotonic() - t0
        if _metrics.enabled():
            _metrics.counter(
                "resilience_elastic_membership_changes",
                help="elastic resizes by direction",
                kind="grow" if len(alive) > old_size else "shrink",
            ).inc()
            _metrics.histogram(
                "resilience_elastic_resize_seconds",
                help="wall time of one membership change (rollback + mesh "
                     "re-formation + reshard + epoch barrier)",
            ).observe(dt)
        # NOTE: tools/tpu_window_watcher.py matches this exact prefix to
        # classify a mid-rung resize as healthy progress, not a wedge.
        logger.warning(
            "elastic: resized to world size %d (generation %d, lost=%s "
            "joined=%s) in %.3fs",
            len(alive), gen, list(wc.lost), list(wc.joined), dt,
        )
        if self._publisher is not None and self._published_step != next_step:
            # republish from the post-resize consolidated state: any
            # generation the fence aborted mid-resize is superseded here,
            # and subscribers see the exact weights the replayed steps
            # start from (off-cadence on purpose — the resize IS the
            # event; skipped only when this exact committed step already
            # published, e.g. a resize landing right on the cadence)
            from horovod_tpu import serving as _serving

            try:
                self._publisher.publish(state, next_step)
                self._published_step = next_step
            except _serving.PublishError as e:
                logger.warning(
                    "post-resize weight publication failed: %s", e)
        return state, next_step

    def _numerics_rollback(self, nr):
        """Handle one :class:`numerics.NumericsRollback`: replay from the
        last committed snapshot with a FRESH data epoch (the replay salt
        data pipelines fold into batch selection), bounded by
        ``HOROVOD_NUMERICS_MAX_ROLLBACKS``. Exhausting the budget is
        FATAL — the run cannot make numerically sound progress."""
        from horovod_tpu.resilience import numerics as _numerics

        self._numerics_rollbacks += 1
        if self._numerics_rollbacks > _numerics.max_rollbacks():
            _health.record_fatal(
                f"numerics rollback budget exhausted "
                f"({self._numerics_rollbacks - 1} rollbacks)"
            )
            raise _numerics.NumericsError(
                f"still seeing {nr.streak} consecutive bad steps after "
                f"{self._numerics_rollbacks - 1} rollback(s); giving up"
            ) from nr
        if self._committed is None:
            _health.record_fatal("numerics rollback with no snapshot")
            raise _numerics.NumericsError(
                "consecutive bad steps before any committed snapshot"
            ) from nr
        self._recovering_until = nr.step + 1
        epoch = _numerics.bump_replay_epoch()
        if _metrics.enabled():
            _metrics.counter(
                "numerics_rollbacks",
                help="rollbacks to the committed snapshot forced by "
                     "consecutive bad steps",
            ).inc()
            if nr.step >= self._committed_step:
                _metrics.counter(
                    "numerics_rollback_steps",
                    help="steps replayed after a numerics rollback",
                ).inc(nr.step + 1 - self._committed_step)
        logger.warning(
            "numerics: %d consecutive bad steps at step %d; rolling back "
            "to committed step %d (replay epoch %d)",
            nr.streak, nr.step, self._committed_step, epoch,
        )
        # rewind the sample cursors to the committed boundary; the bumped
        # replay epoch (folded into batch selection by the loader) makes
        # the replayed steps draw FRESH batches from that same cursor
        self._restore_cursors()
        return self._committed, self._committed_step

    # -------------------------------------------------------------- driver

    def run(
        self,
        state: Any,
        *,
        num_steps: int,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 0,
        callbacks: Optional[Iterable] = None,
    ) -> Any:
        import jax

        from horovod_tpu import basics

        if self._devices is None:
            self._devices = list(jax.devices())
        cap = self._max_workers or int(
            os.environ.get(MAX_WORKERS_ENV, "0")
        ) or len(self._devices)
        self._max_workers = min(cap, len(self._devices))
        if self._coord is None:
            self._coord = ElasticCoordinator()
        if self._publisher is not None and self._publisher.fence_fn is None:
            # the elastic generation IS the publish fence: a resize bumps
            # it, aborting any in-flight generation before it can commit
            self._publisher.fence_fn = lambda: self._coord.generation

        # everything past coordinator creation sits inside the try: a
        # failed initial formation or a bad checkpoint dir must not leak
        # the owned coordinator's bound socket
        try:
            # initial formation at full strength (bounded by max_workers);
            # the admissible band applies from step 0, not just on
            # resizes — a host that cannot field min_workers must error,
            # not silently train below the floor for the whole run
            self._alive = list(range(self._max_workers))
            if len(self._alive) < self._min_workers:
                raise WorldTooSmall(
                    len(self._alive), self._min_workers, 0)
            if not (
                basics.is_initialized()
                and basics.size() == len(self._alive)
            ):
                self._form(self._alive)
            self._coord.heartbeat_all(self._alive)
            gen = self._coord.begin_generation(self._alive)
            for r in self._alive:
                self._coord.ack(gen, r)
            self._coord.await_acks(gen, self._alive)
            try:
                from horovod_tpu.data import sampler as _data_sampler

                _data_sampler.generation_fence(gen, len(self._alive))
            except Exception as e:
                logger.debug("loader generation fence skipped: %s", e)
            self._sync_observability(gen)

            next_step = 0
            if checkpoint_dir:
                resumed = _loop.resume_state(checkpoint_dir)
                if resumed is not None:
                    next_step, state = resumed
                    state = self._reshard(state, len(self._alive))
                    logger.info(
                        "elastic: resumed from checkpoint at step %d",
                        next_step)
            self._commit(next_step, state)

            from horovod_tpu.resilience import numerics as _numerics

            built_for: Optional[tuple] = None  # membership the fn targets
            step_fn = None
            while True:
                # key the cache on MEMBERSHIP, not count: a simultaneous
                # loss+join keeps the size but re-forms the mesh over a
                # different device set — only a numerics rollback (same
                # membership, replay) may reuse the compiled step
                membership = tuple(self._alive)
                if step_fn is None or built_for != membership:
                    step_fn = self._step_builder(len(self._alive))
                    built_for = membership
                try:
                    return _loop.run(
                        self._wrap(step_fn),
                        state,
                        num_steps=num_steps,
                        checkpoint_dir=checkpoint_dir,
                        checkpoint_every=checkpoint_every,
                        start_step=next_step,
                        callbacks=callbacks,
                    )
                except WorldChanged as wc:
                    # a staged verdict from the broken mesh / abandoned
                    # trajectory must not be read against the new one
                    self._staged = None
                    state, next_step = self._resize(wc)
                except _numerics.NumericsRollback as nr:
                    self._staged = None
                    state, next_step = self._numerics_rollback(nr)
        except WorldTooSmall:
            # _committed is None when the floor broke before any snapshot
            # (initial formation): nothing to save, just surface the error
            if checkpoint_dir and self._committed is not None:
                from horovod_tpu import checkpoint as _checkpoint

                _checkpoint.save(
                    checkpoint_dir, self._committed_step,
                    _checkpoint.attach_data_state(
                        {"step": self._committed_step,
                         "state": self._committed},
                        cursors=self._committed_cursors,
                    ),
                    force=True, fence=False,
                )
            raise
        finally:
            # hand the fingerprint boundary back: a standalone
            # InstrumentedStep loop after this run must publish again
            from horovod_tpu.resilience import numerics as _numerics

            if self._staged is not None:
                # the LAST step's lagged verdict has no next boundary —
                # drain it so its gauges/chaos accounting land (best
                # effort: the mesh may be the thing that just died)
                try:
                    _numerics.note_step_staged(*self._staged)
                except Exception as e:
                    logger.debug("staged verdict drain failed: %s", e)
                self._staged = None
            _numerics.release_boundary()
            if self._own_coord and self._coord is not None:
                self._coord.close()


def run(
    step_builder: Callable[[int], Callable[[Any, int], Any]],
    state: Any,
    *,
    num_steps: int,
    min_workers: Optional[int] = None,
    max_workers: Optional[int] = None,
    snapshot_every: int = 1,
    reshard_fn: Optional[Callable[[Any, int], Any]] = None,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 0,
    callbacks: Optional[Iterable] = None,
    coordinator: Optional[ElasticCoordinator] = None,
    devices: Optional[Sequence] = None,
    publisher=None,
    publish_every: int = 0,
) -> Any:
    """Drive elastic training: ``state = step_fn(state, i)`` where
    ``step_fn = step_builder(world_size)`` is rebuilt every time membership
    changes. Returns the final state.

    - `step_builder(world_size)`: called after each mesh (re-)formation —
      ``hvd.mesh()`` is the fresh mesh — and must return a ``(state, step)
      -> state`` step callable for that world size.
    - `min_workers` / `max_workers` (env ``HOROVOD_ELASTIC_MIN_WORKERS`` /
      ``HOROVOD_ELASTIC_MAX_WORKERS``): the admissible world-size band.
      Falling below `min_workers` raises :class:`WorldTooSmall` after an
      emergency checkpoint of the last committed snapshot.
    - `snapshot_every`: commit a host-offloaded rollback snapshot every N
      completed steps (default 1). On a rank loss the run rolls back to
      the last committed step — a death detected at step k replays steps
      ``[committed, k)`` at the new world size.
    - `reshard_fn(state, new_size)`: state re-packing across world sizes;
      the default consolidates ZeRO-1 optimizer state for dicts carrying
      ``params`` + ``opt_state`` and passes everything else through.
    - `checkpoint_dir` / `checkpoint_every` / `callbacks`: forwarded to the
      inner :func:`horovod_tpu.resilience.run` — periodic checkpoints,
      SIGTERM preemption (drain → emergency checkpoint → exit 75), and
      resume all keep working inside each epoch.
    - `coordinator`: a shared :class:`ElasticCoordinator` (multi-party
      setups); by default the run owns a private one.
    - `publisher` / `publish_every`: a
      :class:`horovod_tpu.serving.WeightPublisher` to stream consolidated
      weights from every Nth committed snapshot. The elastic generation is
      wired up as its fence (a resize aborts any in-flight publication) and
      every resize republishes from the post-resize consolidated state.

    The numerics guard composes (:mod:`horovod_tpu.resilience.numerics`):
    when the carried state holds a guarded optimizer, the driver reads
    the per-step verdict — ``HOROVOD_NUMERICS_MAX_BAD`` consecutive bad
    steps roll back to the committed snapshot with a bumped replay epoch
    (bounded by ``HOROVOD_NUMERICS_MAX_ROLLBACKS``, then FATAL) — and a
    rank the fingerprint cross-check quarantined is evicted on the next
    membership sweep exactly like a dead one.

    Membership faults are injectable deterministically:
    ``HOROVOD_CHAOS="rank_fail=2,rank_fail_at_step=3,rank_join_at_step=6"``
    kills the two highest ranks at step 3's boundary and re-admits them at
    step 6's.
    """
    return ElasticRun(
        step_builder,
        min_workers=min_workers,
        max_workers=max_workers,
        snapshot_every=snapshot_every,
        reshard_fn=reshard_fn,
        coordinator=coordinator,
        devices=devices,
        publisher=publisher,
        publish_every=publish_every,
    ).run(
        state,
        num_steps=num_steps,
        checkpoint_dir=checkpoint_dir,
        checkpoint_every=checkpoint_every,
        callbacks=callbacks,
    )
