"""Process-wide health state machine: ``HEALTHY → SUSPECT → DEGRADED → FATAL``.

The reference's only health signal is the stall inspector's log line; here
every failure-handling layer *feeds* one shared monitor and every consumer
(exceptions, the metrics endpoint, callbacks, the preemption loop) *reads*
it, so a stall is attributable from any vantage point:

- the native core's execute callback calls :func:`beat` each negotiation
  cycle and :func:`record_stall` when the C stall inspector warns
  (``core.py::_on_log``);
- the retry layer calls :func:`record_retry` / :func:`record_retry_exhausted`
  (``resilience.retry``);
- ``CoreHandle.wait(timeout=...)`` expiry calls :func:`record_timeout` and
  embeds the current state in its ``TimeoutError``.

Transitions (forward on evidence, backward on sustained progress):

- ``HEALTHY → SUSPECT``: first stall warning or bounded-wait timeout.
- ``SUSPECT → DEGRADED``: :data:`HealthMonitor.escalate_after` stall/timeout
  reports without an intervening progress beat, or any exhausted retry.
- ``DEGRADED → HEALTHY``: :data:`HealthMonitor.recovery_beats` consecutive
  progress beats (``SUSPECT`` recovers after one).
- ``* → FATAL``: :func:`record_fatal`; terminal, never recovers.

stdlib-only (imported by the launcher and by ``core.py``'s callback thread);
all methods are lock-safe. State is mirrored into the metrics registry as
the ``resilience_health_state`` gauge plus a labeled
``resilience_health_transitions`` counter, so the rank-0 endpoint exports it
without extra plumbing.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Optional

from horovod_tpu.observability import metrics as _metrics

__all__ = [
    "HealthState",
    "HealthMonitor",
    "MONITOR",
    "health_state",
    "beat",
    "record_stall",
    "record_timeout",
    "record_rank_lost",
    "record_replica_lost",
    "record_serving_stale",
    "record_serving_fresh",
    "record_straggler",
    "record_schedule_divergence",
    "record_numeric_corruption",
    "record_data_corruption",
    "record_input_stall",
    "record_slo_burn",
    "record_hang",
    "record_retry",
    "record_retry_exhausted",
    "record_fatal",
    "snapshot",
    "reset",
]


class HealthState(enum.IntEnum):
    """Ordered severity; comparisons (``state >= DEGRADED``) are meaningful."""

    HEALTHY = 0
    SUSPECT = 1
    DEGRADED = 2
    FATAL = 3


class HealthMonitor:
    """One process's health; see the module docstring for the transitions."""

    #: stall/timeout reports without a progress beat before SUSPECT escalates
    escalate_after = 3
    #: consecutive beats required to recover from DEGRADED
    recovery_beats = 3

    def __init__(self):
        self._lock = threading.Lock()
        self._state = HealthState.HEALTHY
        self._reason = ""
        self._since = time.monotonic()
        self._strikes = 0  # stall/timeout reports since the last beat
        self._good_beats = 0  # consecutive beats while DEGRADED
        self._last_beat: Optional[float] = None
        #: True while THIS monitor's DEGRADED was caused by serving-weight
        #: staleness — the one condition that clears instantly when the
        #: condition does (a fully observable state, unlike stall evidence)
        self._serving_stale = False

    # ------------------------------------------------------------- feeders

    def beat(self) -> None:
        """A unit of forward progress (negotiation cycle executed, train
        step completed). Clears strikes and walks SUSPECT/DEGRADED back to
        HEALTHY; FATAL is terminal."""
        with self._lock:
            self._last_beat = time.monotonic()
            self._strikes = 0
            if self._state == HealthState.SUSPECT:
                self._transition(HealthState.HEALTHY, "progress resumed")
            elif self._state == HealthState.DEGRADED:
                self._good_beats += 1
                if self._good_beats >= self.recovery_beats:
                    self._transition(
                        HealthState.HEALTHY,
                        f"{self._good_beats} consecutive beats",
                    )

    def record_stall(self, tensor: str, seconds: float = 0.0) -> None:
        """A stall-inspector warning for `tensor` (coordinator rank)."""
        self._strike(f"stalled collective '{tensor}'"
                     + (f" ({seconds:.0f}s)" if seconds else ""))
        if _metrics.enabled():
            _metrics.counter(
                "resilience_stalls", help="stall-inspector warnings observed"
            ).inc()

    def record_timeout(self, tensor: str) -> None:
        """A bounded wait (``CoreHandle.wait(timeout=...)``) expired."""
        self._strike(f"wait timeout on '{tensor}'")
        if _metrics.enabled():
            _metrics.counter(
                "resilience_wait_timeouts", help="bounded collective waits "
                "that expired"
            ).inc()

    def record_rank_lost(self, rank: int) -> None:
        """A peer rank's heartbeat expired (elastic membership loss). One
        strike — the elastic coordinator's successful re-form then beats the
        machine back toward HEALTHY; a coordinator that *cannot* re-form
        keeps striking until DEGRADED."""
        self._strike(f"rank {rank} heartbeat lost")
        if _metrics.enabled():
            _metrics.counter(
                "resilience_rank_lost",
                help="peer ranks whose heartbeats expired",
            ).inc()

    def record_replica_lost(self, replica, reason: str = "") -> None:
        """A serving replica dropped out of the fleet (killed, lease
        expired, or failed mid-decode). One strike — the fleet router's
        successful re-route of its in-flight requests then leaves the
        machine to recover on forward progress; a fleet that keeps
        losing replicas escalates like any other stall source."""
        self._strike(
            f"serving replica {replica} lost"
            + (f" ({reason})" if reason else ""))
        if _metrics.enabled():
            _metrics.counter(
                "resilience_replicas_lost",
                help="serving replicas dropped from the fleet",
            ).inc()

    def record_schedule_divergence(
        self, rank: int, op: str, step: Optional[int] = None
    ) -> None:
        """The schedule sanitizer caught `rank` issuing a different
        collective sequence (first divergent op `op`). One strike —
        HEALTHY goes SUSPECT with the rank AND op named in the reason; a
        rank that keeps diverging escalates like any other stall source.
        This is the failure mode the reference's negotiation protocol
        exists to prevent (PAPER.md L4) — left unflagged it is a silent
        deadlock or corruption."""
        self._strike(
            f"rank {rank} diverged collective schedule at '{op}'"
            + (f" (step {step})" if step is not None else "")
        )
        if _metrics.enabled():
            _metrics.counter(
                "resilience_schedule_divergences",
                help="cross-rank schedule mismatches fed to the health "
                     "machine by the sanitizer",
            ).inc()

    def record_numeric_corruption(
        self, rank: int, step: Optional[int] = None
    ) -> None:
        """The numerics fingerprint cross-check caught `rank` publishing a
        gradient fingerprint that is non-finite or wildly outside the
        fleet's family while the collective schedule matches — the silent
        data corruption (SDC) signature. One strike — HEALTHY goes SUSPECT
        with the rank named in the reason; the elastic coordinator reads
        the quarantine set (:func:`horovod_tpu.resilience.numerics
        .take_corrupt_ranks`) and evicts the rank."""
        self._strike(
            f"rank {rank} numerically corrupt gradient fingerprint"
            + (f" (step {step})" if step is not None else "")
        )
        if _metrics.enabled():
            _metrics.counter(
                "resilience_numeric_corruptions",
                help="corrupt-gradient fingerprints fed to the health "
                     "machine by the numerics cross-check",
            ).inc()

    def record_hang(self, rank, sig=None, *,
                    kind: str = "rank_missing") -> None:
        """The hang watchdog's verdict
        (:mod:`horovod_tpu.observability.flight`): the mesh made no
        collective/step progress for ``HOROVOD_HANG_TIMEOUT`` and the
        cross-rank diagnosis named `rank` (None: every rank parked — an
        external stall) at collective signature `sig` ``(step, gen,
        seq)``. Goes straight to DEGRADED — a hang IS sustained
        no-progress, the condition strikes exist to accumulate toward —
        with the rank and signature in the reason; never overrides FATAL
        or steals another subsystem's DEGRADED reasonlessly (it claims
        the reason, like an exhausted retry)."""
        key = tuple(sig) if sig else None
        if rank is None:
            reason = f"mesh hung at collective {key} (all ranks parked)"
        elif kind == "schedule_divergence":
            reason = (f"rank {rank} hung the mesh: schedule diverged at "
                      f"collective {key}")
        else:
            reason = f"rank {rank} hung the mesh: missing at collective " \
                     f"{key}"
        with self._lock:
            if self._state < HealthState.DEGRADED:
                self._transition(HealthState.DEGRADED, reason)
            elif self._state == HealthState.DEGRADED:
                self._serving_stale = False
                self._reason = reason
            self._good_beats = 0
        if _metrics.enabled():
            _metrics.counter(
                "resilience_hangs",
                help="hang-watchdog verdicts fed to the health machine",
                rank=-1 if rank is None else int(rank),
            ).inc()

    def record_serving_stale(self, lag: int,
                             seconds: Optional[float] = None) -> None:
        """The serving subscriber's staleness watermark tripped
        (``stale()``): the weights this process serves are `lag`
        generations behind the observed head (`seconds` old). Goes
        straight to DEGRADED with the lag in the reason — the ``/health``
        endpoint answers 503 and the balancer sheds traffic — but never
        overrides a DEGRADED/FATAL some other subsystem owns."""
        with self._lock:
            if self._state >= HealthState.DEGRADED:
                # refresh OUR reason only while we own a DEGRADED state;
                # a FATAL (or someone else's degradation) keeps its own
                # cause on /health
                if (self._serving_stale
                        and self._state == HealthState.DEGRADED):
                    self._reason = self._stale_reason(lag, seconds)
                return
            self._serving_stale = True
            self._transition(
                HealthState.DEGRADED, self._stale_reason(lag, seconds))
        if _metrics.enabled():
            _metrics.counter(
                "resilience_serving_stale",
                help="serving-staleness degradations fed to the health "
                     "machine by the subscriber watermark",
            ).inc()

    @staticmethod
    def _stale_reason(lag: int, seconds: Optional[float]) -> str:
        age = "unknown age" if seconds is None else f"{seconds:.0f}s old"
        return (f"serving weights stale: {lag} generation(s) behind head "
                f"({age})")

    def record_serving_fresh(self) -> None:
        """The staleness condition cleared (a poll caught up). Recovery is
        immediate — but ONLY when serving staleness owns the degradation
        outright: evidence earned since (exhausted retries drop the
        ownership flag, stall/timeout strikes accumulate in ``_strikes``)
        means some other subsystem is unhealthy and still needs its
        beats."""
        with self._lock:
            if not self._serving_stale:
                return
            self._serving_stale = False
            if self._state == HealthState.DEGRADED and self._strikes == 0:
                self._transition(
                    HealthState.HEALTHY, "serving weights fresh again")

    def record_straggler(self, rank: int, spread: float = 0.0,
                         cause: Optional[str] = None) -> None:
        """A persistent straggler: `rank` trailed every other rank at
        ``HOROVOD_STRAGGLER_PERSIST`` consecutive correlated collectives
        (:func:`horovod_tpu.observability.straggler.attribute`). One
        strike — HEALTHY goes SUSPECT with the rank named in the reason;
        a straggler that keeps striking without progress escalates like
        any other stall source. `cause` (``"input"``/``"compute"``, from
        the input-side attribution) lands in the reason so the operator
        reads "slow disk" vs "slow chip" straight off ``/health``."""
        detail = ""
        if spread:
            detail = f" ({spread * 1e3:.0f} ms behind"
            if cause:
                detail += f", {cause}-bound"
            detail += ")"
        elif cause:
            detail = f" ({cause}-bound)"
        self._strike(f"rank {rank} straggling collectives{detail}")
        if _metrics.enabled():
            _metrics.counter(
                "resilience_stragglers",
                help="persistent-straggler reports fed to the health "
                     "machine",
            ).inc()

    def record_data_corruption(self, shard: str,
                               detail: Optional[str] = None) -> None:
        """The data store quarantined a corrupt shard (CRC mismatch that
        survived the retry budget — :class:`horovod_tpu.data
        .ArrayShardStore`). One strike — HEALTHY goes SUSPECT with the
        shard named in the reason, and training continues past the
        quarantine (degrade-don't-crash, the subscriber-staleness
        contract applied to the input plane)."""
        self._strike(
            f"corrupt data shard '{shard}' quarantined"
            + (f" ({detail})" if detail else "")
        )
        if _metrics.enabled():
            _metrics.counter(
                "resilience_data_corruptions",
                help="corrupt data shards quarantined by the input plane",
            ).inc()

    def record_input_stall(self, seconds: float = 0.0) -> None:
        """The input-pipeline watchdog expired: the prefetch thread
        produced no batch for ``HOROVOD_DATA_WATCHDOG`` seconds while the
        step loop was waiting. One strike per watchdog interval — the
        stall-warning cadence — so a stuck disk walks the machine toward
        DEGRADED instead of silently freezing the step loop."""
        self._strike(
            "input pipeline stalled"
            + (f" ({seconds:.0f}s without a batch)" if seconds else "")
        )
        if _metrics.enabled():
            _metrics.counter(
                "resilience_input_stalls",
                help="input-pipeline watchdog expiries fed to the health "
                     "machine",
            ).inc()

    def record_slo_burn(self, objective: str, window: str = "") -> None:
        """An SLO objective is burning its error budget
        (:mod:`horovod_tpu.observability.slo`'s multi-window verdict).
        One strike per evaluator cadence with the objective named —
        HEALTHY goes SUSPECT immediately and a burn that persists
        without progress escalates to DEGRADED like every other stall
        source, so ``/health`` names the objective an operator should
        chase."""
        self._strike(
            f"slo objective '{objective}' burning its error budget"
            + (f" ({window})" if window else "")
        )
        if _metrics.enabled():
            _metrics.counter(
                "resilience_slo_burns",
                help="SLO burn-rate verdicts fed to the health machine",
                objective=objective,
            ).inc()

    def record_retry(self, scope: str) -> None:
        """One retried transient failure (informational; no transition)."""
        if _metrics.enabled():
            _metrics.counter(
                "resilience_retries",
                help="transient failures retried by a RetryPolicy",
                scope=scope,
            ).inc()

    def record_retry_exhausted(self, scope: str) -> None:
        """A RetryPolicy gave up: the failure was not transient after all."""
        with self._lock:
            if self._state < HealthState.DEGRADED:
                self._transition(
                    HealthState.DEGRADED, f"retries exhausted in {scope}"
                )
            else:
                # already DEGRADED (possibly owned by serving staleness):
                # this evidence claims the degradation too — a catching-up
                # subscriber must NOT clear it back to HEALTHY
                self._serving_stale = False
                self._reason = f"retries exhausted in {scope}"
            self._good_beats = 0
        if _metrics.enabled():
            _metrics.counter(
                "resilience_retry_exhausted",
                help="RetryPolicy attempts exhausted without success",
                scope=scope,
            ).inc()

    def record_fatal(self, reason: str) -> None:
        """Unrecoverable failure; terminal."""
        with self._lock:
            if self._state != HealthState.FATAL:
                self._transition(HealthState.FATAL, reason)

    # ------------------------------------------------------------- readers

    def state(self) -> HealthState:
        return self._state

    def reason(self) -> str:
        return self._reason

    def snapshot(self) -> dict:
        """JSON-able view (what the ``/health`` endpoint serves)."""
        with self._lock:
            return {
                "state": self._state.name,
                "value": int(self._state),
                "reason": self._reason,
                "since_seconds": round(time.monotonic() - self._since, 3),
                "strikes": self._strikes,
                "last_beat_age_seconds": (
                    None
                    if self._last_beat is None
                    else round(time.monotonic() - self._last_beat, 3)
                ),
            }

    def reset(self) -> None:
        """Back to a fresh HEALTHY monitor (tests / per-run isolation)."""
        with self._lock:
            self._state = HealthState.HEALTHY
            self._reason = ""
            self._since = time.monotonic()
            self._strikes = 0
            self._good_beats = 0
            self._last_beat = None
            self._serving_stale = False
            if _metrics.enabled():
                _metrics.gauge(
                    "resilience_health_state",
                    help="0=HEALTHY 1=SUSPECT 2=DEGRADED 3=FATAL",
                ).set(0)

    # ------------------------------------------------------------ internal

    def _strike(self, reason: str) -> None:
        with self._lock:
            if self._state == HealthState.FATAL:
                return
            self._strikes += 1
            self._good_beats = 0
            if self._state == HealthState.HEALTHY:
                self._transition(HealthState.SUSPECT, reason)
            elif (
                self._state == HealthState.SUSPECT
                and self._strikes >= self.escalate_after
            ):
                self._transition(
                    HealthState.DEGRADED,
                    f"{self._strikes} strikes without progress "
                    f"(last: {reason})",
                )
            else:
                self._reason = reason

    def _transition(self, new: HealthState, reason: str) -> None:
        """Caller holds the lock."""
        old = self._state
        self._state = new
        self._reason = reason
        self._since = time.monotonic()
        if new != HealthState.DEGRADED:
            # serving-staleness ownership is meaningful only while
            # DEGRADED: leaving it (beats, FATAL) must drop the claim or
            # a later record_serving_fresh could clear a degradation some
            # OTHER subsystem earns afterwards
            self._serving_stale = False
        if new == HealthState.HEALTHY:
            self._strikes = 0
            self._good_beats = 0
        if _metrics.enabled():
            _metrics.gauge(
                "resilience_health_state",
                help="0=HEALTHY 1=SUSPECT 2=DEGRADED 3=FATAL",
            ).set(int(new))
            _metrics.counter(
                "resilience_health_transitions",
                help="health state-machine transitions",
                **{"from": old.name, "to": new.name},
            ).inc()
        try:
            # mirror the transition into the flight ring: health history
            # is the context a post-mortem reads first (flight flushes
            # non-collective events immediately, so the transition is on
            # disk before whatever it heralds kills the process)
            from horovod_tpu.observability import flight as _flight

            _flight.record(
                "health", src=old.name, dst=new.name, reason=reason[:200],
            )
        except Exception:
            import logging

            logging.getLogger("horovod_tpu.resilience").debug(
                "flight health event skipped", exc_info=True)


#: the process-wide monitor every layer feeds and reads
MONITOR = HealthMonitor()

beat = MONITOR.beat
record_stall = MONITOR.record_stall
record_timeout = MONITOR.record_timeout
record_rank_lost = MONITOR.record_rank_lost
record_replica_lost = MONITOR.record_replica_lost
record_serving_stale = MONITOR.record_serving_stale
record_serving_fresh = MONITOR.record_serving_fresh
record_straggler = MONITOR.record_straggler
record_data_corruption = MONITOR.record_data_corruption
record_input_stall = MONITOR.record_input_stall
record_slo_burn = MONITOR.record_slo_burn
record_schedule_divergence = MONITOR.record_schedule_divergence
record_hang = MONITOR.record_hang
record_numeric_corruption = MONITOR.record_numeric_corruption
record_retry = MONITOR.record_retry
record_retry_exhausted = MONITOR.record_retry_exhausted
record_fatal = MONITOR.record_fatal
health_state = MONITOR.state
snapshot = MONITOR.snapshot
reset = MONITOR.reset
