"""Preemption-aware training loop: drain, checkpoint, exit resumable.

TPU slices get preempted with a SIGTERM and a short grace window. The
reference Horovod dies mid-step and loses everything since the last manual
checkpoint; :func:`run` converts that into a classified, resumable outcome:

1. SIGTERM/SIGINT handlers (installed for the duration of the loop, previous
   handlers restored) set a flag; the loop checks it at every step boundary.
2. On preemption the loop *drains*: waits for the native core's queued
   collectives and blocks on the training state so no in-flight XLA program
   is cut mid-collective. Any registered weight publisher
   (:mod:`horovod_tpu.serving`) then flushes a final generation inside the
   remaining drain budget, so serving subscribers get the last good weights
   across the preemption.
3. It writes an **emergency checkpoint** via ``checkpoint.save`` (wrapped as
   ``{"step": N, "state": ...}``) and raises :class:`Preempted` — a
   ``SystemExit`` subclass whose code is :data:`RESUMABLE_EXIT_CODE` (75 =
   BSD ``EX_TEMPFAIL``), so an unguarded training script exits with the
   code launchers (``run/runner.py`` bounded restarts) and
   ``tools/tpu_window_watcher.py`` read as "preempted, retry" rather than
   "failed".
4. On the next launch, :func:`run` (or :func:`resume_state`) restores the
   newest *valid* checkpoint and continues from the recorded step.

This module is stdlib-importable (the launcher imports
:data:`RESUMABLE_EXIT_CODE` without dragging in JAX); the data plane is
imported lazily inside :func:`run`.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Any, Callable, Iterable, Optional, Tuple

from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.resilience import chaos as _chaos, health as _health

__all__ = ["RESUMABLE_EXIT_CODE", "Preempted", "run", "resume_state"]

logger = logging.getLogger("horovod_tpu.resilience")

#: BSD EX_TEMPFAIL: "temporary failure, retry later" — distinct from every
#: code the stack otherwise produces (0 ok, 1/2 errors, 143 SIGTERM-kill),
#: so supervisors can tell "preempted, resume me" from "failed, debug me".
RESUMABLE_EXIT_CODE = 75

#: seconds to wait for the native core's queued collectives while draining
DRAIN_TIMEOUT_S = float(os.environ.get("HOROVOD_PREEMPT_DRAIN_TIMEOUT", "30"))


class Preempted(SystemExit):
    """Raised by :func:`run` after a preemption was drained and emergency-
    checkpointed. Subclasses ``SystemExit`` with :data:`RESUMABLE_EXIT_CODE`
    so an unguarded ``python train.py`` exits resumable; catch it to handle
    preemption in-process instead."""

    def __init__(self, step: int, checkpoint_path: Optional[str] = None,
                 signum: Optional[int] = None):
        super().__init__(RESUMABLE_EXIT_CODE)
        self.step = step
        self.checkpoint_path = checkpoint_path
        self.signum = signum

    def __str__(self):
        sig = (
            f" (signal {self.signum})" if self.signum is not None else ""
        )
        ckpt = (
            f"; emergency checkpoint at {self.checkpoint_path}"
            if self.checkpoint_path
            else "; no emergency checkpoint from this rank"
        )
        return f"preempted at step {self.step}{sig}{ckpt}"


def resume_state(checkpoint_dir: str) -> Optional[Tuple[int, Any]]:
    """``(next_step, state)`` from the newest valid checkpoint under
    `checkpoint_dir` written by :func:`run`, or None when there is none.
    Corrupt/incomplete step directories are skipped (``checkpoint.restore``
    falls back). Collective when ``process_size() > 1``: the root's
    filesystem decides the resume step for every rank, so a rank whose
    local disk lacks the checkpoint still joins the restore broadcast
    instead of silently starting fresh while its peers resume."""
    from horovod_tpu import basics, checkpoint

    multi = basics.is_initialized() and basics.process_size() > 1
    # only the broadcast root pays the CRC sweep of latest_step — every
    # other rank's answer would be discarded by the broadcast anyway
    step = (
        checkpoint.latest_step(checkpoint_dir)
        if not multi or basics.process_rank() == 0
        else None
    )
    if multi:
        from horovod_tpu.ops import collective as C

        step = C.broadcast_object(step, 0)
    if step is None:
        return None
    payload = checkpoint.restore(checkpoint_dir, step)
    # any input-pipeline cursor riding the payload is restored into the
    # loader registry here (pending until the loader registers on a cold
    # restart), so the resumed run draws the exact remaining sample
    # stream — docs/data.md
    payload = checkpoint.detach_data_state(payload)
    if isinstance(payload, dict) and "step" in payload and "state" in payload:
        return int(payload["step"]), payload["state"]
    # a checkpoint not written by run(): resume after its step number
    return step, payload


def _drain(state: Any, timeout_s: float = DRAIN_TIMEOUT_S) -> None:
    """Quiesce the data plane before checkpointing: wait out the native
    core's queued collectives (bounded), then block on the state arrays so
    the snapshot sees completed values, not in-flight buffers."""
    from horovod_tpu import basics

    core = basics._state.core
    if core is not None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                if core.pending_count() == 0:
                    break
            except Exception:
                break
            time.sleep(0.01)
    try:
        import jax

        jax.block_until_ready(state)
    except Exception as e:
        # non-array state (or a dead backend) must not block the save
        logger.debug("pre-save state sync skipped: %s", e)


def run(
    step_fn: Callable[[Any, int], Any],
    state: Any,
    *,
    num_steps: int,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 0,
    start_step: Optional[int] = None,
    callbacks: Optional[Iterable] = None,
    signals: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT),
) -> Any:
    """Drive ``state = step_fn(state, i)`` for ``i in [start, num_steps)``
    with preemption awareness; returns the final state.

    - `checkpoint_dir`: enables resume (newest valid checkpoint is restored
      when `start_step` is None) and emergency checkpoints on preemption.
    - `checkpoint_every`: also checkpoint every N completed steps (0 = only
      on preemption).
    - `callbacks`: :class:`horovod_tpu.callbacks.Callback` objects; the loop
      fires ``on_batch_begin/on_batch_end`` per step and
      ``on_train_begin/on_train_end`` around the run.
    - `signals`: which signals mean "preempted" (default SIGTERM + SIGINT).
      Handlers are only installable on the main thread; elsewhere the loop
      still runs, relying on ``HOROVOD_CHAOS`` or an external flag for
      preemption testing.

    On preemption: drain → emergency checkpoint → raise :class:`Preempted`
    (a ``SystemExit`` carrying :data:`RESUMABLE_EXIT_CODE`). The chaos
    harness (``HOROVOD_CHAOS=sigterm_at_step=K``) delivers a real SIGTERM
    to this process before step K so the whole path is testable in-process.
    """
    first = start_step or 0
    if checkpoint_dir and start_step is None:
        resumed = resume_state(checkpoint_dir)
        if resumed is not None:
            first, state = resumed
            logger.info("resuming from checkpoint at step %d", first)
            if _metrics.enabled():
                _metrics.counter(
                    "resilience_resumes",
                    help="runs resumed from a checkpoint",
                ).inc()

    flag = threading.Event()
    draining = threading.Event()
    received = {"signum": None, "extra": 0}

    def _on_signal(signum, frame):
        # Signal latch: the handler ONLY ever sets flags/counters. Once the
        # drain → emergency-checkpoint sequence has begun, a second SIGTERM
        # (impatient supervisors escalate) must neither re-enter the drain
        # path nor interrupt the in-progress checkpoint write — it is
        # recorded and the first preemption keeps its grace window. The
        # handlers stay installed until _preempt() has completed, so the
        # default action (terminate, truncating the staged npz before its
        # atomic rename) can never fire mid-write.
        if draining.is_set():
            received["extra"] += 1
            return
        received["signum"] = signum
        flag.set()

    previous = {}
    if threading.current_thread() is threading.main_thread():
        for sig in signals:
            try:
                previous[sig] = signal.signal(sig, _on_signal)
            except (ValueError, OSError):  # pragma: no cover
                pass

    from horovod_tpu.callbacks import CallbackList

    cbs = CallbackList(list(callbacks or []))
    chaos_step = _chaos.sigterm_at_step() if _chaos.enabled() else None

    def _preempt(step: int) -> None:
        if draining.is_set():
            # non-reentrant: a second path into preemption (signal during
            # the final-step check, a callback raising) must not drain or
            # checkpoint again over the first pass's in-progress write
            raise Preempted(step, None, received["signum"])
        draining.set()
        drain_deadline = time.monotonic() + DRAIN_TIMEOUT_S
        _drain(state)
        # flush the observability record BEFORE the emergency checkpoint:
        # a preempted run used to keep its weights but lose its spans and
        # flight ring (clean shutdown() was the only flush path — and a
        # supervisor's escalation to SIGKILL never reaches it). Cheap and
        # bounded, so it rides inside the grace window ahead of the
        # checkpoint write.
        try:
            from horovod_tpu import basics as _basics
            from horovod_tpu.observability import flight as _flight

            _flight.record("preempt", step=step)
            _flight.flush()
            _basics.flush_timeline()
        except Exception:
            logger.debug(
                "observability flush during drain failed", exc_info=True)
        # final weight publication (best-effort, inside the remaining drain
        # budget): a preempted trainer's subscribers get the last good
        # generation instead of a staleness gap the length of the restart.
        # Before the emergency checkpoint — the publish is bounded and
        # lossy-safe where the checkpoint is neither.
        try:
            from horovod_tpu import serving as _serving

            if _serving.active_publishers():
                budget = max(0.5, drain_deadline - time.monotonic())
                flushed = _serving.flush_on_preempt(state, step, budget)
                if flushed:
                    logger.warning(
                        "flushed final weight publication from %d "
                        "publisher(s) before the emergency checkpoint",
                        flushed,
                    )
        except Exception:
            logger.warning(
                "final weight publication failed; continuing to the "
                "emergency checkpoint", exc_info=True,
            )
        path = None
        note = "(disabled)"
        state_finite = True
        save_state = state
        if checkpoint_dir:
            # a live state carrying NaN/Inf must NOT become the emergency
            # checkpoint: writing it would displace the newest VALID
            # checkpoint as the resume target (restore skips non-finite
            # checkpoints now, but not writing poison at all preserves
            # the retention budget and the operator's trust in `latest`).
            # ONE device→host snapshot serves both the sweep and the save
            # — the drain window races a supervisor's kill deadline, so
            # the state must not cross the bus twice. Only the WRITER
            # pays it at all: save() no-ops on every other rank, and a
            # non-writer burning its grace window on a full device→host
            # copy shrinks the writer's real budget for nothing.
            try:
                from horovod_tpu import checkpoint as _ckpt
                from horovod_tpu.resilience import numerics as _numerics
                from horovod_tpu.training import host_snapshot

                if _ckpt._is_writer():
                    save_state = host_snapshot(state)
                    if _numerics.checkpoint_finite_check_enabled():
                        state_finite = _numerics.tree_finite(save_state)
            except Exception as e:
                logger.debug("pre-save finiteness sweep skipped: %s", e)
                save_state = state
        if checkpoint_dir and not state_finite:
            note = "(skipped: live state is non-finite; newest valid " \
                   "checkpoint preserved)"
            logger.error(
                "emergency checkpoint at step %d skipped: the live state "
                "carries non-finite values", step,
            )
            if _metrics.enabled():
                _metrics.counter(
                    "resilience_emergency_checkpoint_skipped",
                    help="emergency checkpoints skipped because the live "
                         "state was non-finite",
                ).inc()
        elif checkpoint_dir:
            from horovod_tpu import basics, checkpoint

            # fence=False: on an asymmetric preemption (only this host got
            # SIGTERM) the peers are still training and would never join the
            # save's status broadcast — the grace window must not be spent
            # deadlocked in a collective
            saved = checkpoint.save(
                checkpoint_dir, step,
                checkpoint.attach_data_state(
                    {"step": step, "state": save_state}),
                force=True, fence=False,
            )
            # save() only stages anything on the writer (process rank 0);
            # a preempted non-root rank must not report — or count — a
            # checkpoint it never wrote
            if not basics.is_initialized() or basics.process_rank() == 0:
                path = saved
                note = path
                if _metrics.enabled():
                    _metrics.counter(
                        "resilience_emergency_checkpoints",
                        help="checkpoints written on preemption",
                    ).inc()
                    _metrics.gauge(
                        "resilience_last_checkpoint_step",
                        help="step of the most recent resilience checkpoint",
                    ).set(step)
            else:
                note = "(rank 0 is the writer)"
        if _metrics.enabled():
            _metrics.counter(
                "resilience_preemptions",
                help="preemption signals honored by the training loop",
            ).inc()
            if received["extra"]:
                _metrics.counter(
                    "resilience_extra_preempt_signals",
                    help="signals latched while draining/checkpointing",
                ).inc(received["extra"])
        if received["extra"]:
            logger.warning(
                "latched %d extra signal(s) during drain/checkpoint",
                received["extra"],
            )
        logger.warning(
            "preempted at step %d; emergency checkpoint: %s", step, note,
        )
        raise Preempted(step, path, received["signum"])

    try:
        cbs.on_train_begin()
        step = first
        for step in range(first, num_steps):
            if chaos_step is not None and step >= chaos_step:
                _chaos.consume_sigterm()
                chaos_step = None
                os.kill(os.getpid(), signal.SIGTERM)
                # the Python-level handler runs at the next bytecode
                # boundary; give it one explicit chance before the check
                time.sleep(0)
            if flag.is_set():
                _preempt(step)
            cbs.on_batch_begin(step)
            state = step_fn(state, step)
            _health.beat()
            cbs.on_batch_end(step)
            if (
                checkpoint_dir
                and checkpoint_every
                and (step + 1) % checkpoint_every == 0
                and step + 1 < num_steps
            ):
                from horovod_tpu import checkpoint

                _drain(state)
                checkpoint.save(
                    checkpoint_dir, step + 1,
                    checkpoint.attach_data_state(
                        {"step": step + 1, "state": state}),
                    force=True,
                )
                if _metrics.enabled():
                    _metrics.gauge(
                        "resilience_last_checkpoint_step",
                        help="step of the most recent resilience checkpoint",
                    ).set(step + 1)
        if flag.is_set():
            # the signal landed during the final step: still checkpoint so
            # the restart is a no-op resume instead of a silent rerun
            _preempt(num_steps)
        cbs.on_train_end()
        return state
    finally:
        for sig, handler in previous.items():
            try:
                signal.signal(sig, handler)
            except (ValueError, OSError):  # pragma: no cover
                pass
