"""The shared retry/backoff policy: exponential backoff + jitter + deadline.

One policy object serves every transient-failure site in the stack — the
rendezvous KV client's server-startup race, the launcher's bounded worker
restarts, and the eager-collective dispatch path — so backoff behavior and
its observability (``resilience_retries{scope=...}`` /
``resilience_retry_exhausted{scope=...}`` counters, health feed) cannot
drift between layers.

Deterministic by construction when seeded: :meth:`RetryPolicy.delays` is a
pure function of the policy fields (the jitter RNG is a private
``random.Random(seed)``), so tier-1 tests assert exact delay sequences
instead of sleeping.

stdlib-only; see the package docstring.
"""

from __future__ import annotations

import dataclasses
import os
import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type, Union

from horovod_tpu.resilience import health as _health

__all__ = ["TransientError", "RetryError", "RetryPolicy", "policy_from_env"]


class TransientError(Exception):
    """A failure the caller believes is transient (chaos injection raises
    this; classifiers may map backend errors onto it)."""


class RetryError(Exception):
    """All attempts failed. ``__cause__`` is the last underlying error;
    ``attempts`` records how many were made."""

    def __init__(self, msg: str, attempts: int):
        super().__init__(msg)
        self.attempts = attempts


Retriable = Union[
    Tuple[Type[BaseException], ...],
    Type[BaseException],
    Callable[[BaseException], bool],
]


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with jitter under a total deadline.

    Attempt ``k`` (0-based) sleeps ``min(base_delay * multiplier**k,
    max_delay) + U[0, jitter) * that`` before retrying; at most
    ``max_attempts`` attempts are made and no sleep is started past
    ``deadline`` seconds after the first attempt began.
    """

    scope: str = "generic"
    max_attempts: int = 5
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    #: jitter fraction: each delay is scaled by ``1 + U[0, jitter)``
    jitter: float = 0.1
    #: total seconds across all attempts (None = attempts-bounded only)
    deadline: Optional[float] = None
    #: seed for the jitter RNG (tests); None = nondeterministic
    seed: Optional[int] = None

    def delays(self) -> Iterator[float]:
        """The sleep before each retry (``max_attempts - 1`` values)."""
        rng = random.Random(self.seed)
        d = self.base_delay
        for _ in range(max(0, self.max_attempts - 1)):
            capped = min(d, self.max_delay)
            yield capped * (1.0 + (rng.random() * self.jitter
                                   if self.jitter else 0.0))
            d *= self.multiplier

    def call(self, fn: Callable, *args,
             retriable: Retriable = (TransientError,),
             on_retry: Optional[Callable[[BaseException, int], None]] = None,
             sleep: Callable[[float], None] = time.sleep,
             **kwargs):
        """Run ``fn(*args, **kwargs)``, retrying failures that match
        `retriable` (an exception class/tuple, or a predicate) under the
        backoff schedule. Non-matching failures propagate immediately.
        Exhaustion raises :class:`RetryError` from the last failure and
        marks the health monitor DEGRADED."""
        if callable(retriable) and not isinstance(retriable, type):
            matches = retriable
        else:
            matches = lambda e: isinstance(e, retriable)  # noqa: E731
        t0 = time.monotonic()
        attempts = 0
        last: Optional[BaseException] = None
        for delay in list(self.delays()) + [None]:
            attempts += 1
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                if not matches(e):
                    raise
                last = e
                if delay is None:
                    break  # attempts exhausted
                if (
                    self.deadline is not None
                    and time.monotonic() - t0 + delay > self.deadline
                ):
                    break  # the next sleep would blow the total deadline
                _health.record_retry(self.scope)
                if on_retry is not None:
                    on_retry(e, attempts)
                sleep(delay)
        _health.record_retry_exhausted(self.scope)
        raise RetryError(
            f"{self.scope}: {attempts} attempt(s) failed; last error: "
            f"{last!r}",
            attempts,
        ) from last


def _env_float(name: str) -> Optional[float]:
    v = os.environ.get(name)
    return float(v) if v else None


def policy_from_env(scope: str, **defaults) -> RetryPolicy:
    """A :class:`RetryPolicy` for `scope` with env overrides layered over
    `defaults`: ``HOROVOD_RETRY_<SCOPE>_<FIELD>`` (scope upper-cased,
    non-alnum → ``_``) beats ``HOROVOD_RETRY_<FIELD>`` beats the default.
    Fields: ``MAX_ATTEMPTS``, ``BASE_DELAY``, ``MAX_DELAY``, ``MULTIPLIER``,
    ``JITTER``, ``DEADLINE``."""
    sc = "".join(c if c.isalnum() else "_" for c in scope.upper())
    fields = {
        "max_attempts": int,
        "base_delay": float,
        "max_delay": float,
        "multiplier": float,
        "jitter": float,
        "deadline": float,
    }
    kw = dict(defaults)
    for field, cast in fields.items():
        env = _env_float(f"HOROVOD_RETRY_{sc}_{field.upper()}")
        if env is None:
            env = _env_float(f"HOROVOD_RETRY_{field.upper()}")
        if env is not None:
            kw[field] = cast(env)
    return RetryPolicy(scope=scope, **kw)
