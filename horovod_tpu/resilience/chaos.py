"""Env-gated fault injection (``HOROVOD_CHAOS=...``) — deterministic chaos.

Every recovery path in this package is only trustworthy if tier-1 can
exercise it on CPU, so the harness injects faults *deterministically*
(counted, not sampled): "drop the first N KV requests" reproduces bit-for-bit
where "drop 10% of requests" flakes.

Grammar — comma-separated ``key=value`` pairs::

    HOROVOD_CHAOS="kv_drop=2,collective_delay=0.05,sigterm_at_step=3"

Supported keys (unknown keys raise ``ValueError`` at parse time so typos
fail loudly, not silently inject nothing):

- ``kv_drop=N`` — fail the first N rendezvous KV client requests with
  ``ConnectionRefusedError`` (the server-startup race, on demand).
- ``collective_fail=N`` — fail the first N eager collective launches with
  :class:`~horovod_tpu.resilience.retry.TransientError` (the XLA:CPU
  rendezvous-abort class of failure).
- ``collective_delay=S`` — sleep S seconds before every eager collective
  launch (keep ≤ 0.2 in tier-1 tests).
- ``sigterm_at_step=K`` — have :func:`horovod_tpu.resilience.run` deliver a
  real ``SIGTERM`` to this process just before step K (0-based), driving
  the full preempt → drain → emergency-checkpoint path.
- ``rank_fail=N`` — have the elastic coordinator kill N ranks (highest
  rank ids first, never rank 0): their heartbeats tombstone and the job
  re-forms at the smaller world size. Fires at the step-boundary
  membership sweep of step ``rank_fail_at_step`` (default 1).
- ``rank_fail_at_step=K`` — pin the step (0-based boundary) at which the
  ``rank_fail`` charge fires.
- ``rank_join_at_step=K`` — at step K's boundary, revive every previously
  failed rank: the elastic coordinator re-admits them and grows the world
  back (bounded by ``--max-workers``).
- ``publish_fail=N`` — fail the first N weight-publication attempts
  (:class:`horovod_tpu.serving.WeightPublisher`) with
  :class:`~horovod_tpu.resilience.retry.TransientError` partway through the
  chunk upload, exercising the commit-last ordering: the torn generation is
  never visible and the shared retry policy republishes it.
- ``kv_restart_at_step=K`` — restart the rendezvous KV server at step K's
  publish boundary (``KVStoreServer.restart()``): with a WAL the store
  replays; without one the subscriber must keyframe-resync.
- ``kv_kill_primary_at_step=K`` — SIGKILL-model the **primary** KV server
  at step K's publish boundary (``KVStoreServer.kill()``: socket, WAL
  handle, and ``.lock`` dropped with no graceful teardown): the failover
  drill — a standby must promote, clients must reconnect within their
  original deadlines, and the promoted state must be byte-identical to
  the dead primary's WAL. Consumed by the publisher driving the drill
  (``WeightPublisher.chaos_primary``, falling back to its own store).
- ``kv_partition=<s>`` — blackhole the client's **first-listed** KV
  endpoint (the original primary) for `s` seconds: every request to it
  fails like a refused connection, forcing the multi-endpoint failover
  path without killing the server. The window opens at the first consult
  and self-clears; each dropped request is counted.
- ``subscriber_stall=S`` — sleep S seconds before every subscriber poll
  (keep ≤ 0.2 in tier-1 tests), forcing the catch-up/lag path.
- ``request_burst=N`` — slam N synthetic generation requests into the
  serving engine's queue at one iteration boundary
  (:meth:`horovod_tpu.serving.engine.InferenceEngine.step`), driving the
  queue-overflow admission-control path
  (``serving_admission_rejected{reason=queue_full}``). Fires once.
- ``cache_evict_at_pass=K`` — at the engine's K-th iteration boundary,
  force-evict the serving prefix cache: every refcount-0 cached page is
  dropped AND live sequences aliasing shared pages swap them for fresh
  owned pages and re-prefill from position 0
  (:meth:`horovod_tpu.serving.scheduler.ContinuousBatchingScheduler
  .chaos_evict`) — the drill pins that a forced eviction mid-flight
  rewrites the same KV and the victim's tokens stay bit-identical.
  Fires once.
- ``rank_slow=<rank>:<seconds>`` — make `rank` arrive `seconds` late at
  every eager collective (the deterministic straggler): in a multi-process
  job the matching process sleeps before each dispatch; on the
  single-controller SPMD mesh the sleep happens in the one dispatching
  process and the delay is attributed to `rank`'s simulated arrival
  (:mod:`horovod_tpu.observability.straggler`). Persistent, like
  ``collective_delay``; keep ≤ 0.2 in tier-1 tests.
- ``schedule_diverge_at_step=K`` — at step K's publish boundary, the
  schedule sanitizer (``HOROVOD_SANITIZE=1``,
  :mod:`horovod_tpu.analysis.sanitizer`) perturbs the highest rank's
  published collective-schedule record (never rank 0, like
  ``rank_fail``), so rank 0's cross-check must name that rank and the
  first divergent op. Fires once.
- ``grad_nan_at_step=K`` — the numerics guard
  (:mod:`horovod_tpu.resilience.numerics`) multiplies the gradient tree
  by NaN on its K-th guarded update (0-based, the guard's own step
  counter). The injection is compiled INTO the jitted step at trace time
  (the config is read when the step is built), so the in-jit finiteness
  detector is exercised for real; the charge is consumed host-side by
  :func:`numerics.note_step` once the guard's counter has passed K.
- ``grad_spike_at_step=K:<scale>`` — same mechanism, multiplying the
  gradients by ``<scale>`` (default 1e3) instead of NaN, so the EWMA
  global-norm spike detector trips while every value stays finite.
- ``rank_hang_at_step=K`` — the hung-rank drill
  (:mod:`horovod_tpu.observability.flight`): the highest rank (never
  rank 0; in a multi-process job the highest process rank) *stops
  dispatching* mid-step — from step K's second collective on — really
  holding the dispatching thread so the ``HOROVOD_HANG_TIMEOUT``
  watchdog fires for real. Single-controller: the victim's flight
  record/sidecar/KV-tail view is frozen *before* the parked collective
  (it stays "missing at (step, gen, seq)" even after the drill resumes,
  so live AND offline ``tools/hvd_blackbox.py`` diagnosis agree), and
  the in-process live diagnosis releases the hold early. Multi-process:
  the victim holds the full ``rank_hang_hold`` budget (the release
  signal is process-local to rank 0) and then resumes, so its
  post-drill record shows recovery — for a dead-process offline drill,
  SIGKILL the victim mid-hold. Consumed only by the process that hangs.
- ``rank_hang_hold=S`` — bound on how long the ``rank_hang_at_step``
  victim holds, default 5.0 (keeps the drill from wedging a run).
- ``grad_corrupt_rank=<r>:<step>`` — at `step`'s fingerprint boundary,
  rank `r`'s published per-dtype gradient fingerprint is perturbed to a
  non-finite record (single-controller: the dispatching process writes
  the perturbed copy for `r`; multi-process: the matching process
  perturbs its own). Rank 0's cross-check must name `r` within one
  step; like ``schedule_diverge_at_step``, the charge is consumed only
  by the process that actually perturbs — a 1-rank world leaves it
  armed.
- ``data_stall=<rank>:<seconds>`` — `rank`'s input pipeline stalls
  `seconds` before producing every batch (the deterministic slow-disk:
  in a multi-process job the matching process's loader really sleeps;
  single-controller, the one loader sleeps and the wait is *attributed*
  to `rank`'s simulated input pipeline, the ``rank_slow`` convention),
  so straggler attribution must name the rank **input-bound** — not
  compute-bound — and the prefetch watchdog must detect the stall.
  Persistent, like ``rank_slow``; the loader
  (:class:`horovod_tpu.data.ResumableLoader`) owns the sleep and calls
  :func:`record_injection` per application.
- ``shard_corrupt=<shard>:<k>`` — from its `k`-th read (0-based) on,
  data shard `<shard>`'s bytes come back corrupted (CRC mismatch), so
  the store's retry → quarantine → degrade-don't-crash path runs for
  real (:class:`horovod_tpu.data.ArrayShardStore`). Persistent from
  read `k` (a transiently corrupt read would be healed by the retry and
  prove nothing); applied — and counted per corrupted read — by the
  reading process.
- ``slow_decode=<seconds>[:<arm>[@<replica>]]`` — the serving engine
  sleeps `seconds` before every prefill/decode pass, optionally scoped
  to one rollout arm (``slow_decode=0.05:canary`` slows ONLY the
  canary arm and its drain labels) and, with an ``@<replica>`` suffix,
  to one fleet replica's engine (``slow_decode=0.05:canary@r1``) — the
  deterministic latency regression: TTFT and TPOT burn on the scoped
  arm only, the SLO gate (:mod:`horovod_tpu.observability.slo`)
  auto-rolls the canary back, and ``/health`` names the burning
  objective. Tokens are unaffected (the sleep is host-side), so a
  rolled-back drill keeps token parity with a clean run. Persistent,
  like ``rank_slow``; the engine owns the sleep and calls
  :func:`record_injection` per applied pass; keep ≤ 0.2 in tier-1
  tests.
- ``replica_kill=<i>[:<at_pump>]`` — the fleet router
  (:class:`horovod_tpu.serving.fleet.FleetRouter`) kills serving
  replica index `i` at its `at_pump`-th pump boundary (default 1): the
  replica's lease is tombstoned, its in-flight sequences are abandoned
  mid-decode, and the router must re-route every stranded request with
  exactly-once completion. Consumed when it fires.
- ``replica_stale=<i>:<seconds>`` — fleet replica index `i` reports
  its subscriber `seconds` stale regardless of what it actually
  applied, driving the PR-12 staleness→health path (503, DEGRADED) and
  the router's last-resort demotion without having to wedge a real
  publisher. Persistent; the replica's status publisher calls
  :func:`record_injection` per published status.

Each injection increments ``resilience_chaos_injected{site=...}`` so tests
(and operators running a game-day) can assert the fault actually fired.

stdlib-only. Configuration is lazy: the env is parsed on first query;
:func:`configure` overrides it programmatically and :func:`reset` returns
to env-driven (tests use both).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Union

from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.resilience.retry import TransientError

__all__ = [
    "CHAOS_ENV",
    "parse_spec",
    "configure",
    "reset",
    "enabled",
    "should_fail",
    "maybe_delay",
    "sigterm_at_step",
    "take_rank_fail",
    "take_rank_join",
    "take_kv_restart",
    "take_kv_kill_primary",
    "kv_partition_active",
    "take_request_burst",
    "take_cache_evict",
    "take_schedule_diverge",
    "rank_slow",
    "grad_nan_step",
    "consume_grad_nan",
    "grad_spike",
    "consume_grad_spike",
    "grad_corrupt",
    "consume_grad_corrupt",
    "rank_hang_step",
    "rank_hang_hold",
    "consume_rank_hang",
    "data_stall",
    "shard_corrupt",
    "slow_decode",
    "take_replica_kill",
    "replica_stale",
    "record_injection",
]

CHAOS_ENV = "HOROVOD_CHAOS"

#: count-consuming sites (value = how many times the fault fires)
_COUNT_KEYS = ("kv_drop", "collective_fail", "publish_fail")
#: float-valued knobs
_FLOAT_KEYS = (
    "collective_delay",
    "subscriber_stall",
    "rank_hang_hold",
    "kv_partition",
)
#: int-valued knobs
_INT_KEYS = (
    "sigterm_at_step",
    "rank_fail",
    "rank_fail_at_step",
    "rank_join_at_step",
    "kv_restart_at_step",
    "kv_kill_primary_at_step",
    "schedule_diverge_at_step",
    "grad_nan_at_step",
    "request_burst",
    "rank_hang_at_step",
    "cache_evict_at_pass",
)
#: structured knobs with their own value grammar
_STRUCT_KEYS = (
    "rank_slow",
    "grad_spike_at_step",
    "grad_corrupt_rank",
    "data_stall",
    "shard_corrupt",
    "slow_decode",
    "replica_kill",
    "replica_stale",
)

_lock = threading.Lock()
_config: Optional[Dict[str, Union[int, float]]] = None  # None = read env


def parse_spec(spec: str) -> Dict[str, Union[int, float]]:
    """``"kv_drop=2,collective_delay=0.05"`` → ``{"kv_drop": 2, ...}``."""
    out: Dict[str, Union[int, float]] = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        key, sep, value = item.partition("=")
        key = key.strip()
        if not sep:
            raise ValueError(
                f"{CHAOS_ENV}: expected key=value, got {item!r}"
            )
        if key in _COUNT_KEYS or key in _INT_KEYS:
            out[key] = int(value)
        elif key in _FLOAT_KEYS:
            out[key] = float(value)
        elif key in ("rank_slow", "data_stall"):
            rank_s, sep2, sec_s = value.partition(":")
            if not sep2:
                raise ValueError(
                    f"{CHAOS_ENV}: {key} expects <rank>:<seconds>, "
                    f"got {value!r}"
                )
            out[key] = (int(rank_s), float(sec_s))
        elif key == "shard_corrupt":
            shard_s, sep2, at_s = value.partition(":")
            out[key] = (int(shard_s), int(at_s) if sep2 and at_s else 0)
        elif key == "slow_decode":
            sec_s, sep2, arm_s = value.partition(":")
            out[key] = (float(sec_s),
                        arm_s.strip() if sep2 and arm_s.strip() else None)
        elif key == "replica_kill":
            idx_s, sep2, at_s = value.partition(":")
            out[key] = (int(idx_s),
                        int(at_s) if sep2 and at_s.strip() else 1)
        elif key == "replica_stale":
            idx_s, sep2, sec_s = value.partition(":")
            if not sep2:
                raise ValueError(
                    f"{CHAOS_ENV}: replica_stale expects "
                    f"<replica>:<seconds>, got {value!r}"
                )
            out[key] = (int(idx_s), float(sec_s))
        elif key == "grad_spike_at_step":
            step_s, _sep2, scale_s = value.partition(":")
            out[key] = (int(step_s), float(scale_s) if scale_s else 1e3)
        elif key == "grad_corrupt_rank":
            rank_s, sep2, step_s = value.partition(":")
            if not sep2:
                raise ValueError(
                    f"{CHAOS_ENV}: grad_corrupt_rank expects "
                    f"<rank>:<step>, got {value!r}"
                )
            out[key] = (int(rank_s), int(step_s))
        else:
            known = ", ".join(
                _COUNT_KEYS + _FLOAT_KEYS + _INT_KEYS + _STRUCT_KEYS
            )
            raise ValueError(
                f"{CHAOS_ENV}: unknown chaos site {key!r} (known: {known})"
            )
    return out


def configure(spec: Union[str, Dict[str, Union[int, float]], None]) -> None:
    """Set the active chaos config programmatically (a spec string or a
    parsed dict); ``configure(None)`` disables chaos entirely regardless of
    the env (distinct from :func:`reset`, which re-reads the env)."""
    global _config, _kv_partition_t0
    with _lock:
        if spec is None:
            _config = {}
        elif isinstance(spec, str):
            _config = parse_spec(spec)
        else:
            _config = dict(spec)
        _kv_partition_t0 = None


def reset() -> None:
    """Forget programmatic config; the env is re-parsed on next query."""
    global _config, _kv_partition_t0
    with _lock:
        _config = None
        _kv_partition_t0 = None


def _active() -> Dict[str, Union[int, float]]:
    global _config
    with _lock:
        if _config is None:
            _config = parse_spec(os.environ.get(CHAOS_ENV, ""))
        return _config


def enabled() -> bool:
    return bool(_active())


def _record(site: str) -> None:
    if _metrics.enabled():
        _metrics.counter(
            "resilience_chaos_injected",
            help="faults injected by the chaos harness",
            site=site,
        ).inc()
    try:
        # the flight ring keeps injections in the post-mortem record: a
        # crash AFTER a chaos charge fired must be attributable to it
        from horovod_tpu.observability import flight as _flight

        _flight.record("chaos", site=site)
    except Exception as e:
        import logging

        logging.getLogger("horovod_tpu.resilience").debug(
            "flight chaos event skipped: %s", e)


def should_fail(site: str) -> bool:
    """Consume one charge of a counted fault at `site`; True while charges
    remain. Thread-safe (concurrent dispatchers never double-spend)."""
    cfg = _active()
    with _lock:
        remaining = int(cfg.get(site, 0))
        if remaining <= 0:
            return False
        cfg[site] = remaining - 1
    _record(site)
    return True


def inject_failure(site: str, exc_factory=None) -> None:
    """Raise at `site` while charges remain (default
    :class:`TransientError`); no-op otherwise."""
    if should_fail(site):
        raise (exc_factory or TransientError)(
            f"chaos: injected fault at {site}"
        )


def maybe_delay(site: str = "collective_delay") -> None:
    """Sleep the configured delay for `site` (no-op when unset)."""
    delay = float(_active().get(site, 0.0))
    if delay > 0:
        _record(site)
        time.sleep(delay)


def rank_slow():
    """The armed ``(rank, seconds)`` straggler charge, or None. NOT
    consumed on read — the charge applies to every eager collective, like
    ``collective_delay`` (persistent stragglers are the detection target).
    The applier (:func:`horovod_tpu.observability.straggler
    .collective_begin`) owns the sleep and calls
    :func:`record_injection` per application."""
    v = _active().get("rank_slow")
    if v is None:
        return None
    return int(v[0]), float(v[1])


def data_stall():
    """The armed ``(rank, seconds)`` input-stall charge, or None. NOT
    consumed on read — the charge applies to every produced batch, like
    ``rank_slow`` (persistent input-side stragglers are the detection
    target). The applier (:class:`horovod_tpu.data.ResumableLoader`'s
    producer) owns the sleep and calls :func:`record_injection` per
    application."""
    v = _active().get("data_stall")
    if v is None:
        return None
    return int(v[0]), float(v[1])


def shard_corrupt():
    """The armed ``(shard, from_read)`` shard-corruption charge, or None.
    NOT consumed on read — corruption is persistent from the shard's
    ``from_read``-th read onward (a one-shot corrupt read would be healed
    by the retry layer and never reach quarantine). The applier
    (:class:`horovod_tpu.data.ArrayShardStore`) calls
    :func:`record_injection` per corrupted read."""
    v = _active().get("shard_corrupt")
    if v is None:
        return None
    return int(v[0]), int(v[1])


def slow_decode():
    """The armed ``(seconds, arm_or_None)`` serving-latency charge, or
    None. NOT consumed on read — the charge applies to every engine
    prefill/decode pass (persistent latency regressions are the
    detection target, like ``rank_slow``). ``arm_or_None`` scopes the
    sleep to one rollout arm (and its drain labels); None slows every
    arm. The applier (:class:`horovod_tpu.serving.engine
    .InferenceEngine`) owns the sleep and calls
    :func:`record_injection` per applied pass."""
    v = _active().get("slow_decode")
    if v is None:
        return None
    return float(v[0]), (None if v[1] is None else str(v[1]))


def take_replica_kill(pump: int) -> Optional[int]:
    """Index of the serving replica the fleet router should kill at
    `pump`'s boundary, or None when the charge is unarmed or its pump
    has not arrived (default boundary 1). Consumed on a non-None return
    (fires once) — like ``rank_fail``, but aimed at the serving fleet
    instead of the training collective."""
    cfg = _active()
    with _lock:
        v = cfg.get("replica_kill")
        if v is None or pump < int(v[1]):
            return None
        cfg.pop("replica_kill", None)
    _record("replica_kill")
    return int(v[0])


def replica_stale():
    """The armed ``(replica, seconds)`` forced-staleness charge, or
    None. NOT consumed on read — staleness is a persistent condition
    until the charge is cleared (a one-pump stale blip would never trip
    the health watermark). The applier
    (:class:`horovod_tpu.serving.fleet.FleetReplica`) calls
    :func:`record_injection` per published status."""
    v = _active().get("replica_stale")
    if v is None:
        return None
    return int(v[0]), float(v[1])


def record_injection(site: str) -> None:
    """Count one applied injection at `site`
    (``resilience_chaos_injected{site=}``) — for appliers that implement
    the fault themselves rather than through :func:`inject_failure` /
    :func:`maybe_delay`."""
    _record(site)


def sigterm_at_step() -> Optional[int]:
    """The step index before which ``resilience.run`` should deliver a fake
    preemption SIGTERM, or None. Consumed on read (fires once)."""
    cfg = _active()
    with _lock:
        step = cfg.get("sigterm_at_step")
        return None if step is None else int(step)


def consume_sigterm() -> None:
    """Mark the configured fake SIGTERM as delivered (fires once)."""
    cfg = _active()
    with _lock:
        cfg.pop("sigterm_at_step", None)
    _record("sigterm_at_step")


def take_rank_fail(step: int) -> int:
    """Number of ranks the elastic coordinator should kill at `step`'s
    boundary (0 when the charge is unarmed or its step has not arrived).
    Consumed on a nonzero return (fires once)."""
    cfg = _active()
    with _lock:
        n = int(cfg.get("rank_fail", 0))
        at = int(cfg.get("rank_fail_at_step", 1))
        if n <= 0 or step < at:
            return 0
        cfg.pop("rank_fail", None)
        cfg.pop("rank_fail_at_step", None)
    _record("rank_fail")
    return n


def take_kv_restart(step: int) -> bool:
    """True when the rendezvous KV server should be restarted at `step`'s
    publish boundary (0 when unarmed or the step has not arrived).
    Consumed on True (fires once)."""
    cfg = _active()
    with _lock:
        at = cfg.get("kv_restart_at_step")
        if at is None or step < int(at):
            return False
        cfg.pop("kv_restart_at_step", None)
    _record("kv_restart_at_step")
    return True


def take_kv_kill_primary(step: int) -> bool:
    """True when the primary rendezvous KV server should be
    SIGKILL-modeled (``KVStoreServer.kill()``) at `step`'s publish
    boundary (False when unarmed or the step has not arrived). Consumed
    on True (fires once) — the control-plane failover drill."""
    cfg = _active()
    with _lock:
        at = cfg.get("kv_kill_primary_at_step")
        if at is None or step < int(at):
            return False
        cfg.pop("kv_kill_primary_at_step", None)
    _record("kv_kill_primary_at_step")
    return True


#: monotonic time the kv_partition window opened (None = not yet consulted)
_kv_partition_t0: Optional[float] = None


def kv_partition_active() -> bool:
    """True while the ``kv_partition`` window is open: the KV client must
    drop requests to its first-listed endpoint (the original primary).
    The window opens at the FIRST consult — so it always covers the
    consulting client's next requests regardless of setup time — and
    self-clears after its configured seconds. Each dropped request is
    counted (``site=kv_partition``)."""
    global _kv_partition_t0
    cfg = _active()
    with _lock:
        window = float(cfg.get("kv_partition", 0.0))
        if window <= 0:
            return False
        now = time.monotonic()
        if _kv_partition_t0 is None:
            _kv_partition_t0 = now
        if now - _kv_partition_t0 >= window:
            cfg.pop("kv_partition", None)
            _kv_partition_t0 = None
            return False
    _record("kv_partition")
    return True


def take_request_burst() -> int:
    """Number of synthetic requests the serving engine should inject at
    this iteration boundary (0 when unarmed). Consumed on a nonzero
    return (fires once)."""
    cfg = _active()
    with _lock:
        n = int(cfg.get("request_burst", 0))
        if n <= 0:
            return 0
        cfg.pop("request_burst", None)
    _record("request_burst")
    return n


def take_cache_evict(pass_count: int) -> bool:
    """True when the serving engine should force-evict its prefix cache
    at `pass_count`'s iteration boundary (False when unarmed or the
    pass has not arrived). Consumed on True (fires once)."""
    cfg = _active()
    with _lock:
        at = cfg.get("cache_evict_at_pass")
        if at is None or pass_count < int(at):
            return False
        cfg.pop("cache_evict_at_pass", None)
    _record("cache_evict_at_pass")
    return True


def take_schedule_diverge(step: int) -> bool:
    """True when the schedule sanitizer should perturb the highest rank's
    published record at `step`'s publish boundary (False when unarmed or
    the step has not arrived). Consumed on True (fires once)."""
    cfg = _active()
    with _lock:
        at = cfg.get("schedule_diverge_at_step")
        if at is None or step < int(at):
            return False
        cfg.pop("schedule_diverge_at_step", None)
    _record("schedule_diverge_at_step")
    return True


def grad_nan_step() -> Optional[int]:
    """The guard-counter value at which the numerics guard should inject
    NaN gradients, or None. NOT consumed on read — the injection is
    compiled into the jitted step at trace time; the host-side consumer
    (:func:`horovod_tpu.resilience.numerics.note_step`) calls
    :func:`consume_grad_nan` once the guard's counter has passed it."""
    cfg = _active()
    with _lock:
        step = cfg.get("grad_nan_at_step")
        return None if step is None else int(step)


def consume_grad_nan() -> None:
    """Mark the grad-NaN charge as fired (once) and count the injection."""
    cfg = _active()
    with _lock:
        if "grad_nan_at_step" not in cfg:
            return
        cfg.pop("grad_nan_at_step", None)
    _record("grad_nan_at_step")


def grad_spike():
    """The armed ``(step, scale)`` gradient-spike charge, or None. NOT
    consumed on read (trace-time config, like :func:`grad_nan_step`)."""
    v = _active().get("grad_spike_at_step")
    if v is None:
        return None
    return int(v[0]), float(v[1])


def consume_grad_spike() -> None:
    """Mark the grad-spike charge as fired (once) and count the injection."""
    cfg = _active()
    with _lock:
        if "grad_spike_at_step" not in cfg:
            return
        cfg.pop("grad_spike_at_step", None)
    _record("grad_spike_at_step")


def grad_corrupt():
    """The armed ``(rank, step)`` fingerprint-corruption charge, or None.
    NOT consumed on read — only the process that actually perturbs the
    published fingerprint consumes it (:func:`consume_grad_corrupt`), so
    a 1-rank world leaves the charge armed."""
    v = _active().get("grad_corrupt_rank")
    if v is None:
        return None
    return int(v[0]), int(v[1])


def consume_grad_corrupt() -> None:
    """Mark the fingerprint-corruption charge as fired (once)."""
    cfg = _active()
    with _lock:
        if "grad_corrupt_rank" not in cfg:
            return
        cfg.pop("grad_corrupt_rank", None)
    _record("grad_corrupt_rank")


def rank_hang_step() -> Optional[int]:
    """The step at which the hung-rank drill arms, or None. NOT consumed
    on read — every dispatch consults it; only the process that actually
    hangs consumes (:func:`consume_rank_hang`, the ``grad_corrupt``
    convention), so a 1-rank world leaves the charge armed."""
    v = _active().get("rank_hang_at_step")
    return None if v is None else int(v)


def rank_hang_hold() -> float:
    """Bound (seconds) on how long the hung rank holds before resuming —
    the drill must never wedge a test run. Default 5.0."""
    return float(_active().get("rank_hang_hold", 5.0))


def consume_rank_hang() -> None:
    """Mark the hung-rank charge as fired (once) and count the injection."""
    cfg = _active()
    with _lock:
        if "rank_hang_at_step" not in cfg:
            return
        cfg.pop("rank_hang_at_step", None)
    _record("rank_hang_at_step")


def take_rank_join(step: int) -> bool:
    """True when the elastic coordinator should re-admit the failed ranks
    at `step`'s boundary. Consumed on True (fires once)."""
    cfg = _active()
    with _lock:
        at = cfg.get("rank_join_at_step")
        if at is None or step < int(at):
            return False
        cfg.pop("rank_join_at_step", None)
    _record("rank_join_at_step")
    return True
