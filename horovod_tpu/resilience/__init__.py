"""Fault-tolerance subsystem: classify, retry, or checkpoint — never just die.

The reference Horovod's failure story is a stall inspector that warns and
eventually kills the job (``HOROVOD_STALL_*``, mirrored in our native core).
Elastic Horovod / TorchElastic showed that surviving worker loss and
preemption is what makes data-parallel training production-grade; this
package is that layer for the TPU-native stack:

- :mod:`~horovod_tpu.resilience.health` — a process-wide health state
  machine (``HEALTHY → SUSPECT → DEGRADED → FATAL``) fed by the native
  core's cycle/stall signals and the retry layer, exposed through
  ``basics.health_state()`` and the rank-0 metrics endpoint (``/health``).
- :mod:`~horovod_tpu.resilience.retry` — the shared
  :class:`~horovod_tpu.resilience.retry.RetryPolicy` (exponential backoff +
  seeded jitter + total deadline, instrumented with
  ``resilience_retries``/``resilience_retry_exhausted`` counters) applied to
  rendezvous KV calls, worker restarts, and eager collective dispatch.
- :mod:`~horovod_tpu.resilience.loop` — the preemption-aware training loop
  :func:`~horovod_tpu.resilience.loop.run`: SIGTERM/SIGINT drain in-flight
  collectives, write an emergency checkpoint, and exit with the resumable
  exit code (:data:`RESUMABLE_EXIT_CODE`, 75 = ``EX_TEMPFAIL``) that
  launchers and ``tools/tpu_window_watcher.py`` read as "preempted, retry".
- :mod:`~horovod_tpu.resilience.chaos` — the env-gated
  (``HOROVOD_CHAOS=...``) fault-injection harness that makes all of the
  above deterministically testable on CPU in tier-1.
- :mod:`~horovod_tpu.resilience.elastic` — elastic world-size training:
  KV-heartbeat membership with TTL, generation-numbered epochs, in-process
  mesh re-formation, ZeRO-1 state reshard, and rollback to the last
  committed host snapshot — rank loss/join without a job restart
  (:class:`~horovod_tpu.resilience.elastic.ElasticRun` /
  :func:`~horovod_tpu.resilience.elastic.run`).
- :mod:`~horovod_tpu.resilience.numerics` — the value-plane guard: in-jit
  per-step gradient/loss anomaly detection (finiteness + EWMA norm-spike,
  one fused reduction) with atomic step skip, dynamic loss scaling,
  bounded skip/replay via the elastic snapshot, corrupting-rank
  fingerprint quarantine → eviction, and the poison-free weight-publish
  gate. NOT imported here: it needs the data plane (jax) — import it as
  ``from horovod_tpu.resilience import numerics``.

Import hygiene: everything exported here is stdlib-only at import time (no
JAX, no device backend) so the launcher (``run/``) and standalone tools can
use it; :func:`run` imports the data plane lazily on first call.
"""

from __future__ import annotations

from horovod_tpu.resilience import chaos, elastic  # noqa: F401
from horovod_tpu.resilience.health import (  # noqa: F401
    HealthMonitor,
    HealthState,
    MONITOR,
    health_state,
)
from horovod_tpu.resilience.loop import (  # noqa: F401
    Preempted,
    RESUMABLE_EXIT_CODE,
    resume_state,
    run,
)
from horovod_tpu.resilience.retry import (  # noqa: F401
    RetryError,
    RetryPolicy,
    TransientError,
    policy_from_env,
)

__all__ = [
    "HealthMonitor",
    "HealthState",
    "MONITOR",
    "health_state",
    "Preempted",
    "RESUMABLE_EXIT_CODE",
    "resume_state",
    "run",
    "RetryError",
    "RetryPolicy",
    "TransientError",
    "policy_from_env",
    "chaos",
    "elastic",
]
