"""Numerics guard: gradient anomaly detection, skip, quarantine, and gates.

The rest of the resilience stack survives process death (elastic), KV loss
(serving WAL), stragglers, and schedule divergence — but a single NaN/Inf
or silently-corrupted gradient still poisons the weights unchecked, and
the weight publisher would happily stream that poisoned state to a serving
fleet. This module is the graceful-degradation layer for *values*:

1. **In-jit per-step guard** — :func:`guard` wraps any optax
   transformation (typically a
   :func:`horovod_tpu.optim.DistributedOptimizer`) so every update first
   computes, INSIDE the jitted step, the gradient tree's global norm
   (per-dtype partial sums stacked into one small vector — one fused
   reduction, a single ``lax.pmean`` when a collective axis is bound; no
   host sync, hvdlint HVD003-clean) plus finiteness of that norm and the
   step loss. A non-finite value or an EWMA global-norm spike marks the
   step **BAD**: the inner update's outputs are discarded atomically via
   ``jnp.where`` selection — parameters, optimizer moments,
   error-feedback residuals, and PowerSGD warm-start ``Q`` factors are
   all bit-identical to the pre-step state.
2. **Dynamic loss scaling** — ``loss_scale=`` keeps a grow/backoff scale
   in the guard state for the bf16/fp16 mixed-precision path: the step
   builders multiply the loss by :func:`current_scale` before the
   backward pass, the guard divides the gradients back before the inner
   update, a bad step halves the scale, and ``growth_interval``
   consecutive good steps double it (clamped).
3. **Skip/replay policy** — the elastic driver
   (:mod:`horovod_tpu.resilience.elastic`) reads the guard verdict at
   every step boundary (:func:`note_step`); ``HOROVOD_NUMERICS_MAX_BAD``
   consecutive bad steps raise :class:`NumericsRollback`, rolling the run
   back to the last committed host snapshot with
   :func:`replay_epoch` bumped so data pipelines can draw FRESH batches
   for the replay. The rollback budget is bounded
   (``HOROVOD_NUMERICS_MAX_ROLLBACKS``); exhausting it is FATAL.
4. **Corrupting-rank localization** — each rank publishes a cheap
   per-dtype gradient fingerprint (finiteness + norms, the pre-collective
   checksum) to the rendezvous KV beside the PR-8 sanitizer record
   (:func:`publish_fingerprint`); rank 0 cross-checks
   (:func:`cross_check_fingerprints`): a rank whose fingerprint is
   non-finite — or a factor ``HOROVOD_NUMERICS_OUTLIER_FACTOR`` outside
   the fleet's median — while the collective *schedule* matches goes into
   the quarantine set, feeds
   :func:`horovod_tpu.resilience.health.record_numeric_corruption`
   (SUSPECT with the rank named), and is evicted by the elastic
   coordinator on the next membership sweep.
5. **Publish gate** — :func:`publish_gate_reason` refuses a weight
   publication whose consolidated tree is non-finite, whose trainer just
   marked a step bad, or while a quarantine is pending
   (:class:`horovod_tpu.serving.WeightPublisher` emits
   ``serving_publish_rejected{reason=}`` instead of a poisoned head).

Deterministic chaos charges (``HOROVOD_CHAOS=grad_nan_at_step=K``,
``grad_spike_at_step=K:<scale>``, ``grad_corrupt_rank=<r>:<step>``) make
every path testable on the 8-device CPU mesh in tier-1; the in-jit charges
are compiled into the guarded step at trace time and consumed host-side by
:func:`note_step` once they have fired.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
from typing import Any, Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from horovod_tpu.observability import metrics as _metrics
from horovod_tpu.resilience import chaos as _chaos

__all__ = [
    "NumericsGuardState",
    "GuardedTransformation",
    "NumericsRollback",
    "NumericsError",
    "guard",
    "is_guarded",
    "shard_state_spec",
    "current_scale",
    "find_guard_states",
    "verdict",
    "note_step",
    "stage_verdict",
    "note_step_staged",
    "flush_staged",
    "set_step",
    "claim_boundary",
    "boundary",
    "publish_fingerprint",
    "cross_check_fingerprints",
    "fingerprint_enabled",
    "fingerprint_key",
    "take_corrupt_ranks",
    "quarantine_pending",
    "clear_quarantine",
    "array_finite",
    "tree_finite",
    "checkpoint_finite_check_enabled",
    "publish_gate_reason",
    "max_consecutive_bad",
    "max_rollbacks",
    "replay_epoch",
    "bump_replay_epoch",
    "configure",
    "reset",
]

logger = logging.getLogger("horovod_tpu.resilience.numerics")

SPIKE_FACTOR_ENV = "HOROVOD_NUMERICS_SPIKE_FACTOR"
EWMA_ALPHA_ENV = "HOROVOD_NUMERICS_EWMA_ALPHA"
WARMUP_ENV = "HOROVOD_NUMERICS_WARMUP"
MAX_BAD_ENV = "HOROVOD_NUMERICS_MAX_BAD"
MAX_ROLLBACKS_ENV = "HOROVOD_NUMERICS_MAX_ROLLBACKS"
OUTLIER_ENV = "HOROVOD_NUMERICS_OUTLIER_FACTOR"
FINGERPRINT_ENV = "HOROVOD_NUMERICS_FINGERPRINT"
SCALE_INIT_ENV = "HOROVOD_NUMERICS_SCALE_INIT"
SCALE_GROWTH_ENV = "HOROVOD_NUMERICS_SCALE_GROWTH_INTERVAL"
GATE_ENV = "HOROVOD_PUBLISH_NUMERICS_GATE"
CKPT_FINITE_ENV = "HOROVOD_CHECKPOINT_FINITE_CHECK"

#: loss-scale dynamics (NVIDIA AMP conventions): halve on a bad step,
#: double after `growth_interval` consecutive good ones, clamped.
SCALE_BACKOFF = 0.5
SCALE_GROWTH = 2.0
SCALE_MIN = 1.0
SCALE_MAX = float(2 ** 24)

_lock = threading.Lock()
_kv = None  # explicit KV override; falls back to the sanitizer's store
_quarantine: set = set()
_fp_override: Optional[bool] = None
_replay_epoch = 0
_last_record: Optional[dict] = None  # fingerprint of the last noted step
_last_corruption: Optional[dict] = None
_perturbed_steps: Dict[int, int] = {}  # step -> victim rank (sticky chaos)
_warned_impossible_charge = False  # one loud warning per armed bad charge
_last_boundary: Optional[int] = None
#: steps rank 0 could not fully cross-check (a peer's fingerprint had not
#: landed) -> remaining recheck attempts; retried at later boundaries —
#: the corrupt rank is often the SLOW one (the PR-8 sanitizer lesson)
_pending_checks: Dict[int, int] = {}
PENDING_CHECK_ATTEMPTS = 8
#: (step, rank) findings already reported — a deferred step re-checked
#: at later boundaries must not re-strike health / re-quarantine per try
_flagged: set = set()
#: True once a driver with authoritative step numbering (the elastic
#: wrapper) owns the boundary: InstrumentedStep's generic hook then
#: stands down — two hooks with diverging counters would double-publish
#: every step under different keys
_external_boundary = False
#: (step, staged verdict) the standalone hook reads one boundary late —
#: guard-only observability without fencing the dispatch chain
_standalone_staged: Optional[tuple] = None


class NumericsGuardState(NamedTuple):
    """Guard state wrapping the inner optimizer state (the ``_EFState``
    composition discipline). Every non-``inner`` leaf is a replicated
    scalar (or a dict of scalars), so the state reshards across world
    sizes and broadcasts untouched; :func:`shard_state_spec` gives the
    matching ``shard_map`` pytree-prefix spec."""

    inner: Any
    ewma: Any         # f32: EWMA of the global grad norm over good steps
    count: Any        # i32: guarded updates seen (the chaos-charge clock)
    bad_count: Any    # i32: total bad (skipped) steps
    bad_streak: Any   # i32: consecutive bad steps (the rollback trigger)
    last_bad: Any     # i32: 1 when the most recent step was bad
    last_finite: Any  # i32: 1 when the most recent step was finite
    last_norm: Any    # f32: last global grad norm (0 when non-finite)
    norms: Any        # {dtype: f32} per-dtype norms (the fingerprint)
    loss_scale: Any   # f32: current dynamic loss scale (1 when disabled)
    good_streak: Any  # i32: consecutive good steps at the current scale
    chaos_fired: Any  # i32 bitmask: 1 = grad_nan injected, 2 = grad_spike
    rank_norms: Any   # f32 [N]: per-rank PRE-reduction local grad norms
    #                  (-1 marks a non-finite rank; replicated content —
    #                  the bound path all_gathers one scalar per rank)


class GuardedTransformation(optax.GradientTransformationExtraArgs):
    """Marker subclass so step builders can detect a numerics-guarded
    optimizer (:func:`is_guarded`) and thread the loss through."""


class NumericsRollback(Exception):
    """Control flow: the guard saw ``max_consecutive_bad`` bad steps in a
    row; the elastic driver unwinds the inner loop and replays from the
    last committed snapshot with :func:`replay_epoch` bumped."""

    def __init__(self, step: int, streak: int):
        self.step = step
        self.streak = streak
        super().__init__(
            f"{streak} consecutive bad steps at step {step}; rolling back"
        )


class NumericsError(RuntimeError):
    """The rollback budget is exhausted: the run cannot make numerically
    sound progress (bad data shard, persistent SDC). The health machine
    was marked FATAL before this raised."""


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def max_consecutive_bad() -> int:
    """Bad steps in a row before the skip policy escalates to a rollback
    (``HOROVOD_NUMERICS_MAX_BAD``, default 3)."""
    return max(1, _env_int(MAX_BAD_ENV, 3))


def max_rollbacks() -> int:
    """Numerics rollbacks tolerated per run before FATAL
    (``HOROVOD_NUMERICS_MAX_ROLLBACKS``, default 3)."""
    return max(1, _env_int(MAX_ROLLBACKS_ENV, 3))


def replay_epoch() -> int:
    """Bumped on every numerics rollback. Data pipelines that fold this
    into their batch selection draw FRESH batches for the replayed steps
    instead of re-serving the batch that went bad."""
    return _replay_epoch


def bump_replay_epoch() -> int:
    global _replay_epoch
    with _lock:
        _replay_epoch += 1
        return _replay_epoch


def configure(*, kv=None, fingerprint: Optional[bool] = None) -> None:
    """Programmatic setup: wire a KV store for the fingerprint plane or
    force the fingerprint publication on/off (None = env/chaos-driven)."""
    global _kv, _fp_override
    with _lock:
        if kv is not None:
            _kv = kv
        if fingerprint is not None:
            _fp_override = bool(fingerprint)


def reset() -> None:
    """Back to env-driven config and empty quarantine (tests)."""
    global _kv, _fp_override, _replay_epoch, _last_record, _last_corruption
    global _step, _last_boundary, _external_boundary
    global _warned_impossible_charge, _standalone_staged
    with _lock:
        _warned_impossible_charge = False
        _kv = None
        _fp_override = None
        _replay_epoch = 0
        _last_record = None
        _last_corruption = None
        _last_boundary = None
        _external_boundary = False
        _standalone_staged = None
        _perturbed_steps.clear()
        _pending_checks.clear()
        _flagged.clear()
        _quarantine.clear()
        _step = 0


# --------------------------------------------------------------------------
# the in-jit guard


def _is_guard_leaf(x) -> bool:
    return isinstance(x, NumericsGuardState)


def find_guard_states(tree) -> List[NumericsGuardState]:
    """Every :class:`NumericsGuardState` in `tree` (outermost first) —
    works on live device states, host snapshots, and tracers."""
    leaves = jax.tree_util.tree_flatten(tree, is_leaf=_is_guard_leaf)[0]
    return [l for l in leaves if isinstance(l, NumericsGuardState)]


def is_guarded(tx) -> bool:
    """Is `tx` a :func:`guard`-wrapped transformation? Step builders use
    this to thread the loss kwarg and the loss scale through."""
    return isinstance(tx, GuardedTransformation)


def current_scale(opt_state):
    """The dynamic loss scale carried in `opt_state`'s guard state (1.0
    when unguarded). Trace-safe: returns the traced leaf inside a jitted
    step, so builders can scale the loss before the backward pass."""
    states = find_guard_states(opt_state)
    if not states:
        return jnp.float32(1.0)
    return states[0].loss_scale


def shard_state_spec(inner_spec):
    """``shard_map`` pytree-prefix spec for a guarded state: the inner
    (e.g. ZeRO-1 ``[N, shard]``) subtree takes `inner_spec`; every guard
    scalar stays replicated."""
    from jax.sharding import PartitionSpec as P

    rep = P()
    return NumericsGuardState(
        inner=inner_spec, ewma=rep, count=rep, bad_count=rep,
        bad_streak=rep, last_bad=rep, last_finite=rep, last_norm=rep,
        norms=rep, loss_scale=rep, good_streak=rep, chaos_fired=rep,
        rank_norms=rep,
    )


def _float_key(x) -> Optional[str]:
    dt = getattr(x, "dtype", None)
    dt = jnp.dtype(dt) if dt is not None else jnp.result_type(x)
    return str(dt) if jnp.issubdtype(dt, jnp.inexact) else None


def guard(
    tx,
    *,
    spike_factor: Optional[float] = None,
    ewma_alpha: Optional[float] = None,
    warmup: Optional[int] = None,
    loss_scale=None,
    growth_interval: Optional[int] = None,
    axis=None,
):
    """Wrap `tx` so every update is guarded per the module docstring.

    - `spike_factor` (env ``HOROVOD_NUMERICS_SPIKE_FACTOR``, default 10):
      a step whose global grad norm exceeds ``spike_factor × EWMA`` after
      `warmup` steps is BAD. The EWMA only absorbs *good* steps, so one
      spike cannot raise its own bar.
    - `warmup` (env ``HOROVOD_NUMERICS_WARMUP``, default 5): *good* steps
      before spike detection arms — bad steps don't feed the EWMA, so
      they don't count toward its baseline either (finiteness is checked
      from step 0).
    - `loss_scale`: ``None`` disables scaling (the scale leaf stays 1);
      ``"dynamic"``/``True`` starts at ``HOROVOD_NUMERICS_SCALE_INIT``
      (default 2^15); a float starts there. Grow/backoff per the AMP
      conventions; pair with a step builder that multiplies the loss by
      :func:`current_scale` (the ``make_*_train_step`` builders do this
      automatically for guarded optimizers).
    - `axis`: the collective axis the verdict is agreed over when the
      update runs inside ``shard_map`` (default: the data axis).

    Apply OUTERMOST — after ``DistributedOptimizer`` (so the skip also
    freezes EF residuals and PowerSGD ``Q``) and after ``MultiSteps`` if
    used. The state is :class:`NumericsGuardState`;
    ``reshard_optimizer_state``/``consolidate_opt_state`` re-pack the
    inner state across world sizes and carry the guard scalars through.
    """
    sf = float(
        spike_factor if spike_factor is not None
        else _env_float(SPIKE_FACTOR_ENV, 10.0))
    alpha = float(
        ewma_alpha if ewma_alpha is not None
        else _env_float(EWMA_ALPHA_ENV, 0.1))
    warm = int(warmup if warmup is not None else _env_int(WARMUP_ENV, 5))
    grow_n = int(
        growth_interval if growth_interval is not None
        else _env_int(SCALE_GROWTH_ENV, 200))
    scaling = loss_scale is not None
    if loss_scale in (True, "dynamic"):
        scale0 = _env_float(SCALE_INIT_ENV, float(2 ** 15))
    elif scaling:
        scale0 = float(loss_scale)
    else:
        scale0 = 1.0

    def init_fn(params):
        from horovod_tpu import basics

        inner = tx.init(params)
        keys = []
        for leaf in jax.tree_util.tree_leaves(params):
            k = _float_key(leaf)
            if k is not None and k not in keys:
                keys.append(k)
        try:
            world = basics.size() if basics.is_initialized() else 1
        except Exception:
            world = 1
        return NumericsGuardState(
            inner=inner,
            ewma=jnp.zeros((), jnp.float32),
            count=jnp.zeros((), jnp.int32),
            bad_count=jnp.zeros((), jnp.int32),
            bad_streak=jnp.zeros((), jnp.int32),
            last_bad=jnp.zeros((), jnp.int32),
            last_finite=jnp.ones((), jnp.int32),
            last_norm=jnp.zeros((), jnp.float32),
            norms={k: jnp.zeros((), jnp.float32) for k in keys},
            loss_scale=jnp.asarray(scale0, jnp.float32),
            good_streak=jnp.zeros((), jnp.int32),
            chaos_fired=jnp.zeros((), jnp.int32),
            rank_norms=jnp.zeros((world,), jnp.float32),
        )

    def update_fn(grads, state, params=None, *, loss=None, **extra):
        from horovod_tpu import basics
        from horovod_tpu.ops import collective as _C

        leaves = jax.tree_util.tree_leaves(grads)
        traced = any(_C._is_tracer(l) for l in leaves)
        bound_ax = None
        if basics.is_initialized() and traced:
            try:
                ax = _C._axis(axis)
                if _C._axis_bound(ax):
                    bound_ax = ax
            except Exception as e:
                logger.debug("guard axis probe failed: %s", e)

        # deterministic chaos, compiled into the step at TRACE time: the
        # guard's own counter is the clock, so the injection fires exactly
        # once even through jit. note_step() consumes the charge host-side.
        nan_k = _chaos.grad_nan_step() if _chaos.enabled() else None
        spike_cfg = _chaos.grad_spike() if _chaos.enabled() else None
        fired = jnp.zeros((), jnp.int32)
        if nan_k is not None or spike_cfg is not None:
            factor = jnp.float32(1.0)
            if nan_k is not None:
                hit = state.count == nan_k
                factor = jnp.where(hit, jnp.float32(jnp.nan), factor)
                fired = fired | hit.astype(jnp.int32)
            if spike_cfg is not None:
                hit = state.count == spike_cfg[0]
                # COMPOSE with any nan injection at the same step (NaN ×
                # scale stays NaN) — a where-select overwrite would zero
                # the nan charge's effect while its fired bit still told
                # note_step the NaN path was exercised
                factor = jnp.where(
                    hit, factor * jnp.float32(spike_cfg[1]), factor)
                fired = fired | (2 * hit.astype(jnp.int32))
            grads = jax.tree_util.tree_map(
                lambda g: (
                    g * factor.astype(g.dtype)
                    if _float_key(g) is not None else g
                ),
                grads,
            )

        # unscale the (loss-scaled) gradients before anything downstream
        # sees them: the wire, EF residuals, and moments all live in
        # unscaled space, so the scale can change without perturbing them
        if scaling:
            inv = (1.0 / state.loss_scale).astype(jnp.float32)
            grads = jax.tree_util.tree_map(
                lambda g: (
                    g * inv.astype(g.dtype)
                    if _float_key(g) is not None else g
                ),
                grads,
            )

        # one fused reduction: per-dtype partial square-sums stacked into
        # a single small vector; when a collective axis is bound, ONE
        # pmean of that vector makes the verdict identical on every rank
        # (NaN/Inf anywhere propagates to everyone)
        leaves = jax.tree_util.tree_leaves(grads)  # post-inject/unscale
        keys = list(state.norms.keys())
        sums = {k: jnp.zeros((), jnp.float32) for k in keys}
        extra_sum = jnp.zeros((), jnp.float32)
        for g in leaves:
            k = _float_key(g)
            if k is None:
                continue
            s = jnp.sum(jnp.square(jnp.asarray(g).astype(jnp.float32)))
            if k in sums:
                sums[k] = sums[k] + s
            else:
                extra_sum = extra_sum + s
        vec = jnp.stack([sums[k] for k in keys] + [extra_sum])
        n_ranks = int(state.rank_norms.shape[0]) \
            if getattr(state.rank_norms, "ndim", 0) else 1
        if bound_ax is not None:
            # the fingerprint's localization signal: each rank's LOCAL
            # pre-reduction square-sum, gathered as one scalar per rank
            # (replicated content; -1 marks a non-finite rank so the
            # carried state itself stays finite)
            local_total = jnp.sum(vec)
            gathered = lax.all_gather(local_total, bound_ax)
            rank_norms = jnp.where(
                jnp.isfinite(gathered), jnp.sqrt(gathered),
                jnp.float32(-1.0))
            if rank_norms.shape[0] != n_ranks:  # static; mesh mismatch
                rank_norms = jnp.resize(rank_norms, (n_ranks,))
            vec = lax.pmean(vec, bound_ax)
        total = jnp.sum(vec)
        norm = jnp.sqrt(total)
        if bound_ax is None:
            # unbound (global jit / eager): no per-rank view — replicate
            # the global norm (the cross-check's outlier test then sees a
            # uniform family, which is truthful: nothing distinguishes
            # the ranks from this vantage point)
            rank_norms = jnp.broadcast_to(
                jnp.where(jnp.isfinite(norm), norm, jnp.float32(-1.0)),
                (n_ranks,),
            )
        finite = jnp.isfinite(norm)
        if loss is not None:
            finite = finite & jnp.all(
                jnp.isfinite(jnp.asarray(loss, jnp.float32)))
        # armed after `warm` GOOD steps (the documented contract): only
        # good norms feed the EWMA, so counting bad ones toward warmup
        # would arm the spike verdict over a baseline of fewer samples
        # than the operator asked for
        warmed = (state.count - state.bad_count) >= warm
        spike = warmed & finite & (norm > sf * state.ewma) & (state.ewma > 0)
        bad = jnp.logical_or(~finite, spike)

        # the inner update runs unconditionally (a lax.cond would change
        # the collective schedule per verdict — exactly what HVD001/the
        # sanitizer forbid); its outputs are discarded by scalar selection
        updates, new_inner = tx.update(grads, state.inner, params, **extra)
        new_inner = jax.tree_util.tree_map(
            lambda old, new: jnp.where(bad, old, new), state.inner,
            new_inner,
        )
        # discard with NEGATIVE zero: apply_updates computes p + u, and
        # IEEE gives p + (+0.0) = +0.0 for p = -0.0 (sign bit flipped —
        # not bit-identical) while p + (-0.0) = p for EVERY p
        updates = jax.tree_util.tree_map(
            lambda u: jnp.where(bad, jnp.full_like(u, -0.0), u), updates)

        per_dtype = {
            k: jnp.where(
                jnp.isfinite(vec[i]), jnp.sqrt(vec[i]), jnp.float32(0))
            for i, k in enumerate(keys)
        }
        # fast-seed on the FIRST GOOD norm (not count==0: a bad step 0 —
        # chaos, loss-scale hunting — must not strand the baseline near 0
        # and make ordinary early fluctuation read as a spike at warmup)
        new_ewma = jnp.where(
            bad,
            state.ewma,
            jnp.where(
                state.ewma == 0, norm,
                (1.0 - alpha) * state.ewma + alpha * norm),
        )
        if scaling:
            grown = (~bad) & (state.good_streak + 1 >= grow_n)
            new_scale = jnp.where(
                bad,
                jnp.maximum(state.loss_scale * SCALE_BACKOFF, SCALE_MIN),
                jnp.where(
                    grown,
                    jnp.minimum(state.loss_scale * SCALE_GROWTH, SCALE_MAX),
                    state.loss_scale,
                ),
            )
            new_good = jnp.where(bad | grown, 0, state.good_streak + 1)
        else:
            new_scale = state.loss_scale
            new_good = state.good_streak
        bad_i = bad.astype(jnp.int32)
        new_state = NumericsGuardState(
            inner=new_inner,
            ewma=new_ewma,
            count=state.count + 1,
            bad_count=state.bad_count + bad_i,
            bad_streak=jnp.where(bad, state.bad_streak + 1, 0),
            last_bad=bad_i,
            last_finite=finite.astype(jnp.int32),
            last_norm=jnp.where(finite, norm, jnp.float32(0)),
            norms=per_dtype,
            loss_scale=new_scale,
            good_streak=new_good,
            # the compiled step records which in-jit chaos injections
            # executed THIS update (note_step consumes the host-side
            # charge from this, so a restored counter already past K can
            # never count an injection that never ran). Deliberately NOT
            # sticky: a bit persisted through a checkpoint would consume
            # a freshly-armed charge in the next run; the per-step policy
            # loop observes every boundary, so nothing is missed.
            chaos_fired=fired,
            rank_norms=rank_norms,
        )
        return updates, new_state

    return GuardedTransformation(init_fn, update_fn)


# --------------------------------------------------------------------------
# host-side verdict readers + policy


def _verdict_leaves(g):
    """(keys, leaf tuple) of every scalar the verdict needs, in the
    order :func:`_verdict_from` unpacks them."""
    keys = sorted(g.norms or {})
    return keys, (
        g.count, g.bad_count, g.bad_streak, g.last_bad, g.last_finite,
        g.last_norm, g.ewma, g.loss_scale, g.chaos_fired,
        [g.norms[k] for k in keys], g.rank_norms,
    )


def verdict(state_tree) -> Optional[dict]:
    """Host-readable view of the (first) guard state in `state_tree`, or
    None when there is none. Reading syncs on the scalar leaves — call at
    step boundaries, not inside the step (or use :func:`stage_verdict` +
    :func:`note_step_staged` to read one boundary late without fencing
    the dispatch chain)."""
    states = find_guard_states(state_tree)
    if not states:
        return None
    keys, leaves = _verdict_leaves(states[0])
    # ONE batched device->host fetch for every scalar the verdict needs
    # — per-leaf float()/int() reads each cost a separate blocking
    # transfer, turning the guard's "no host sync inside the step" into
    # ~a dozen syncs at every boundary of the elastic hot loop
    return _verdict_from(keys, jax.device_get(leaves))


def _verdict_from(keys, fetched) -> dict:
    (count, bad_count, bad_streak, last_bad, last_finite, last_norm,
     ewma, loss_scale, chaos_fired, per_dtype_vals, rank_norms) = fetched
    return {
        "count": int(count),
        "bad_count": int(bad_count),
        "bad_streak": int(bad_streak),
        "last_bad": bool(last_bad),
        "last_finite": bool(last_finite),
        "last_norm": float(last_norm),
        "ewma": float(ewma),
        "loss_scale": float(loss_scale),
        "chaos_fired": int(chaos_fired),
        "per_dtype": {
            k: float(v) for k, v in zip(keys, per_dtype_vals)
        },
        "rank_norms": [
            float(x) for x in np.asarray(rank_norms).reshape(-1)
        ],
    }


def note_step(step: int, state_tree) -> Optional[dict]:
    """Step-boundary bookkeeping: read the guard verdict from the carried
    state, mirror it into the metrics registry, cache the fingerprint
    record for :func:`publish_fingerprint`, and consume any in-jit chaos
    charge whose injection has fired (the guard counter passed its step).
    Returns the verdict (or None when the state carries no guard)."""
    v = verdict(state_tree)
    if v is None:
        return None
    return _note_verdict(step, v)


def stage_verdict(state_tree):
    """Asynchronously snapshot the guard scalars WITHOUT fencing the
    dispatch chain: the leaves are copied on-device (a handful of tiny
    eager copies — new buffers, so they survive the carried state being
    DONATED into the next step) and the host returns immediately. Feed
    the result to :func:`note_step_staged` one boundary later: by then
    the step has completed in the background and the device→host read
    returns without stalling the pipeline. Returns None when the tree
    carries no guard."""
    states = find_guard_states(state_tree)
    if not states:
        return None
    keys, leaves = _verdict_leaves(states[0])
    return keys, jax.tree_util.tree_map(jnp.copy, leaves)


def note_step_staged(step: int, staged) -> Optional[dict]:
    """:func:`note_step` for a verdict captured by :func:`stage_verdict`
    at an earlier boundary — same bookkeeping, one step late."""
    if staged is None:
        return None
    keys, leaves = staged
    return _note_verdict(step, _verdict_from(keys, jax.device_get(leaves)))


def _note_verdict(step: int, v: dict) -> dict:
    global _last_record
    if _metrics.enabled():
        _metrics.gauge(
            "numerics_guard_bad_steps",
            help="total steps the numerics guard marked BAD and skipped",
        ).set(v["bad_count"])
        _metrics.gauge(
            "numerics_guard_bad_streak",
            help="consecutive BAD steps (the rollback trigger)",
        ).set(v["bad_streak"])
        _metrics.gauge(
            "numerics_guard_grad_norm",
            help="global gradient norm of the last guarded step",
        ).set(v["last_norm"])
        _metrics.gauge(
            "numerics_guard_grad_norm_ewma",
            help="EWMA of the global gradient norm over good steps",
        ).set(v["ewma"])
        _metrics.gauge(
            "numerics_loss_scale",
            help="current dynamic loss scale (1 when scaling is off)",
        ).set(v["loss_scale"])
    with _lock:
        _last_record = {
            "step": int(step),
            "finite": int(v["last_finite"]),
            "norm": v["last_norm"],
            "per_dtype": v["per_dtype"],
            "rank_norms": v["rank_norms"],
        }
    if _chaos.enabled():
        # consume a charge ONLY when the compiled step recorded its
        # injection in the chaos_fired bitmask — a restored guard state
        # whose counter is already past K never executes the traced
        # `count == K` injection, so the charge (and the game-day
        # `resilience_chaos_injected` evidence) must stay un-fired
        if v["chaos_fired"] & 1 and _chaos.grad_nan_step() is not None:
            _chaos.consume_grad_nan()
        if v["chaos_fired"] & 2 and _chaos.grad_spike() is not None:
            _chaos.consume_grad_spike()
    return v


def maybe_note_output(step: int, out_tree) -> Optional[dict]:
    """:class:`training.InstrumentedStep`'s standalone hook (the elastic
    wrapper claims the boundary and runs :func:`note_step` itself).

    Fingerprint plane on: read the verdict from the step's RETURNED
    pytree synchronously so the record published at the next boundary
    carries real data instead of the default — one device→host sync per
    step, the documented cost of the opt-in plane.

    Plane off but a guard present: the troubleshooting contract is that
    ``HOROVOD_NUMERICS_GUARD=1`` *alone* feeds the ``numerics_guard_*``
    gauges and consumes fired chaos charges — but a synchronous read
    here would fence every step of a plain jitted loop. So the verdict
    is STAGED (:func:`stage_verdict`, an async on-device copy that
    survives donation) and noted one boundary late, preserving async
    dispatch; :func:`flush_staged` drains the final step's."""
    global _standalone_staged
    with _lock:
        if _external_boundary:
            return None
    if fingerprint_enabled():
        with _lock:
            _standalone_staged = None
        return note_step(step, out_tree)
    if not (_metrics.enabled() or _chaos.enabled()):
        return None
    staged = stage_verdict(out_tree)
    if staged is None:
        return None
    with _lock:
        pending, _standalone_staged = _standalone_staged, (step, staged)
    if pending is not None:
        return note_step_staged(pending[0], pending[1])
    return None


def flush_staged() -> Optional[dict]:
    """Drain the lagged standalone verdict (the LAST step of a loop has
    no next boundary to read it at) — called from ``basics.shutdown``;
    harmless when nothing is pending."""
    global _standalone_staged
    with _lock:
        pending, _standalone_staged = _standalone_staged, None
    if pending is None:
        return None
    return note_step_staged(pending[0], pending[1])


# --------------------------------------------------------------------------
# fingerprint plane: publish + cross-check + quarantine


def fingerprint_enabled() -> bool:
    """Fingerprint publication is on when forced via :func:`configure`,
    the ``HOROVOD_NUMERICS_FINGERPRINT`` env is truthy, or the
    ``grad_corrupt_rank`` chaos charge is armed (the drill implies the
    plane it drills)."""
    if _fp_override is not None:
        return _fp_override
    env = os.environ.get(FINGERPRINT_ENV, "")
    if env:
        return env.lower() not in ("0", "false", "off")
    return _chaos.enabled() and _chaos.grad_corrupt() is not None


def fingerprint_key(step: int, rank: int) -> str:
    return f"/numerics/{int(step)}/{int(rank)}"


def _store():
    """The fingerprint KV: an explicit :func:`configure` override, else
    the schedule sanitizer's store — fingerprints land beside the PR-8
    sanitizer records, on the launcher KV when one is wired up and the
    in-process store otherwise."""
    with _lock:
        if _kv is not None:
            return _kv
    from horovod_tpu.analysis import sanitizer as _sanitizer

    return _sanitizer._store()


def _identity():
    """(world, process_rank, process_size); a pre-init process is its own
    1-rank world (mirrors the sanitizer)."""
    try:
        from horovod_tpu import basics

        if basics.is_initialized():
            return (
                basics.size(), basics.process_rank(), basics.process_size()
            )
    except Exception as e:
        logger.debug("numerics identity probe failed: %s", e)
    return 1, 0, 1


def _default_record(step: int) -> dict:
    with _lock:
        if _last_record is not None:
            rec = dict(_last_record)
            rec["step"] = int(step)
            return rec
    return {"step": int(step), "finite": 1, "norm": 0.0, "per_dtype": {}}


def _corrupt_record(rec: dict) -> dict:
    """The chaos perturbation: what a rank with a silently corrupted
    gradient would publish — a non-finite fingerprint."""
    out = dict(rec)
    out["finite"] = 0
    out["norm"] = None
    return out


def publish_fingerprint(step: int, record: Optional[dict] = None) -> None:
    """Publish `step`'s gradient fingerprint to the KV. Single-controller
    writes one record for EVERY rank (the dispatching process computed
    them all), except a rank named by an armed ``grad_corrupt_rank``
    charge, whose copy is perturbed; multi-process ranks publish only
    their own (the matching process perturbs). The charge is consumed
    ONLY by the process that perturbs — a 1-rank world leaves it armed."""
    rec = record if record is not None else _default_record(step)
    world, prank, psize = _identity()
    store = _store()
    ttl = _env_float("HOROVOD_SANITIZE_TTL", 120.0)
    # sticky per-step perturbation: a step can be published from MORE
    # than one boundary hook (InstrumentedStep + the elastic wrapper);
    # once the charge perturbed a step, every republication of that step
    # keeps the perturbed record instead of overwriting it clean
    # device-rank ownership: with several devices per process (a 2-host
    # × 4-chip topology) each process owns `world // psize` consecutive
    # DEVICE ranks — `rank_norms` is indexed by device rank, so keying
    # the published record by process rank would misattribute a corrupt
    # chip to the wrong rank. Heterogeneous worlds (world % psize != 0)
    # fall back to one record per process.
    local = world // psize if psize > 1 and world % psize == 0 else 1
    with _lock:
        victim = _perturbed_steps.get(int(step))
    gc = _chaos.grad_corrupt() if _chaos.enabled() else None
    if victim is None and gc is not None and step >= gc[1]:
        r = gc[0]
        if world > 1 and not (0 < r < world):
            # fail loudly, not silently inject nothing: this charge can
            # NEVER fire in this world (rank 0 is the driver; r >= world
            # does not exist). A 1-rank world legitimately stays armed —
            # the drill may be aimed at a later multi-rank phase.
            global _warned_impossible_charge
            with _lock:
                warned = _warned_impossible_charge
                _warned_impossible_charge = True
            if not warned:
                logger.warning(
                    "chaos: grad_corrupt_rank=%d can never fire in a "
                    "%d-rank world (valid victims are 1..%d); the charge "
                    "stays armed", r, world, world - 1)
        elif psize > 1:
            # guarded by the invalid-rank branch above: rank 0 (the
            # driver, un-evictable) is never perturbed multi-process
            # either — it would gate publication forever
            if prank == r // local:
                _chaos.consume_grad_corrupt()
                victim = r
        elif 0 < r < world:
            victim = r
            _chaos.consume_grad_corrupt()
        if victim is not None:
            with _lock:
                _perturbed_steps[int(step)] = victim
    def _rank_record(r: int) -> dict:
        """Rank `r`'s own record: its PRE-reduction local norm when the
        guard gathered one (-1 = that rank's gradients were non-finite),
        else the shared record — localization needs the per-rank view,
        NOT the globally-agreed verdict every rank shares."""
        out = dict(rec)
        rns = rec.get("rank_norms") or []
        if len(rns) > r:
            rn = float(rns[r])
            out["norm"] = None if rn < 0 else rn
            out["finite"] = 0 if rn < 0 else 1
        out.pop("rank_norms", None)
        return out

    if psize > 1:
        for r in range(prank * local, prank * local + local):
            one = _corrupt_record(_rank_record(r)) \
                if r == victim else _rank_record(r)
            store.put(
                fingerprint_key(step, r),
                json.dumps(one, separators=(",", ":")).encode(), ttl=ttl)
        return
    for r in range(max(1, world)):
        one = _corrupt_record(_rank_record(r)) \
            if r == victim else _rank_record(r)
        store.put(
            fingerprint_key(step, r),
            json.dumps(one, separators=(",", ":")).encode(),
            ttl=ttl,
        )
    # bound the sticky map: steps far behind can no longer republish
    with _lock:
        for s in [s for s in _perturbed_steps if s < step - 64]:
            _perturbed_steps.pop(s, None)


def _schedule_diverged(step: int, rank: int) -> bool:
    """Did the PR-8 sanitizer already name (step, rank) as a SCHEDULE
    divergence? Then the anomaly is a control-flow bug, not data
    corruption — the numerics verdict defers to it."""
    try:
        from horovod_tpu.analysis import sanitizer as _sanitizer

        d = _sanitizer.last_divergence()
    except Exception as e:
        logger.debug("sanitizer divergence probe failed: %s", e)
        return False
    return (
        d is not None
        and d.get("step") == step
        and d.get("rank") == rank
    )


def cross_check_fingerprints(step: int) -> Optional[List[dict]]:
    """Rank 0: compare every rank's published fingerprint for `step`.
    A rank whose record is non-finite — or whose norm exceeds
    ``HOROVOD_NUMERICS_OUTLIER_FACTOR`` (default 100) times the median of
    the finite family — while its collective schedule matches is flagged:
    quarantined, counted (``numerics_corrupt_ranks{rank=}``), and fed to
    :func:`health.record_numeric_corruption` (SUSPECT with the rank
    named). Returns the list of corruption findings, or None."""
    global _last_corruption
    world, prank, psize = _identity()
    if prank != 0:
        return None
    store = _store()
    # records are keyed by DEVICE rank whenever the world divides evenly
    # over the processes (each process publishes its owned device ranks);
    # only a heterogeneous world falls back to per-process records
    n = world if psize == 1 or world % psize == 0 else psize
    records: Dict[int, dict] = {}
    missing = False
    for r in range(max(1, n)):
        blob = store.get(fingerprint_key(step, r))
        if blob is None:
            missing = True  # not published yet: defer, don't drop
            continue
        try:
            records[r] = json.loads(blob)
        except ValueError:
            # an unparseable blob is a VERDICT, not an absence: garbled
            # bytes often come from the exact corrupt host this plane
            # hunts, and dropping the record would count the step as
            # fully checked with the most-broken rank never examined.
            # Judge it like a non-finite fingerprint.
            records[r] = {"step": int(step), "finite": 0, "norm": None}
    deferred = False
    with _lock:
        if missing and records:
            # a peer's put has not landed (the corrupt rank is often the
            # SLOW one): remember the step and re-check at the next
            # boundaries instead of silently marking it done
            left = _pending_checks.get(step, PENDING_CHECK_ATTEMPTS) - 1
            if left > 0:
                _pending_checks[step] = left
                deferred = True
            else:
                _pending_checks.pop(step, None)
        else:
            _pending_checks.pop(step, None)
    if not records:
        return None
    if _metrics.enabled() and not deferred:
        # counted once per step, when the check COMPLETES (all records
        # present, or the retry budget exhausted) — a deferred step's
        # rechecks would otherwise inflate "steps checked" several-fold
        _metrics.counter(
            "numerics_fingerprints_checked",
            help="steps whose cross-rank gradient fingerprints rank 0 "
                 "compared",
        ).inc()
    finite_norms = [
        float(rec["norm"])
        for rec in records.values()
        if rec.get("finite", 1) and rec.get("norm") is not None
        and math.isfinite(float(rec["norm"]))
    ]
    med = float(np.median(finite_norms)) if finite_norms else 0.0
    factor = _env_float(OUTLIER_ENV, 100.0)
    # corruption is a MINORITY deviation from a healthy family: when the
    # finite ranks are not a strict majority, the step went bad globally
    # (a poisoned batch — the guard's skip already handled it) and naming
    # "corrupt ranks" would mass-quarantine the fleet (8→1) for one
    # skippable step
    if 2 * len(finite_norms) <= len(records):
        return None
    # a family missing members — mid-deferral OR at retry-budget
    # exhaustion — gets no norm-relative verdicts: 2 records of 8 would
    # otherwise form a 2-rank "majority" whose partial median can indict
    # a healthy rank. Non-finite records are corrupt regardless of
    # family, so those are still judged below.
    partial = deferred or len(records) < max(1, n)
    findings: List[dict] = []
    for r, rec in sorted(records.items()):
        if _schedule_diverged(step, r):
            continue
        with _lock:
            if (int(step), int(r)) in _flagged:
                continue  # already reported on an earlier recheck
        norm = rec.get("norm")
        corrupt = not rec.get("finite", 1) or (
            norm is not None and not math.isfinite(float(norm)))
        if not corrupt and partial:
            # the family is incomplete: a median over a partial record set
            # can indict a HEALTHY rank (2 of 8 landed, one corrupt at 600
            # and one healthy at 0.5 → median 300 puts the healthy rank
            # below med/factor) and _flagged would then mute the real
            # culprit forever. Non-finite records are corrupt regardless
            # of family, so those were judged above; the norm-relative
            # verdict requires every expected record — even at deferral-
            # budget exhaustion a sliver of the family convicts nobody.
            continue
        if not corrupt and norm is not None and med > 0:
            # symmetric family test: a rank blowing up (>factor×median)
            # OR collapsing (stuck-at-zero SDC, <median/factor) is
            # outside the fleet's family. norm == 0.0 exactly is the
            # no-signal sentinel the default record publishes — never a
            # verdict on its own. (`nv`, not `n`: the outer `n` is the
            # expected-record count)
            nv = float(norm)
            corrupt = nv > factor * med or (0.0 < nv < med / factor)
        if not corrupt:
            continue
        finding = {
            "step": int(step),
            "rank": int(r),
            "norm": norm,
            "finite": bool(rec.get("finite", 1)),
            "median_norm": med,
        }
        findings.append(finding)
        with _lock:
            _quarantine.add(int(r))
            _flagged.add((int(step), int(r)))
            # bound the memory: findings far behind can't recur
            for key in [x for x in _flagged if x[0] < step - 256]:
                _flagged.discard(key)
        _last_corruption = finding
        if _metrics.enabled():
            _metrics.counter(
                "numerics_corrupt_ranks",
                help="corrupt-gradient fingerprints attributed per rank",
                rank=int(r),
            ).inc()
        from horovod_tpu.resilience import health as _health

        _health.record_numeric_corruption(int(r), step=int(step))
        logger.warning(
            "numerics: rank %d published a corrupt gradient fingerprint "
            "at step %d (norm=%s, fleet median %.3g) — quarantined",
            r, step, norm, med,
        )
    return findings or None


def last_corruption() -> Optional[dict]:
    """The most recent corruption finding this process detected, or None."""
    return _last_corruption


def take_corrupt_ranks() -> List[int]:
    """Pop the quarantine set — the elastic coordinator's eviction feed
    (each returned rank is tombstoned on the next membership sweep)."""
    with _lock:
        out = sorted(_quarantine)
        _quarantine.clear()
    return out


def requeue_corrupt_ranks(ranks) -> None:
    """Put corrupt ranks the coordinator could NOT evict back in the
    quarantine set (rank 0 is the single-controller driver and cannot
    tombstone itself). The publish gate keys on :func:`quarantine_pending`
    — silently draining an un-evictable rank would re-open publication
    of a corrupt trainer's weights. No metrics here: the finding was
    already counted when it was flagged."""
    with _lock:
        _quarantine.update(int(r) for r in ranks)


def quarantine_pending() -> bool:
    with _lock:
        return bool(_quarantine)


def clear_quarantine() -> None:
    """Drop pending quarantine verdicts without evicting (operator
    override / non-elastic deployments)."""
    with _lock:
        _quarantine.clear()


_step = 0


def claim_boundary() -> None:
    """A driver with authoritative step numbering (the elastic wrapper)
    takes ownership of the fingerprint boundary; ``InstrumentedStep``'s
    generic :func:`set_step` hook stands down. Without a single owner,
    the two hooks' counters diverge after a step-fn rebuild (resize,
    rollback, resume) and every step is published twice under different
    keys. Sticky for the process; :func:`reset` clears it."""
    global _external_boundary
    with _lock:
        _external_boundary = True


def release_boundary() -> None:
    """Undo :func:`claim_boundary` when the owning driver's run ends: a
    later standalone ``InstrumentedStep`` loop in the same process must
    be able to publish again (a claim pinned until the test-only
    :func:`reset` would silently disable its fingerprint plane)."""
    global _external_boundary
    with _lock:
        _external_boundary = False


def set_step(step: int) -> None:
    """Open step `step`'s fingerprint scope: the step that just finished
    is published and (rank 0) cross-checked — the same boundary protocol
    as the schedule sanitizer. ``InstrumentedStep`` calls this per
    dispatched train step; explicit loops call :func:`boundary`. A no-op
    once a driver :func:`claim_boundary`-ed the protocol."""
    global _step
    prev = _step
    _step = int(step)
    if not fingerprint_enabled() or _external_boundary:
        return
    if prev == _step:
        # first call of a run (set_step(0) BEFORE step 0 executes): no
        # step has finished — publishing here would emit a premature
        # default record for step 0 whose boundary dedupe then suppresses
        # the REAL record
        return
    boundary(prev)


def boundary(step: int) -> Optional[List[dict]]:
    """Publish + cross-check `step`'s fingerprint (no-op when the plane
    is disabled). Consecutive duplicate calls for the same step are
    deduplicated — an instrumented step inside the elastic wrapper
    otherwise drives the boundary twice per step (double publish, double
    cross-check). A rollback legitimately revisits EARLIER steps, which
    never look like consecutive duplicates. Returns the corruption
    findings, if any."""
    global _last_boundary
    if not fingerprint_enabled():
        return None
    with _lock:
        dup = _last_boundary == int(step)
        _last_boundary = int(step)
        pending = sorted(_pending_checks)
    out: Optional[List[dict]] = None
    # re-check earlier steps whose peers had not published yet (the slow
    # rank — often the corrupt one — publishes late; its step must not
    # be silently dropped)
    for p in pending:
        if p != int(step):
            out = cross_check_fingerprints(p) or out
    if dup:
        return out
    publish_fingerprint(step)
    return cross_check_fingerprints(step) or out


# --------------------------------------------------------------------------
# finiteness + publish gate


def array_finite(a) -> bool:
    """Is this host array free of NaN/Inf? Integer/bool/object dtypes
    are trivially finite; dtypes the probe cannot judge (exotic custom
    dtypes) pass rather than invalidating otherwise-loadable data. THE
    one float-poison predicate — :func:`tree_finite`, the checkpoint
    validator, and the emergency-checkpoint gate all share it."""
    try:
        a = np.asarray(a)
        if a.dtype.kind in "fc" or "float" in str(a.dtype):
            return bool(np.isfinite(a).all())
    except (TypeError, ValueError) as e:
        logger.debug("finiteness probe skipped an array: %s", e)
    return True


def tree_finite(tree) -> bool:
    """Host-side finiteness sweep over the float/complex array leaves of
    `tree` (non-arrays and integer leaves pass). The checkpoint validator
    and the emergency-checkpoint path share this so a poisoned state can
    never displace the newest valid checkpoint."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if not isinstance(leaf, (jax.Array, np.ndarray, np.generic)):
            continue
        if not array_finite(leaf):
            return False
    return True


def checkpoint_finite_check_enabled() -> bool:
    """The checkpoint-poison sweep's opt-out
    (``HOROVOD_CHECKPOINT_FINITE_CHECK=0``): a state that LEGITIMATELY
    carries non-finite leaves — an additive ``-inf`` attention-mask
    buffer, a best-loss tracker initialized to ``inf`` — would otherwise
    invalidate EVERY checkpoint the run writes, and resume would silently
    restart from scratch. Gates both :func:`checkpoint.is_valid_checkpoint`'s
    non-finite rejection and the emergency-checkpoint finiteness sweep."""
    return os.environ.get(CKPT_FINITE_ENV, "1").lower() not in (
        "0", "false", "off")


def publish_gate_reason(state, tree) -> Optional[str]:
    """Why a weight publication of `tree` (extracted/consolidated from
    the full training `state`) must be refused, or None when it is safe:

    - ``"quarantine"`` — a corrupt rank was flagged and not yet evicted;
    - ``"bad_step"`` — the trainer's most recent guarded steps were BAD
      (the state being published may predate the anomaly, but the trainer
      is mid-incident: the staleness contract covers the gap);
    - ``"nonfinite"`` — the consolidated tree itself carries NaN/Inf (the
      defense of last resort — nothing upstream may ever let this pass).

    Disabled with ``HOROVOD_PUBLISH_NUMERICS_GATE=0``.
    """
    if os.environ.get(GATE_ENV, "1").lower() in ("0", "false", "off"):
        return None
    if quarantine_pending():
        return "quarantine"
    try:
        v = verdict(state) if state is not None else None
    except Exception as e:
        logger.debug("publish gate verdict read failed: %s", e)
        v = None
    if v is not None and v["bad_streak"] > 0:
        return "bad_step"
    if not tree_finite(tree):
        return "nonfinite"
    return None
