"""horovod_tpu: a TPU-native distributed deep-learning training framework.

Capability surface modeled on Horovod 0.19.2 (reference: morganwang010/horovod
``horovod/__init__.py``), redesigned for TPUs: collectives lower to XLA
(``lax.psum`` / ``lax.all_gather`` / ``lax.ppermute``) over a named
``jax.sharding.Mesh`` spanning ICI/DCN, rather than NCCL/MPI/Gloo rings.

Reference API parity map (file:line cites are into the reference tree):

- ``hvd.init/shutdown/rank/size/local_rank/local_size/...``
  (reference ``horovod/common/basics.py:22-131``) -> :mod:`horovod_tpu.basics`
- ``hvd.allreduce/allgather/broadcast`` + Sum/Average/Adasum ops
  (reference ``horovod/tensorflow/mpi_ops.py``, ``horovod/torch/mpi_ops.py``)
  -> :mod:`horovod_tpu.ops`
- ``DistributedOptimizer`` / ``DistributedGradientTape``
  (reference ``horovod/tensorflow/__init__.py:270-535``,
  ``horovod/torch/__init__.py:67-222``) -> :mod:`horovod_tpu.optim`
- tensor fusion / response cache / autotune / timeline / stall inspection
  (reference ``horovod/common/``) -> native C++ core in ``csrc/`` +
  :mod:`horovod_tpu.core`
- ``horovodrun`` launcher (reference ``horovod/run/``) -> :mod:`horovod_tpu.run`
"""

__version__ = "0.1.0"

from horovod_tpu.basics import (  # noqa: F401
    init,
    shutdown,
    is_initialized,
    rank,
    size,
    local_rank,
    local_size,
    local_chip_count,
    cross_rank,
    cross_size,
    process_rank,
    process_size,
    is_homogeneous,
    health,
    health_state,
    mesh,
    data_axis,
    mpi_threads_supported,
    mpi_enabled,
    gloo_enabled,
    num_rank_is_power_2,
    gpu_available,
    nccl_built,
    mpi_built,
    gloo_built,
    ccl_built,
    ddl_built,
    xla_built,
)
from horovod_tpu.ops import (  # noqa: F401
    Average,
    Sum,
    Adasum,
    ReduceOp,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    grouped_allreduce,
    grouped_allgather,
    allgather,
    allgather_async,
    allgather_object,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    broadcast_object,
    alltoall,
    alltoall_async,
    reducescatter,
    reducescatter_async,
    synchronize,
    poll,
    join,
)
from horovod_tpu.compression import Compression  # noqa: F401
from horovod_tpu.optim import (  # noqa: F401
    DistributedOptimizer,
    DistributedGradientTape,
    broadcast_parameters,
    broadcast_variables,
    broadcast_optimizer_state,
    fused_adam,
    reshard_optimizer_state,
    FsdpParams,
    fsdp_pack_params,
    fsdp_unpack_params,
    fsdp_gather_params,
    fsdp_reshard_params,
)
from horovod_tpu import profiler  # noqa: F401
from horovod_tpu import tuning  # noqa: F401
from horovod_tpu import observability  # noqa: F401
from horovod_tpu.observability import metrics  # noqa: F401
from horovod_tpu.serving import subscribe_weights  # noqa: F401
