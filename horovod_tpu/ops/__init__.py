"""Collective operations (allreduce / allgather / broadcast / ...).

TPU-native analog of Horovod's op layer (reference
``horovod/tensorflow/mpi_ops.py``, ``horovod/torch/mpi_ops.py``,
``horovod/common/ops/``): ops lower to XLA collectives over the global mesh
instead of NCCL/MPI/Gloo calls.
"""

from horovod_tpu.ops.collective import (  # noqa: F401
    Average,
    Sum,
    Adasum,
    ReduceOp,
    Handle,
    allreduce,
    allreduce_,
    allreduce_async,
    allreduce_async_,
    grouped_allreduce,
    grouped_allgather,
    grouped_allreduce_async,
    allgather,
    allgather_async,
    allgather_object,
    broadcast,
    broadcast_,
    broadcast_async,
    broadcast_async_,
    broadcast_object,
    alltoall,
    alltoall_async,
    reducescatter,
    reducescatter_async,
    synchronize,
    poll,
    join,
)
from horovod_tpu.ops import overlap  # noqa: F401
from horovod_tpu.ops.hierarchical import (  # noqa: F401
    hierarchical_allreduce,
    hierarchical_allgather,
    hier_allreduce,
    hier_allgather,
    set_hierarchical,
    set_hierarchical_allgather,
)
