"""Adasum reduction (reference ``horovod/common/ops/adasum/adasum.h``).

The reference implements vector-halving distance-doubling (VHDD): at level
``l`` each rank pairs with ``rank ^ 2^l``, the pair computes
``a·b, |a|^2, |b|^2`` and combines ``a' = (1 - dot/(2|a|^2)) a +
(1 - dot/(2|b|^2)) b`` (``adasum.h:194-398``). The TPU-native formulation is
the ``ppermute`` butterfly in :func:`_adasum_butterfly` below, usable in-jit
(inside ``shard_map``), on eager stacked arrays, and on multi-process
host-local values. ``tests/test_ops.py`` checks it against a NumPy VHDD
oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu import basics


def _require_flat_axis(ax):
    if isinstance(ax, tuple):
        raise ValueError(
            "Adasum does not support hierarchical (tuple) axes; the VHDD "
            "butterfly needs one flat rank ordering — pass a single-axis "
            "mesh or an explicit axis"
        )
    return ax


def _pair_combine(a, b):
    """One Adasum pairwise combine (reference ``adasum.h:271-337``:
    ComputeDotAndNormSqrds + ScaledAdd). Under ``HOROVOD_PALLAS`` the
    three reductions come out of ONE fused read of both operands
    (:func:`horovod_tpu.ops.pallas_kernels.adasum_pair_combine`); the
    chunked partial sums change the f32 reduction order, so equivalence
    is pinned to tolerance in tests/test_pallas.py."""
    from horovod_tpu.ops import pallas_kernels as _pk

    if _pk.enabled():
        return _pk.adasum_pair_combine(a, b)
    dot = jnp.vdot(a, b).real.astype(jnp.float32)
    na = jnp.vdot(a, a).real.astype(jnp.float32)
    nb = jnp.vdot(b, b).real.astype(jnp.float32)
    ca = jnp.where(na == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(na, 1e-30)))
    cb = jnp.where(nb == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(nb, 1e-30)))
    return (ca * a.astype(jnp.float32) + cb * b.astype(jnp.float32)).astype(a.dtype)


def adasum_allreduce(tensor, *, axis=None, name=None):
    """Adasum allreduce over the data axis via a ppermute butterfly.

    Power-of-2 rank counts only, matching the reference's constraint
    (``torch/mpi_ops.py:117-118``).
    """
    ax = axis if axis is not None else basics.data_axis()
    _require_flat_axis(ax)
    n = basics.mesh().shape[ax]
    if not basics.num_rank_is_power_2(n):
        raise ValueError(
            f"Adasum requires a power-of-2 number of ranks, got {n} "
            "(reference horovod/torch/mpi_ops.py:117-118)"
        )
    if isinstance(tensor, jax.core.Tracer):
        from horovod_tpu.ops.collective import _axis_bound

        if not _axis_bound(ax):
            return tensor  # global value: adasum of identical tensors is identity
        return _adasum_butterfly(tensor, ax, n)

    from horovod_tpu.ops.collective import (
        _as_array, _hostlocal_mode, _is_stacked,
    )

    tensor = _as_array(tensor)
    if _hostlocal_mode(tensor):
        # multi-process: this process's contribution, tiled over its local
        # chips. Tiling is harmless for Adasum — combine(a, a) = a — so the
        # chip-level butterfly computes exactly VHDD over process values.
        # Flattened so join() zero-backfill shape-matches (hostlocal.py).
        from horovod_tpu.ops import hostlocal

        shape = tensor.shape
        g = hostlocal._stack_local(jnp.reshape(tensor, (-1,)), ax)
        out = _eager_adasum_fn(basics.mesh(), ax, n, _pallas_key())(g)
        return jnp.reshape(jnp.squeeze(out, axis=0), shape)

    # eager single-controller: stacked [n, ...] per-rank values
    if not _is_stacked(tensor, ax):
        # replicated input: all ranks identical; adasum(a, a) = a
        return tensor

    out = _eager_adasum_fn(basics.mesh(), ax, n, _pallas_key())(tensor)
    return jnp.squeeze(out, axis=0)


def _pallas_key():
    """Resolved ``HOROVOD_PALLAS`` state, mixed into the compiled eager
    program caches (the traced combines consult the knob)."""
    from horovod_tpu.ops import pallas_kernels as _pk

    return _pk.cache_key()


@functools.lru_cache(maxsize=None)
def _eager_adasum_fn(mesh, ax, n, pallas_key=(False, False)):
    """Compile once per (mesh, axis); jit's own cache handles shape/dtype."""
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops.collective import _smap

    def fn(v):
        v = jnp.squeeze(v, axis=0)
        r = _adasum_butterfly(v, ax, n)
        return r[None]

    return jax.jit(_smap(fn, mesh, (P(ax),), P()))


def _adasum_butterfly(v, ax, n):
    """VHDD butterfly: level l exchanges with partner rank^2^l via ppermute.

    Unlike the reference there is no vector *halving* (the scalar reductions
    ride ICI at full bandwidth and XLA fuses the elementwise combine), so each
    level is one ppermute of the full tensor + one fused combine; log2(n)
    levels total, numerically identical to the reference's recursion order.
    """
    idx = lax.axis_index(ax)
    level = 1
    while level < n:
        perm = [(i, i ^ level) for i in range(n)]
        # hvdlint: waive=HVD002 trip count is log2(axis size) — static at trace time
        partner = lax.ppermute(v, ax, perm)
        lower = (idx & level) == 0
        a = jnp.where(lower, v, partner)
        b = jnp.where(lower, partner, v)
        v = _pair_combine(a, b)
        level *= 2
    return v


# --------------------------------------------------------------- fused group


def _segment_combine(a, b, seg_ids, n_segments):
    """Per-tensor Adasum combine over a concatenated flat buffer: all
    dot/norm scalars come out of ONE fused elementwise+segment-reduce pass
    (the role of the reference's ``FusedPairwiseReduceWithComm``,
    ``adasum.h:194-398``, which walks fusion-buffer offsets). Under
    ``HOROVOD_PALLAS`` that pass is the real fused VMEM kernel
    (:func:`horovod_tpu.ops.pallas_kernels.adasum_segment_combine`); the
    flat layout — and the butterfly's ``ppermute`` signature — is
    identical either way."""
    from horovod_tpu.ops import pallas_kernels as _pk

    if _pk.enabled():
        return _pk.adasum_segment_combine(a, b, seg_ids, n_segments)
    dot = jax.ops.segment_sum(a * b, seg_ids, num_segments=n_segments)
    na = jax.ops.segment_sum(a * a, seg_ids, num_segments=n_segments)
    nb = jax.ops.segment_sum(b * b, seg_ids, num_segments=n_segments)
    ca = jnp.where(na == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(na, 1e-30)))
    cb = jnp.where(nb == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(nb, 1e-30)))
    return ca[seg_ids] * a + cb[seg_ids] * b


def _grouped_butterfly(flat, seg_ids, n_segments, ax, n):
    """One ppermute per level for the WHOLE tensor group (vs one per tensor):
    an N-tensor Adasum step issues log2(n) collectives, not N*log2(n)."""
    idx = lax.axis_index(ax)
    level = 1
    while level < n:
        perm = [(i, i ^ level) for i in range(n)]
        # hvdlint: waive=HVD002 trip count is log2(axis size) — static at trace time
        partner = lax.ppermute(flat, ax, perm)
        lower = (idx & level) == 0
        a = jnp.where(lower, flat, partner)
        b = jnp.where(lower, partner, flat)
        flat = _segment_combine(a, b, seg_ids, n_segments)
        level *= 2
    return flat


def _flatten_group(tensors):
    """(flat fp32 concat, seg_ids, offsets). The combine runs in fp32 for
    every dtype (the per-level cast the single-tensor path does anyway);
    results cast back to each tensor's own dtype on split."""
    sizes = [int(np.prod(t.shape)) if t.shape else 1 for t in tensors]
    seg_ids = np.repeat(np.arange(len(tensors)), sizes)
    flat = jnp.concatenate(
        [jnp.ravel(t).astype(jnp.float32) for t in tensors]
    )
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    return flat, jnp.asarray(seg_ids), offsets


def _split_group(flat, offsets, shapes, dtypes):
    return [
        jnp.reshape(flat[int(offsets[i]):int(offsets[i + 1])], shapes[i])
        .astype(dtypes[i])
        for i in range(len(shapes))
    ]


def grouped_adasum_allreduce(tensors, *, axis=None, name=None):
    """Fused Adasum of a tensor group: all per-tensor dot/norm scalars in one
    launch and ONE combined butterfly pass (reference ``adasum.h:194-398``
    fuses the same way over its fusion buffer). O(log n) collectives per
    step regardless of tensor count."""
    ax = axis if axis is not None else basics.data_axis()
    _require_flat_axis(ax)
    n = basics.mesh().shape[ax]
    if not basics.num_rank_is_power_2(n):
        raise ValueError(
            f"Adasum requires a power-of-2 number of ranks, got {n} "
            "(reference horovod/torch/mpi_ops.py:117-118)"
        )
    tensors = list(tensors)
    if not tensors:
        return []
    shapes = [t.shape for t in tensors]
    dtypes = [t.dtype for t in tensors]

    if any(isinstance(t, jax.core.Tracer) for t in tensors):
        from horovod_tpu.ops.collective import _axis_bound

        if not _axis_bound(ax):
            return tensors  # global values: adasum of identical copies
        flat, seg_ids, offsets = _flatten_group(tensors)
        out = _grouped_butterfly(flat, seg_ids, len(tensors), ax, n)
        return _split_group(out, offsets, shapes, dtypes)

    from horovod_tpu.ops.collective import (
        _as_array, _hostlocal_mode, _is_stacked,
    )

    tensors = [_as_array(t) for t in tensors]
    modes = [_hostlocal_mode(t) for t in tensors]
    if any(modes) and not all(modes):
        # mixed host-local/global lists dispatch per tensor, mirroring the
        # non-Adasum grouped path (a global mesh array spanning other
        # processes' devices cannot be flattened into the local concat)
        return [adasum_allreduce(t, axis=ax) for t in tensors]
    if all(modes):
        # multi-process: flat-concat this process's contributions, tile over
        # its chips (combine(a, a) = a makes tiling harmless), one grouped
        # butterfly across processes
        from horovod_tpu.ops import hostlocal

        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        seg_np = np.repeat(np.arange(len(tensors)), sizes)
        local_flat = jnp.concatenate(
            [jnp.ravel(t).astype(jnp.float32) for t in tensors]
        )
        offsets = np.concatenate([[0], np.cumsum(sizes)])
        g = hostlocal._stack_local(local_flat, ax)
        fn = _eager_grouped_adasum_fn(
            basics.mesh(), ax, n, len(tensors), _pallas_key())
        out = jnp.squeeze(fn(g, jnp.asarray(seg_np)), axis=0)
        return _split_group(out, offsets, shapes, dtypes)

    stacked = [_is_stacked(t, ax) for t in tensors]
    if not any(stacked):
        return tensors  # replicated: adasum(a, a) = a
    if not all(stacked):
        return [adasum_allreduce(t, axis=ax) for t in tensors]
    sizes = [int(np.prod(s[1:])) if len(s) > 1 else 1 for s in shapes]
    seg_np = np.repeat(np.arange(len(tensors)), sizes)
    flat = jnp.concatenate(
        [jnp.reshape(t, (t.shape[0], -1)).astype(jnp.float32)
         for t in tensors],
        axis=1,
    )
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    fn = _eager_grouped_adasum_fn(
        basics.mesh(), ax, n, len(tensors), _pallas_key())
    out = jnp.squeeze(fn(flat, jnp.asarray(seg_np)), axis=0)
    return [
        jnp.reshape(out[int(offsets[i]):int(offsets[i + 1])], shapes[i][1:])
        .astype(dtypes[i])
        for i in range(len(shapes))
    ]


@functools.lru_cache(maxsize=None)
def _eager_grouped_adasum_fn(mesh, ax, n, n_segments,
                             pallas_key=(False, False)):
    """Compile once per (mesh, axis, group size); jit re-traces per shape."""
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops.collective import _smap

    def fn(v, seg_ids):
        v = jnp.squeeze(v, axis=0)
        r = _grouped_butterfly(v, seg_ids, n_segments, ax, n)
        return r[None]

    return jax.jit(_smap(fn, mesh, (P(ax), P()), P()))
