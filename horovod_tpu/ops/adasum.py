"""Adasum reduction (reference ``horovod/common/ops/adasum/adasum.h``).

The reference implements vector-halving distance-doubling (VHDD): at level
``l`` each rank pairs with ``rank ^ 2^l``, the pair computes
``a·b, |a|^2, |b|^2`` and combines ``a' = (1 - dot/(2|a|^2)) a +
(1 - dot/(2|b|^2)) b`` (``adasum.h:194-398``). The TPU-native formulation is
the ``ppermute`` butterfly in :func:`_adasum_butterfly` below, usable in-jit
(inside ``shard_map``), on eager stacked arrays, and on multi-process
host-local values. ``tests/test_ops.py`` checks it against a NumPy VHDD
oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu import basics


def _pair_combine(a, b):
    """One Adasum pairwise combine (reference ``adasum.h:271-337``:
    ComputeDotAndNormSqrds + ScaledAdd)."""
    dot = jnp.vdot(a, b).real.astype(jnp.float32)
    na = jnp.vdot(a, a).real.astype(jnp.float32)
    nb = jnp.vdot(b, b).real.astype(jnp.float32)
    ca = jnp.where(na == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(na, 1e-30)))
    cb = jnp.where(nb == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(nb, 1e-30)))
    return (ca * a.astype(jnp.float32) + cb * b.astype(jnp.float32)).astype(a.dtype)


def adasum_allreduce(tensor, *, axis=None, name=None):
    """Adasum allreduce over the data axis via a ppermute butterfly.

    Power-of-2 rank counts only, matching the reference's constraint
    (``torch/mpi_ops.py:117-118``).
    """
    ax = axis if axis is not None else basics.data_axis()
    n = basics.mesh().shape[ax]
    if n & (n - 1) != 0:
        raise ValueError(
            f"Adasum requires a power-of-2 number of ranks, got {n} "
            "(reference horovod/torch/mpi_ops.py:117-118)"
        )
    if isinstance(tensor, jax.core.Tracer):
        from horovod_tpu.ops.collective import _axis_bound

        if not _axis_bound(ax):
            return tensor  # global value: adasum of identical tensors is identity
        return _adasum_butterfly(tensor, ax, n)

    from horovod_tpu.ops.collective import (
        _as_array, _hostlocal_mode, _is_stacked,
    )

    tensor = _as_array(tensor)
    if _hostlocal_mode(tensor):
        # multi-process: this process's contribution, tiled over its local
        # chips. Tiling is harmless for Adasum — combine(a, a) = a — so the
        # chip-level butterfly computes exactly VHDD over process values.
        # Flattened so join() zero-backfill shape-matches (hostlocal.py).
        from horovod_tpu.ops import hostlocal

        shape = tensor.shape
        g = hostlocal._stack_local(jnp.reshape(tensor, (-1,)), ax)
        out = _eager_adasum_fn(basics.mesh(), ax, n)(g)
        return jnp.reshape(jnp.squeeze(out, axis=0), shape)

    # eager single-controller: stacked [n, ...] per-rank values
    if not _is_stacked(tensor, ax):
        # replicated input: all ranks identical; adasum(a, a) = a
        return tensor

    out = _eager_adasum_fn(basics.mesh(), ax, n)(tensor)
    return jnp.squeeze(out, axis=0)


@functools.lru_cache(maxsize=None)
def _eager_adasum_fn(mesh, ax, n):
    """Compile once per (mesh, axis); jit's own cache handles shape/dtype."""
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.ops.collective import _smap

    def fn(v):
        v = jnp.squeeze(v, axis=0)
        r = _adasum_butterfly(v, ax, n)
        return r[None]

    return jax.jit(_smap(fn, mesh, (P(ax),), P()))


def _adasum_butterfly(v, ax, n):
    """VHDD butterfly: level l exchanges with partner rank^2^l via ppermute.

    Unlike the reference there is no vector *halving* (the scalar reductions
    ride ICI at full bandwidth and XLA fuses the elementwise combine), so each
    level is one ppermute of the full tensor + one fused combine; log2(n)
    levels total, numerically identical to the reference's recursion order.
    """
    idx = lax.axis_index(ax)
    level = 1
    while level < n:
        perm = [(i, i ^ level) for i in range(n)]
        partner = lax.ppermute(v, ax, perm)
        lower = (idx & level) == 0
        a = jnp.where(lower, v, partner)
        b = jnp.where(lower, partner, v)
        v = _pair_combine(a, b)
        level *= 2
    return v
