"""XLA-native collective ops.

Semantics follow Horovod 0.19.2's op layer (reference
``horovod/tensorflow/mpi_ops.py:104-201``, ``horovod/torch/mpi_ops.py:94-524``,
dispatch in ``horovod/common/ops/``), but execution is pure XLA:

- **in-jit path** — inside a ``shard_map``/``pjit`` region the ops are thin
  wrappers over ``lax.psum``/``lax.all_gather``/``lax.all_to_all`` on the named
  mesh axis. This is the hot path: XLA fuses, schedules, and overlaps the
  collectives with compute (the role NCCL streams + the fusion buffer play in
  the reference, ``nccl_operations.cc:109-159``).
- **eager path** — on concrete ``jax.Array``s we compile (and cache) a tiny
  ``shard_map`` program per (op, shape, dtype). Dispatch is asynchronous, so the
  returned array doubles as Horovod's async handle: ``synchronize`` is
  ``block_until_ready`` (the reference's handle manager + finalizer-thread
  machinery, ``torch/handle_manager.cc``, ``gpu_operations.h:101-112``, is
  subsumed by XLA's async runtime).

Per-rank values in the eager single-controller world are represented as a
*stacked* leading rank axis sharded over the data axis (shape ``[size, ...]``);
arrays without that sharding are treated as replicated (every rank holds the
same tensor), which matches running the same program on every Horovod rank.
"""

from __future__ import annotations

import enum
import functools
import os
import pickle
import threading
import warnings
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 stable name
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

from horovod_tpu import basics
from horovod_tpu.analysis import sanitizer as _sanitizer
from horovod_tpu.observability import (
    flight as _flight,
    metrics as _metrics,
    straggler as _straggler,
    trace as _trace,
)
from horovod_tpu.resilience import chaos as _chaos, retry as _retry


class ReduceOp(enum.IntEnum):
    """Reduction ops (reference ``horovod_reduce_op_{average,sum,adasum}``,
    ``common/operations.cc:770-799``)."""

    AVERAGE = 0
    SUM = 1
    ADASUM = 2


Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM


class Handle:
    """Async-op handle (reference ``torch/handle_manager.{h,cc}``; poll/wait
    semantics ``torch/mpi_ops.py:475-524``). JAX dispatch is already async, so
    the handle just owns the in-flight arrays and its registered name."""

    __slots__ = ("_values", "_name")

    def __init__(self, values, name=None):
        self._values = values if isinstance(values, (list, tuple)) else [values]
        self._name = name

    def done(self) -> bool:
        return all(_array_ready(v) for v in self._values)

    def wait(self, timeout=None):
        """Block until the op completes and return its value(s).

        ``timeout`` exists for signature parity with ``CoreHandle.wait`` but
        is NOT enforced on this path: XLA's ``block_until_ready`` has no
        interruptible form, so the call blocks until completion regardless.
        Callers relying on the timeout for stall detection get a one-time
        warning so the silent divergence is visible.
        """
        if timeout is not None:
            warnings.warn(
                "Handle.wait(timeout=...) is not enforced on the XLA path "
                "(block_until_ready is uninterruptible); the call blocks "
                "until completion. Attach the native core for bounded waits.",
                RuntimeWarning,
                stacklevel=2,
            )
        for v in self._values:
            v.block_until_ready()
        _release_name(self._name)
        if len(self._values) == 1:
            return self._values[0]
        return list(self._values)


_outstanding_lock = threading.Lock()
_outstanding_names = set()


def _register_name(name: Optional[str]):
    """Duplicate outstanding names are an error, as in the reference
    (``DUPLICATE_NAME_ERROR``, ``common/common.h:161-164``)."""
    if name is None:
        return
    with _outstanding_lock:
        if name in _outstanding_names:
            raise ValueError(
                f"Duplicate tensor name '{name}' in outstanding collective; "
                "synchronize the previous op first (reference DUPLICATE_NAME_ERROR)."
            )
        _outstanding_names.add(name)


def _release_name(name: Optional[str]):
    if name is None:
        return
    with _outstanding_lock:
        _outstanding_names.discard(name)


def _async(op_fn, name):
    """Register `name`, run the op, and release the name if the op itself
    fails (otherwise the name would be poisoned forever)."""
    _register_name(name)
    try:
        out = op_fn()
    except BaseException:
        _release_name(name)
        raise
    return Handle(out, name=name)


def _array_ready(v) -> bool:
    try:
        return v.is_ready()
    except AttributeError:  # pragma: no cover
        return True


def synchronize(handle: Handle):
    """Block until the handle's op completed and return its output
    (reference ``torch/mpi_ops.py:491-508``)."""
    return handle.wait()


def poll(handle: Handle) -> bool:
    """Nonblocking completion check (reference ``torch/mpi_ops.py:475-489``)."""
    return handle.done()


def join() -> int:
    """Uneven-data join (reference ``torch/mpi_ops.py:511-524``,
    ``controller.cc:219-307``): a joined rank keeps participating in the
    other ranks' collectives with zero contributions until every rank joins;
    returns the last rank to join.

    With the native core attached this blocks on the controller's JOIN
    response while the background cycle zero-backfills negotiated reductions
    (``core.py::_execute_backfilled``). Under single-controller SPMD every
    chip executes the same program, so there is no raggedness to repair and
    join degenerates to a no-op returning ``rank()``."""
    basics._require_init()
    core = basics._state.core
    if core is not None:
        from horovod_tpu.core import JOIN_TENSOR_NAME, REQUEST_JOIN

        h = core.enqueue(
            JOIN_TENSOR_NAME, np.zeros((0,), np.float32), REQUEST_JOIN
        )
        return int(h.wait())
    return basics.rank()


# --------------------------------------------------------------------------
# helpers


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _hier_enabled() -> bool:
    from horovod_tpu.ops import hierarchical

    return hierarchical.enabled()


def _hier_allgather_enabled() -> bool:
    from horovod_tpu.ops import hierarchical

    return hierarchical.allgather_enabled()


def _axis_bound(ax) -> bool:
    """True iff `ax` is a bound collective axis in the current trace (i.e. we
    are inside a shard_map/pmap region over it). Outside such a region a traced
    value is *global*: under jit + input sharding XLA inserts the cross-chip
    reductions itself, so collectives degrade to their replicated semantics
    (the TPU-native analog of Horovod's single-rank degenerate mode)."""
    if isinstance(ax, tuple):
        return all(_axis_bound(a) for a in ax)
    try:
        lax.axis_index(ax)
        return True
    except NameError:
        return False


def _axis(axis):
    """Normalize the axis arg: default data axis, lists → tuples. A 2-tuple
    ``(cross, local)`` selects the host-hierarchy pair (see
    :mod:`horovod_tpu.ops.hierarchical`)."""
    if axis is None:
        return basics.data_axis()
    return tuple(axis) if isinstance(axis, (list, tuple)) else axis


def _mesh_axis_size(mesh, axis) -> int:
    if isinstance(axis, tuple):
        n = 1
        for a in axis:
            n *= mesh.shape[a]
        return n
    return mesh.shape[axis]


def _axis_size(axis) -> int:
    return _mesh_axis_size(basics.mesh(), axis)


def _hostlocal_mode(x) -> bool:
    """True iff we are multi-process and `x` is this process's host-local
    contribution (the Horovod per-worker model) rather than a global array."""
    from horovod_tpu.ops import hostlocal

    return basics.process_size() > 1 and not hostlocal.is_global_array(x)


def _is_stacked(x, axis) -> bool:
    """True iff x's leading dim is the per-rank axis sharded over `axis`
    (any member of it, for a multi-axis tuple)."""
    sharding = getattr(x, "sharding", None)
    if not isinstance(sharding, NamedSharding):
        return False
    spec = sharding.spec
    if not spec or spec[0] is None:
        return False
    first = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
    axes = axis if isinstance(axis, tuple) else (axis,)
    return any(a in first for a in axes)


def _as_array(x):
    if isinstance(x, (jnp.ndarray, jax.Array)):
        return x
    return jnp.asarray(np.asarray(x))


def _div(x, n):
    if jnp.issubdtype(x.dtype, jnp.integer) or x.dtype == jnp.bool_:
        return (x / n).astype(x.dtype)
    return x / jnp.asarray(n, dtype=x.dtype)



def _smap(fn, mesh, in_specs, out_specs):
    """shard_map with the static replication check disabled: collectives like
    all_gather/ppermute produce values the checker cannot prove replicated."""
    try:
        return _shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except TypeError:  # pragma: no cover - older jax spelling
        return _shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )

# --------------------------------------------------------------------------
# compiled eager kernels (cached per mesh/shape/dtype/op)

#: XLA:CPU's in-process communicator rendezvouses per-device partition
#: threads with NO ordering across concurrently-launched programs: two
#: collective programs in flight (e.g. the core's cycle thread + a user
#: thread's eager hostlocal op) can each capture part of the thread pool and
#: abort on the fixed rendezvous timeout. On CPU every eager collective
#: launch therefore serializes through this lock and completes before the
#: next starts. TPU orders launches on the per-device stream — no wrapping.
_cpu_collective_lock = threading.Lock()


def _flat_axis_index(mesh, axis):
    """Row-major rank within `axis` (a name or a tuple of names) — the
    in-shard_map analog of the flattened data-axis coordinate."""
    if not isinstance(axis, tuple):
        return lax.axis_index(axis)
    idx = lax.axis_index(axis[0])
    for a in axis[1:]:
        idx = idx * mesh.shape[a] + lax.axis_index(a)
    return idx


def _cpu_serialized(jitfn):
    if jax.default_backend() != "cpu":
        return jitfn

    def locked(*args):
        with _cpu_collective_lock:
            out = jitfn(*args)
            jax.block_until_ready(out)
            return out

    return locked


#: substrings marking an eager-dispatch failure as transient — deliberately
#: narrow: only the XLA:CPU in-process communicator's rendezvous-abort
#: class (surfaces as DEADLINE_EXCEEDED mentioning the rendezvous), where a
#: re-dispatch genuinely succeeds. Broad markers like UNAVAILABLE/CANCELLED
#: retried permanent failures (device loss, interpreter shutdown) and
#: delayed their surfacing.
_TRANSIENT_DISPATCH_MARKERS = (
    "deadline exceeded",
    "deadline_exceeded",
    "rendezvous",
)

_dispatch_policy: Optional[_retry.RetryPolicy] = None


def _get_dispatch_policy() -> _retry.RetryPolicy:
    """Shared policy for eager launch retries, built lazily on first
    dispatch so ``HOROVOD_RETRY_COLLECTIVE_DISPATCH_*`` set by user code
    after ``import horovod_tpu`` is still honored (the KV and
    worker-restart policies read the env at use time too)."""
    global _dispatch_policy
    if _dispatch_policy is None:
        _dispatch_policy = _retry.policy_from_env(
            "collective_dispatch", max_attempts=3, base_delay=0.05,
            max_delay=1.0,
        )
    return _dispatch_policy


def _transient_dispatch_error(e: BaseException) -> bool:
    """Is this eager-launch failure worth re-dispatching? Only when every
    participant aborted together: chaos injections and, single-process, the
    XLA:CPU rendezvous-timeout class. Multi-process failures are never
    retried unilaterally — a rank relaunching a collective its peers
    completed would desynchronize the job."""
    if isinstance(e, _retry.TransientError):
        return True
    if basics.is_initialized() and basics.process_size() > 1:
        return False
    msg = str(e).lower()
    return any(m in msg for m in _TRANSIENT_DISPATCH_MARKERS)


def _guarded(jitfn, donated: bool = False):
    """Wrap one compiled eager kernel with the fault-tolerance guard:
    chaos injection (``collective_delay``/``collective_fail``) ahead of the
    launch, and the shared retry/backoff policy around transient dispatch
    failures. This is the dispatch-timeout path of the eager layer — the
    reference's answer was "stall, then die"; ours is classify-and-retry.
    CPU backends additionally serialize through :func:`_cpu_serialized`.

    ``donated=True`` marks a kernel whose launch consumes its input
    buffers: a failure raised DURING the launch must not be re-dispatched
    (the rerun would read already-donated arrays). Chaos injections stay
    retriable — they fire before the launch touches its arguments."""
    inner = _cpu_serialized(jitfn)
    retriable = (
        (lambda e: isinstance(e, _retry.TransientError))
        if donated else _transient_dispatch_error
    )

    def _launch(*args):
        if _chaos.enabled():
            _chaos.maybe_delay("collective_delay")

            def attempt():
                if _chaos.enabled():
                    _chaos.inject_failure("collective_fail")
                return inner(*args)

            return _get_dispatch_policy().call(
                attempt, retriable=retriable
            )
        # happy path: one chaos check, a bare launch, no retry machinery —
        # the backoff schedule is only built once a launch actually fails
        try:
            return inner(*args)
        except BaseException as e:
            if donated or not _transient_dispatch_error(e):
                raise
            # hand the policy the failure that already happened as its
            # first attempt: total launches stay within max_attempts and
            # the first re-dispatch waits out base_delay (re-entering a
            # rendezvous abort immediately tends to hit the same window)
            first = [e]

            def rerun():
                if first:
                    raise first.pop()
                return inner(*args)

            return _get_dispatch_policy().call(
                rerun, retriable=_transient_dispatch_error
            )

    def launch(*args):
        out = _launch(*args)
        # flight-ring end marker for the begin _record_eager_op logged
        # (once per correlation key): a rank that reached here made host
        # progress — the hang watchdog's progress signal
        _flight.collective_end()
        return out

    return launch


def _eager_cache_size() -> Optional[int]:
    """``HOROVOD_EAGER_CACHE_SIZE`` (default 128): LRU capacity of each
    compiled-eager-kernel cache. Shape-polymorphic workloads (ragged batch
    tails, growing gather sizes) mint a new (shape, dtype) signature per
    variant; unbounded, the caches held every compiled program forever.
    ``0``/negative/``none`` disables the cap (the old behavior)."""
    v = os.environ.get("HOROVOD_EAGER_CACHE_SIZE", "128").strip().lower()
    if v in ("none", ""):
        return None
    n = int(v)
    return n if n > 0 else None


def _counted_lru_cache(builder):
    """Capped ``functools.lru_cache`` that also counts hits/misses/evictions
    into the metrics registry. Every compiled-eager-kernel lookup goes
    through one of these, so ``eager_compile_cache_{hits,misses,evictions}``
    is the in-tree answer to "is steady-state training replaying cached
    programs or recompiling every step?" (the eager analog of the
    reference's cycle observability). Labeled by kernel kind
    (``_eager_allreduce_fn`` -> ``kind=allreduce``). The underlying cache is
    built lazily so ``cache_clear()`` re-reads ``HOROVOD_EAGER_CACHE_SIZE``."""
    kind = builder.__name__.replace("_eager_", "").replace("_fn", "")
    box = {}

    def _cached():
        if "c" not in box:
            box["c"] = functools.lru_cache(maxsize=_eager_cache_size())(builder)
        return box["c"]

    @functools.wraps(builder)
    def lookup(*key):
        cached = _cached()
        if not _metrics.enabled():
            return cached(*key)
        before = cached.cache_info()
        fn = cached(*key)
        after = cached.cache_info()
        missed = after.misses > before.misses
        name = "eager_compile_cache_misses" if missed \
            else "eager_compile_cache_hits"
        _metrics.counter(
            name, help="eager shard_map program-cache lookups", kind=kind
        ).inc()
        if (
            missed
            and after.maxsize is not None
            and before.currsize == after.maxsize
            and after.currsize == after.maxsize
        ):
            # a miss that did not grow a full cache displaced its LRU entry
            _metrics.counter(
                "eager_compile_cache_evictions",
                help="compiled eager kernels displaced by the LRU cap",
                kind=kind,
            ).inc()
        return fn

    lookup.cache_info = lambda: _cached().cache_info()
    lookup.cache_clear = lambda: box.pop("c", None)
    return lookup


def _record_eager_op(op_name: str, tensors, axis=None) -> None:
    """Count one dispatched eager collective and its payload bytes (the
    per-op traffic accounting ``bench.py`` previously approximated ad
    hoc), and assign the op its fleet correlation key — ``(step, elastic
    generation, per-op seq)`` via
    :func:`horovod_tpu.observability.straggler.collective_begin`, which
    also records per-rank arrival timestamps and applies any
    ``HOROVOD_CHAOS=rank_slow`` charge. The correlation hook runs even
    with metrics disabled: chaos charges and the seq discipline must not
    depend on the metrics switch (ranks disagreeing on seq would
    mis-correlate every later collective). With ``HOROVOD_SANITIZE=1``
    the op's signature (name, axis, per-tensor shape/dtype) is also
    appended to the schedule sanitizer's per-step ring
    (:mod:`horovod_tpu.analysis.sanitizer`) — the cross-rank schedule
    hash rank 0 verifies each step."""
    try:
        world = basics.size()
        prank = basics.process_rank()
        psize = basics.process_size()
    except RuntimeError:  # before init: eager ops will fail later anyway
        world, prank, psize = 1, 0, 1
    key = _straggler.collective_begin(
        op_name, world=world, process_rank=prank, process_size=psize,
    )
    # flight ring: the crash-durable record of this dispatch (begin; the
    # _guarded launch wrapper records the matching end). Also the hook the
    # rank_hang chaos charge fires through.
    _flight.collective_begin(
        op_name, key, world=world, process_rank=prank, process_size=psize,
    )
    _sanitizer.record(op_name, tensors, axis=axis)
    if not _metrics.enabled():
        return
    nbytes = 0
    for t in tensors:
        nbytes += getattr(t, "nbytes", 0) or 0
    _metrics.counter(
        f"{op_name}_count", help="eager collectives dispatched"
    ).inc()
    _metrics.counter(
        f"{op_name}_bytes", help="payload bytes through eager collectives"
    ).inc(nbytes)
    _metrics.counter(
        f"{op_name}_tensors", help="tensors through eager collectives"
    ).inc(len(tensors) if hasattr(tensors, "__len__") else 1)


@_counted_lru_cache
def _eager_allreduce_fn(mesh, axis, stacked, n_tensors):
    in_spec = P(axis) if stacked else P()

    def fn(*tensors):
        outs = []
        for v in tensors:
            s = lax.psum(v, axis)
            outs.append(s)
        return tuple(outs)

    sm = _smap(fn, mesh, (in_spec,) * n_tensors, (P(),) * n_tensors)
    return _guarded(jax.jit(sm))


_donate_fused: Optional[bool] = None


def _donate_fused_enabled() -> bool:
    """``HOROVOD_DONATE_FUSED``: donate the flat fused-buffer inputs of the
    eager fused allreduce / reduce-scatter programs so XLA aliases the
    output into the input's HBM instead of holding both live across the
    collective — on a 64 MB bin that is 64 MB of transient HBM back.
    Default: on for accelerator backends, OFF on CPU — the CPU/test path is
    where ``_guarded`` may legitimately re-dispatch a launch (XLA:CPU
    rendezvous aborts), and a retry must never replay already-donated
    buffers. Donation is safe with the chaos/retry guard because chaos
    failure injection fires *before* the launch consumes its arguments."""
    global _donate_fused
    if _donate_fused is None:
        env = os.environ.get("HOROVOD_DONATE_FUSED")
        if env is not None:
            _donate_fused = env.lower() not in ("0", "false")
        else:
            _donate_fused = jax.default_backend() != "cpu"
    return _donate_fused


def _maybe_donated_jit(sm, n_args: int, donate: bool):
    """jit with all collective inputs donated when enabled; unusable
    donations (shape-changing outputs, e.g. stacked inputs) surface as a
    one-line XLA warning, filtered here so opting in stays quiet."""
    if not donate:
        return jax.jit(sm)
    jitted = jax.jit(sm, donate_argnums=tuple(range(n_args)))

    def first_call_quiet(*args):
        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message=".*donated.*", category=UserWarning
            )
            return jitted(*args)

    return first_call_quiet


_flat_fusion: Optional[bool] = None


def _flat_fusion_enabled() -> bool:
    """``HOROVOD_FUSION_FLAT`` (default on): fuse a grouped bin into one
    flat buffer per dtype (one collective each). Off = one psum per tensor
    inside the single launch, leaving the merge to XLA's all-reduce
    combiner. Measured on the 8-device CPU mesh (161-tensor 5.9 MB bin):
    flat 34.6 ms vs per-tensor 27.2 ms — host memcpy makes pack/unpack a
    net cost THERE; on TPU one DMA-scheduled collective per dtype is the
    fusion the reference's 64 MB buffer exists to get."""
    global _flat_fusion
    if _flat_fusion is None:
        _flat_fusion = os.environ.get(
            "HOROVOD_FUSION_FLAT", "1").lower() not in ("0", "false")
    return _flat_fusion


@_counted_lru_cache
def _eager_fused_allreduce_fn(mesh, axis, stacked, sig):
    """Flat fusion-buffer allreduce: the true analog of the reference's
    ``MemcpyInFusionBuffer`` → one reduction → ``MemcpyOutFusionBuffer``
    (``common/ops/collective_operations.cc``). Every same-dtype member of the
    fused response is flattened and concatenated into ONE buffer, reduced
    with ONE ``psum`` per dtype, and split back — so a 100-tensor bin costs
    #dtypes collectives instead of 100. XLA lowers the concat/split to fused
    HBM copies around the collective.

    ``sig`` is the trace signature: a tuple of per-tensor (shape, dtype-str)
    pairs (the lru key; shapes are per-shard shapes as seen inside
    shard_map). Non-stacked inputs are donated when
    :func:`_donate_fused_enabled` (each output aliases its same-shaped
    input buffer); stacked inputs change shape through the reduce, so
    donation would never alias and is skipped.
    """
    in_spec = P(axis) if stacked else P()
    n_tensors = len(sig)

    def fn(*tensors):
        by_dtype: dict = {}
        for i, t in enumerate(tensors):
            by_dtype.setdefault(t.dtype, []).append(i)
        outs = [None] * len(tensors)
        for idxs in by_dtype.values():
            if len(idxs) == 1:
                i = idxs[0]
                outs[i] = lax.psum(tensors[i], axis)
                continue
            flat = jnp.concatenate([tensors[i].reshape(-1) for i in idxs])
            red = lax.psum(flat, axis)
            off = 0
            for i in idxs:
                sz = tensors[i].size
                outs[i] = red[off:off + sz].reshape(tensors[i].shape)
                off += sz
        return tuple(outs)

    sm = _smap(fn, mesh, (in_spec,) * n_tensors, (P(),) * n_tensors)
    donate = _donate_fused_enabled() and not stacked
    return _guarded(_maybe_donated_jit(sm, n_tensors, donate), donated=donate)


@_counted_lru_cache
def _eager_allgather_fn(mesh, axis, stacked, n_tensors):
    in_spec = P(axis) if stacked else P()

    def fn(*tensors):
        return tuple(
            lax.all_gather(v, axis, axis=0, tiled=True) for v in tensors
        )

    return _guarded(jax.jit(
        _smap(fn, mesh, (in_spec,) * n_tensors, (P(),) * n_tensors)
    ))


@_counted_lru_cache
def _eager_broadcast_fn(mesh, axis, root):
    def fn(v):
        idx = _flat_axis_index(mesh, axis)
        masked = jnp.where(idx == root, v, jnp.zeros_like(v))
        return lax.psum(masked, axis)

    return _guarded(jax.jit(
        _smap(fn, mesh, (P(axis),), P())
    ))


@_counted_lru_cache
def _eager_alltoall_fn(mesh, axis):
    n = _mesh_axis_size(mesh, axis)

    def fn(v):
        # v: [1, rows, ...] -> per-rank [rows, ...]
        v = jnp.squeeze(v, axis=0)
        rows = v.shape[0]
        v = v.reshape((n, rows // n) + v.shape[1:])
        r = lax.all_to_all(v, axis, split_axis=0, concat_axis=0)
        r = r.reshape((rows,) + r.shape[2:])
        return r[None]

    return _guarded(jax.jit(
        _smap(fn, mesh, (P(axis),), P(axis))
    ))


# --------------------------------------------------------------------------
# int8 quantized collectives (Compression.int8 / the PowerSGD int8 fallback)
#
# int8 values must never be summed in int8 — a ring hop would overflow at
# the second addition. The kernels below keep the wire low-bit while the
# arithmetic stays wide: quantize per destination shard → move int8 + bf16
# scales (all_to_all = the scatter half of a ring reduce-scatter) →
# dequantize and ACCUMULATE IN f32 on the owning rank → requantize the
# reduced shard → all-gather int8 + scales → dequantize. The HLO carries
# s8/bf16 collectives, so the compiled program's wire bytes are the real
# ~4x saving, not a simulation.


def _quant_block(compression) -> int:
    from horovod_tpu.compression import INT8_BLOCK

    return int(getattr(compression, "block", INT8_BLOCK))


def _quant_exchange(flat, axis, block, pre=None):
    """The wire half of the quantized reduce-scatter: split this rank's
    ``[Lp]`` vector into N destination-chunk rows, blockwise-quantize
    (shared ``compression._pad_to_block`` layout), and ``all_to_all`` the
    int8 values + bf16 scales. ``pre=(q, scales)`` reuses an already
    computed wire image with the SAME layout (the fused EF path quantizes
    once for both the residual and the wire). Returns ``(qr [N, sp],
    scr [N, sp/block], n, s, sp)``."""
    from horovod_tpu.compression import _pad_to_block, quantize_blockwise

    n = lax.psum(1, axis)  # static axis size
    s = flat.shape[0] // n
    rows = _pad_to_block(flat.reshape(n, s), block)
    sp = rows.shape[1]
    if pre is not None:
        q, scales = pre
    else:
        # sp % block == 0, so flat blocks align to destination-chunk rows;
        # quantize_blockwise itself dispatches to the fused Pallas kernel
        # under HOROVOD_PALLAS
        q, scales = quantize_blockwise(rows.reshape(-1), block)
    qr = lax.all_to_all(
        q.reshape(n, sp), axis, split_axis=0, concat_axis=0)
    scr = lax.all_to_all(
        scales.reshape(n, sp // block), axis, split_axis=0, concat_axis=0)
    return qr, scr, n, s, sp


def quantized_psum_scatter(flat, axis, *, block=None, pre=None):
    """In-jit (bound axis) int8 reduce-scatter of a flat per-rank vector.

    ``flat``: this rank's ``[Lp]`` contribution, ``Lp`` a multiple of the
    axis size N. Each rank's vector is split into N destination chunks,
    each chunk blockwise-quantized (internal zero-pad up to the scale
    block), exchanged as int8 + bf16 scales via ``all_to_all``, and the N
    received chunks are dequantized and summed in f32. Returns this rank's
    f32(-dtype) SUM shard ``[Lp // N]``. ``pre=(q, scales)`` supplies a
    precomputed wire image (see :func:`_quant_exchange`).

    Under ``HOROVOD_PALLAS`` the dequant-accumulate epilogue runs as ONE
    fused VMEM kernel (no ``[N, sp]`` f32 dequant matrix in HBM); the
    ``all_to_all`` signatures are identical either way, so the collective
    schedule fingerprints are invariant."""
    from horovod_tpu.compression import INT8_BLOCK, dequantize_blockwise
    from horovod_tpu.ops import pallas_kernels as _pk

    block = int(block or INT8_BLOCK)
    qr, scr, n, s, sp = _quant_exchange(flat, axis, block, pre=pre)
    if _pk.enabled():
        return _pk.dequant_accumulate(qr, scr, flat.dtype, block)[:s]
    deq = dequantize_blockwise(
        qr.reshape(-1), scr.reshape(-1), flat.dtype, block).reshape(n, sp)
    return deq.sum(axis=0)[:s]


def _quantized_all_gather_fwd(flat, axis, block):
    from horovod_tpu.compression import dequantize_rows, quantize_blockwise

    n = lax.psum(1, axis)  # static axis size
    s = flat.shape[0]
    q, scales = quantize_blockwise(flat, block)       # [sp], [sp/block]
    sp = q.shape[0]
    qg = lax.all_gather(q, axis, axis=0, tiled=True).reshape(n, sp)
    scg = lax.all_gather(
        scales, axis, axis=0, tiled=True).reshape(n, sp // block)
    deq = dequantize_rows(qg, scg, flat.dtype, block)  # [n, sp]
    return deq[:, :s].reshape(-1), None


def _quantized_all_gather_bwd(axis, block, _res, ct):
    # the gradient leg stays EXACT full precision: the transpose of the
    # plain tiled all_gather — only the forward's parameter values ride
    # the int8 wire
    del block
    return (lax.psum_scatter(ct, axis, scatter_dimension=0, tiled=True),)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _quantized_all_gather(flat, axis, block):
    return _quantized_all_gather_fwd(flat, axis, block)[0]


_quantized_all_gather.defvjp(
    _quantized_all_gather_fwd, _quantized_all_gather_bwd)


def quantized_all_gather(flat, axis, *, block=None):
    """In-jit (bound axis) int8 all-gather of a flat per-rank shard — the
    ZeRO-3 parameter gather-on-use wire (``HOROVOD_FSDP_WIRE=int8``).

    This rank's ``[s]`` shard is blockwise-quantized (internal zero-pad
    up to the scale block), the int8 values + bf16 scales ride the tiled
    all-gather, and every rank dequantizes the N received rows back to
    ``[N*s]`` — ~4x less gather wire than fp32, with the fused per-row
    dequant epilogue under ``HOROVOD_PALLAS``
    (:func:`horovod_tpu.ops.pallas_kernels.dequantize_rows`).

    Differentiable by design: the backward is the transpose of the PLAIN
    tiled all-gather — an exact full-precision ``lax.psum_scatter`` of
    the cotangent — so a ZeRO-3 step under this wire trains on
    int8-rounded weights but exact gradients (the trajectory deviation
    is bounded by the forward rounding alone)."""
    from horovod_tpu.compression import INT8_BLOCK

    return _quantized_all_gather(flat, axis, int(block or INT8_BLOCK))


def _quant_allreduce_bound(v, axis, *, op, block):
    """In-jit (bound axis) int8 allreduce: quantized reduce-scatter, f32
    accumulate, requantize the reduced shard, int8 all-gather, dequantize.
    ``op`` Average divides the f32 shard before the requantize so the
    gather leg quantizes at the final magnitude.

    Under ``HOROVOD_PALLAS`` dequantize → accumulate → divide →
    requantize runs as ONE fused kernel between the ``all_to_all`` and
    the ``all_gather`` (the reduced shard never round-trips HBM); the
    collective signatures are unchanged."""
    from horovod_tpu.compression import (
        dequantize_blockwise, quantize_blockwise,
    )
    from horovod_tpu.ops import pallas_kernels as _pk

    n = lax.psum(1, axis)
    shape, size, dtype = v.shape, v.size, v.dtype
    flat = v.reshape(-1)
    pad = (-size) % (n * block)
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
    if _pk.enabled():
        qr, scr, n, _s, _sp = _quant_exchange(flat, axis, block)
        # s == sp here: Lp is a multiple of N*block, so the rows need no pad
        q2, sc2 = _pk.dequant_accumulate_requantize(
            qr, scr, dtype, block, divisor=(n if op == Average else None))
    else:
        shard = quantized_psum_scatter(flat, axis, block=block)  # [Lp//n]
        if op == Average:
            shard = shard / n
        # shard length is a multiple of block (Lp % n*block == 0)
        q2, sc2 = quantize_blockwise(shard, block)
    qg = lax.all_gather(q2, axis, axis=0, tiled=True)
    scg = lax.all_gather(sc2, axis, axis=0, tiled=True)
    out = dequantize_blockwise(qg, scg, dtype, block)
    return out[:size].reshape(shape)


@_counted_lru_cache
def _eager_quant_allreduce_fn(mesh, axis, stacked, shape, dtype_str, block,
                              avg, pallas_key=(False, False)):
    """Compiled eager int8 allreduce (one program per mesh/shape/dtype,
    LRU-capped + hit/miss counted like every eager kernel). Stacked
    ``[N, ...]`` inputs contribute one per-rank row each; replicated inputs
    contribute the same value from every rank. ``pallas_key`` carries the
    resolved ``HOROVOD_PALLAS`` state into the cache key — the traced body
    consults the knob, so flipping it must never replay a stale program."""
    in_spec = P(axis) if stacked else P()

    def fn(v):
        if stacked:
            v = jnp.squeeze(v, axis=0)
        return _quant_allreduce_bound(
            v, axis, op=Average if avg else Sum, block=block)

    return _guarded(jax.jit(_smap(fn, mesh, (in_spec,), P())))


@_counted_lru_cache
def _eager_quant_reducescatter_fn(mesh, axis, stacked, shape, dtype_str,
                                  block, pallas_key=(False, False)):
    """Compiled eager int8 SUM reduce-scatter on a flat packed buffer
    (the ZeRO-1 exchange): input ``[Lp]`` replicated or ``[N, Lp]``
    stacked per-rank rows; output ``[N, Lp // N]`` f32 shards, one row per
    owning rank (sharded ``P(axis)`` like :func:`_eager_reducescatter_fn`).
    ``pallas_key`` keys the compiled program on the resolved
    ``HOROVOD_PALLAS`` state (the traced body consults the knob)."""
    in_spec = P(axis) if stacked else P()

    def fn(v):
        if stacked:
            v = jnp.squeeze(v, axis=0)
        return quantized_psum_scatter(v, axis, block=block)[None]

    sm = _smap(fn, mesh, (in_spec,), P(axis))
    # same donation discipline as _eager_reducescatter_fn: the flat packed
    # buffer is consumed by the launch, releasing its HBM during the
    # exchange (never aliasable — the output is the 1/N f32 shard)
    donate = _donate_fused_enabled()
    return _guarded(_maybe_donated_jit(sm, 1, donate), donated=donate)


def quantized_reducescatter(tensor, *, axis=None, block=None):
    """SUM reduce-scatter with the int8 wire on a flat packed buffer.

    In-jit (bound axis): per-rank ``[Lp]`` → this rank's f32 shard
    ``[Lp//N]``. Eager: ``[Lp]`` replicated or ``[N, Lp]`` stacked →
    ``[N, Lp//N]`` stacked shards; the input buffer is donated to the
    launch when ``HOROVOD_DONATE_FUSED`` is on (accelerator default) —
    treat it as consumed. ``Lp`` must be a multiple of the axis size (the
    ZeRO-1 flat packing guarantees it)."""
    from horovod_tpu.compression import INT8_BLOCK

    block = int(block or INT8_BLOCK)
    ax = _axis(axis)
    if _is_tracer(tensor):
        if not _axis_bound(ax):
            raise ValueError(
                "quantized_reducescatter is rank-dependent and requires a "
                "bound mesh axis; call it inside shard_map over the data "
                "axis."
            )
        return quantized_psum_scatter(tensor, ax, block=block)
    from horovod_tpu.ops import pallas_kernels as _pk

    tensor = _as_array(tensor)
    stacked = _is_stacked(tensor, ax)
    fn = _eager_quant_reducescatter_fn(
        basics.mesh(), ax, stacked,
        tuple(tensor.shape), str(tensor.dtype), block, _pk.cache_key())
    _record_eager_op("reducescatter", (tensor,), axis=ax)
    return fn(tensor)


def _quantizes_dtype(compression, tensor) -> bool:
    """Does `compression` actually quantize this tensor? Integer and
    already-16-bit leaves pass through the regular path untouched, as do
    leaves below the compressor's ``min_quant_elems`` floor — the ring
    pads every rank-pair message to a whole scale block, so quantizing a
    small bias would move MORE wire than its fp32 psum."""
    from horovod_tpu.compression import _quantizable

    dt = getattr(tensor, "dtype", None)
    if dt is None:
        t = np.asarray(tensor)
        dt, size = t.dtype, t.size
    else:
        size = int(np.prod(getattr(tensor, "shape", ()), dtype=np.int64))
    return _quantizable(dt) and \
        size >= int(getattr(compression, "min_quant_elems", 0))


def _roundtrip_compressed(tensor, compression):
    c, ctx = compression.compress(tensor)
    return compression.decompress(c, ctx)


def _quantized_allreduce(tensor, op, ax, compression, *, name=None,
                         prescale_factor=1.0, postscale_factor=1.0):
    """allreduce() body for quantized (int8-family) compression. The bound
    single-axis path runs the real int8 ring; a bound two-axis hierarchy
    compresses ONLY the cross (DCN) hop while the local (ICI) legs stay
    full-width; everything else models the wire as a quantize roundtrip of
    the contribution (exact error-feedback semantics either way)."""
    if op == Adasum:
        raise ValueError("quantized compression does not support op=Adasum")
    block = _quant_block(compression)
    if prescale_factor != 1.0:
        tensor = tensor * prescale_factor
    if _is_tracer(tensor):
        if _axis_bound(ax):
            if isinstance(ax, tuple) and len(ax) == 2 and _hier_enabled():
                from horovod_tpu.ops import hierarchical

                out = hierarchical.hier_allreduce(
                    tensor, cross_axis=ax[0], local_axis=ax[1],
                    compression=compression)
                if op == Average:
                    out = _div(out, lax.psum(1, ax[0]) * lax.psum(1, ax[1]))
            elif isinstance(ax, tuple):
                # flat multi-axis: model the wire as the roundtrip of the
                # contribution; the reduction itself stays a plain psum
                out = lax.psum(_roundtrip_compressed(tensor, compression), ax)
                if op == Average:
                    out = _div(out, lax.psum(1, ax))
            else:
                out = _quant_allreduce_bound(tensor, ax, op=op, block=block)
        else:
            # global value under jit: replicated semantics + wire roundtrip
            rt = _roundtrip_compressed(tensor, compression)
            out = rt * _axis_size(ax) if op == Sum else rt
    elif _hostlocal_mode(tensor):
        from horovod_tpu.ops import hostlocal

        rt = _roundtrip_compressed(_as_array(tensor), compression)
        _record_eager_op("allreduce", (rt,), axis=ax)
        with _trace.span("eager", f"allreduce:{name or ''}",
                         **_straggler.span_args()):
            out = hostlocal.allreduce(rt, op, ax)
    elif isinstance(ax, tuple):
        # eager multi-axis: roundtrip + the regular eager dispatch
        out = allreduce(
            _roundtrip_compressed(_as_array(tensor), compression), op, axis=ax)
    else:
        from horovod_tpu.ops import pallas_kernels as _pk

        tensor = _as_array(tensor)
        stacked = _is_stacked(tensor, ax)
        fn = _eager_quant_allreduce_fn(
            basics.mesh(), ax, stacked, tuple(tensor.shape),
            str(tensor.dtype), block, op == Average, _pk.cache_key())
        _record_eager_op("allreduce", (tensor,), axis=ax)
        with _trace.span("eager", f"allreduce:{name or ''}",
                         **_straggler.span_args()):
            out = fn(tensor)
    if postscale_factor != 1.0:
        out = out * postscale_factor
    return out


@_counted_lru_cache
def _eager_reducescatter_fn(mesh, axis, stacked):
    in_spec = P(axis) if stacked else P()

    def fn(v):
        if stacked:
            v = jnp.squeeze(v, axis=0)
        r = lax.psum_scatter(v, axis, scatter_dimension=0, tiled=True)
        return r[None]

    sm = _smap(fn, mesh, (in_spec,), P(axis))
    # donation frees the (padded) input buffer during the scatter — never
    # aliasable (the output is the 1/N shard) but the early release is the
    # point on large flat gradient buffers
    donate = _donate_fused_enabled()
    return _guarded(_maybe_donated_jit(sm, 1, donate), donated=donate)


def clear_outstanding_names() -> None:
    """Forget every outstanding async-collective name: an op left in
    flight when a run died must not poison the next ``hvd.init`` on this
    live process with DUPLICATE_NAME. ``basics.shutdown`` calls this."""
    with _outstanding_lock:
        _outstanding_names.clear()


def clear_eager_caches() -> None:
    """Drop every compiled-eager-kernel cache and the outstanding-name set.

    The caches are keyed by mesh; ``basics.init`` calls this when a
    live-process re-init builds a *different* mesh (the elastic resize):
    the old mesh's entries can never hit again, but they pin compiled
    programs (and through them device buffers) for devices the new mesh
    may no longer own. A re-init on an equal mesh keeps the caches — they
    are warm hits, and recompiling every eager collective per init cycle
    would be pure waste."""
    for fn in (
        _eager_allreduce_fn,
        _eager_fused_allreduce_fn,
        _eager_allgather_fn,
        _eager_broadcast_fn,
        _eager_alltoall_fn,
        _eager_reducescatter_fn,
        _eager_quant_allreduce_fn,
        _eager_quant_reducescatter_fn,
    ):
        fn.cache_clear()
    for mod_name, names in (
        ("horovod_tpu.ops.adasum",
         ("_eager_adasum_fn", "_eager_grouped_adasum_fn")),
        ("horovod_tpu.ops.hierarchical",
         ("_eager_hier_allreduce_fn", "_eager_hier_allgather_fn")),
    ):
        import sys as _sys

        mod = _sys.modules.get(mod_name)
        if mod is None:
            continue  # never imported: nothing cached
        for n in names:
            getattr(mod, n).cache_clear()
    with _outstanding_lock:
        _outstanding_names.clear()


# --------------------------------------------------------------------------
# allreduce


def allreduce(tensor, op: ReduceOp = Average, *, axis=None, name: Optional[str] = None,
              compression=None, prescale_factor: float = 1.0,
              postscale_factor: float = 1.0):
    """Sum/average `tensor` across ranks.

    In-jit: `tensor` is a per-shard value; lowers to ``lax.psum``/``pmean``
    over ``axis`` (default: the data axis). Eager: `tensor` is either stacked
    ``[size, ...]`` (per-rank values) or replicated; returns the reduced tensor
    replicated across the mesh. Mirrors reference
    ``tensorflow/__init__.py:43-122`` (Average divides by size after summing).
    """
    ax = _axis(axis)
    if compression is not None and getattr(compression, "factorized", False):
        raise ValueError(
            "factorized compression (PowerSGD) is stateful (warm-started Q "
            "+ error feedback) and cannot ride a stateless allreduce; use "
            "DistributedOptimizer(compression=Compression.powersgd(r), "
            "error_feedback=True)"
        )
    if (
        compression is not None
        and getattr(compression, "quantized", False)
        and _quantizes_dtype(compression, tensor)
    ):
        return _quantized_allreduce(
            tensor, op, ax, compression, name=name,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
    if compression is not None:
        tensor, ctx = compression.compress(tensor)
    if prescale_factor != 1.0:
        tensor = tensor * prescale_factor
    if op == Adasum:
        from horovod_tpu.ops import adasum as _adasum

        out = _adasum.adasum_allreduce(tensor, axis=ax, name=name)
    elif _is_tracer(tensor):
        if _axis_bound(ax):
            if isinstance(ax, tuple) and len(ax) == 2 and _hier_enabled():
                from horovod_tpu.ops import hierarchical

                # reference HOROVOD_HIERARCHICAL_ALLREDUCE: explicit
                # local RS -> cross AR -> local AG decomposition
                out = hierarchical.hier_allreduce(
                    tensor, cross_axis=ax[0], local_axis=ax[1])
            else:
                out = lax.psum(tensor, ax)
            if op == Average:
                out = _div(out, lax.psum(1, ax))
        else:
            # global value under jit: XLA's sharding propagation already did
            # the cross-chip reduction; replicated semantics apply.
            out = tensor * _axis_size(ax) if op == Sum else tensor
    elif _hostlocal_mode(tensor):
        from horovod_tpu.ops import hostlocal

        _record_eager_op("allreduce", (_as_array(tensor),), axis=ax)
        with _trace.span("eager", f"allreduce:{name or ''}",
                         **_straggler.span_args()):
            out = hostlocal.allreduce(tensor, op, ax)
    elif isinstance(ax, tuple) and len(ax) == 2 and _hier_enabled():
        from horovod_tpu.ops import hierarchical

        out = hierarchical.hierarchical_allreduce(
            tensor, op, cross_axis=ax[0], local_axis=ax[1])
    else:
        tensor = _as_array(tensor)
        stacked = _is_stacked(tensor, ax)
        n = _axis_size(ax)
        fn = _eager_allreduce_fn(basics.mesh(), ax, stacked, 1)
        _record_eager_op("allreduce", (tensor,), axis=ax)
        with _trace.span("eager", f"allreduce:{name or ''}",
                         **_straggler.span_args()):
            (out,) = fn(tensor)
        if stacked:
            out = jnp.squeeze(out, axis=0)
        if op == Average:
            out = _div(out, n)
    if postscale_factor != 1.0:
        out = out * postscale_factor
    if compression is not None:
        out = compression.decompress(out, ctx)
    return out


def allreduce_(tensor, op: ReduceOp = Average, *, axis=None, name=None):
    """In-place spelling for torch parity (reference
    ``torch/mpi_ops.py:182-240``); JAX arrays are immutable so this is
    ``allreduce``."""
    return allreduce(tensor, op, axis=axis, name=name)


def _core_enqueue(name, tensor, request_type, **kw):
    """Route a named async op through the native core when one is attached
    (init(native_core=True)); returns None when the direct path should run."""
    core = basics._state.core
    if core is None or name is None:
        return None
    return core.enqueue(name, _as_array(tensor), request_type, **kw)


def allreduce_async(tensor, op: ReduceOp = Average, *, axis=None, name=None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0):
    """Async allreduce returning a handle
    (reference ``torch/mpi_ops.py:94-129``).

    With the native core attached and a tensor `name` given, the op goes
    through the background negotiation cycle (fusion + response cache +
    stall detection); otherwise it dispatches directly (XLA's async runtime
    is the handle)."""
    from horovod_tpu.core import REQUEST_ADASUM, REQUEST_ALLREDUCE

    h = _core_enqueue(
        name, tensor, REQUEST_ADASUM if op == Adasum else REQUEST_ALLREDUCE,
        op=op, axis=axis, prescale=prescale_factor, postscale=postscale_factor,
    )
    if h is not None:
        return h
    return _async(
        lambda: allreduce(tensor, op, axis=axis,
                          prescale_factor=prescale_factor,
                          postscale_factor=postscale_factor),
        name,
    )


allreduce_async_ = allreduce_async


def grouped_allreduce(tensors: Sequence, op: ReduceOp = Average, *, axis=None,
                      name=None):
    """Fused allreduce of a list of tensors in one collective.

    This is the eager-layer analog of the reference's tensor fusion
    (``FuseResponses`` bin-packing, ``controller.cc:640-761`` +
    ``MemcpyInFusionBuffer``, ``collective_operations.cc``): tensors are
    flattened into one buffer, reduced with a single ``psum``, and split back.
    XLA performs the pack/unpack as fused copies in HBM.
    """
    ax = _axis(axis)
    if op == Adasum:
        # fused Adasum: one flat-concat buffer, per-tensor dot/norm scalars
        # via segment reductions inside the combine, ONE butterfly for the
        # whole group -> O(log n) collectives per step (reference
        # adasum.h:194-398 FusedPairwiseReduceWithComm over fusion-buffer
        # offsets).
        from horovod_tpu.ops.adasum import grouped_adasum_allreduce

        return grouped_adasum_allreduce(tensors, axis=ax)
    if not any(_is_tracer(t) for t in tensors) and any(
        _hostlocal_mode(t) for t in tensors
    ):
        from horovod_tpu.ops import hostlocal

        # mixed host-local/global lists dispatch per tensor, like allreduce
        # (global tensors record inside their own allreduce() call)
        _record_eager_op(
            "allreduce",
            [_as_array(t) for t in tensors if _hostlocal_mode(t)],
            axis=ax,
        )
        return [
            hostlocal.allreduce(_as_array(t), op, ax)
            if _hostlocal_mode(t)
            else allreduce(t, op, axis=ax)
            for t in tensors
        ]
    tensors = [_as_array(t) for t in tensors]
    if any(_is_tracer(t) for t in tensors):
        if not _axis_bound(ax):
            n = _axis_size(ax)
            return [t * n if op == Sum else t for t in tensors]
        outs = [lax.psum(t, ax) for t in tensors]
        if op == Average:
            n = lax.psum(1, ax)
            outs = [_div(o, n) for o in outs]
        return outs

    n = _axis_size(ax)
    stacked = [_is_stacked(t, ax) for t in tensors]
    if all(stacked) or not any(stacked):
        st = bool(stacked and stacked[0])
        if len(tensors) > 1 and _flat_fusion_enabled():
            # flat fusion-buffer path: one psum per dtype for the whole bin
            sig = tuple((tuple(t.shape), str(t.dtype)) for t in tensors)
            fn = _eager_fused_allreduce_fn(basics.mesh(), ax, st, sig)
        else:
            fn = _eager_allreduce_fn(basics.mesh(), ax, st, len(tensors))
        _record_eager_op("allreduce", tensors, axis=ax)
        with _trace.span("eager", f"grouped_allreduce:{name or ''}",
                         **_straggler.span_args()):
            outs = list(fn(*tensors))
        if st:
            outs = [jnp.squeeze(o, axis=0) for o in outs]
    else:
        outs = [allreduce(t, Sum, axis=ax) for t in tensors]
    if op == Average:
        outs = [_div(o, n) for o in outs]
    return outs


def grouped_allreduce_async(tensors, op: ReduceOp = Average, *, axis=None,
                            name=None):
    return _async(lambda: grouped_allreduce(tensors, op, axis=axis), name)


# --------------------------------------------------------------------------
# allgather


def allgather(tensor, *, axis=None, name=None):
    """Concatenate per-rank tensors along dim 0 (reference
    ``MPIAllgather``/``NCCL`` path, ``mpi_operations.cc:83+``;
    ``tensorflow/mpi_ops.py:110-143``). All ranks must agree on trailing dims;
    equal dim-0 is required in the XLA (static-shape) path — ragged gather is
    available eagerly via :func:`allgather_object`."""
    ax = _axis(axis)
    if _is_tracer(tensor):
        if not _axis_bound(ax):
            # global value: replicated semantics (every rank contributed the
            # same tensor) -> tile along dim 0.
            return jnp.concatenate([tensor] * _axis_size(ax), axis=0)
        if isinstance(ax, tuple) and len(ax) == 2 and _hier_allgather_enabled():
            from horovod_tpu.ops import hierarchical

            # reference HOROVOD_HIERARCHICAL_ALLGATHER: intra-host gather
            # (ICI) then inter-host (DCN); rank order preserved
            return hierarchical.hier_allgather(
                tensor, cross_axis=ax[0], local_axis=ax[1])
        return lax.all_gather(tensor, ax, axis=0, tiled=True)
    if _hostlocal_mode(tensor):
        from horovod_tpu.ops import hostlocal

        _record_eager_op("allgather", (_as_array(tensor),), axis=ax)
        return hostlocal.allgather(tensor, ax)
    if isinstance(ax, tuple) and len(ax) == 2 and _hier_allgather_enabled():
        from horovod_tpu.ops import hierarchical

        return hierarchical.hierarchical_allgather(
            tensor, cross_axis=ax[0], local_axis=ax[1])
    tensor = _as_array(tensor)
    stacked = _is_stacked(tensor, ax)
    fn = _eager_allgather_fn(basics.mesh(), ax, stacked, 1)
    _record_eager_op("allgather", (tensor,), axis=ax)
    (out,) = fn(tensor)
    if stacked:
        # [size, rows, ...] -> [size*rows, ...]
        out = out.reshape((out.shape[0] * out.shape[1],) + out.shape[2:])
    return out


def grouped_allgather(tensors: Sequence, *, axis=None, name=None):
    """Fused allgather of a tensor list in one XLA launch (the reference
    fuses allgather responses too, ``controller.cc:700-755``; here the
    grouped program holds one ``all_gather`` per tensor — mixed dtypes
    welcome — and XLA schedules them together)."""
    ax = _axis(axis)
    tensors = list(tensors)
    if not tensors:
        return []
    if any(_is_tracer(t) for t in tensors) or any(
        _hostlocal_mode(t) for t in tensors
    ):
        # in-jit and multi-process host paths dispatch per tensor (the
        # hostlocal exchange stages host-side regardless)
        return [allgather(t, axis=ax, name=name) for t in tensors]
    tensors = [_as_array(t) for t in tensors]
    stacked = [_is_stacked(t, ax) for t in tensors]
    if any(stacked) != all(stacked):
        return [allgather(t, axis=ax) for t in tensors]
    st = bool(stacked and stacked[0])
    fn = _eager_allgather_fn(basics.mesh(), ax, st, len(tensors))
    _record_eager_op("allgather", tensors, axis=ax)
    outs = list(fn(*tensors))
    if st:
        outs = [
            o.reshape((o.shape[0] * o.shape[1],) + o.shape[2:]) for o in outs
        ]
    return outs


def allgather_async(tensor, *, axis=None, name=None):
    from horovod_tpu.core import REQUEST_ALLGATHER

    h = _core_enqueue(name, tensor, REQUEST_ALLGATHER, axis=axis)
    if h is not None:
        return h
    return _async(lambda: allgather(tensor, axis=axis), name)


def allgather_object(obj, *, name=None):
    """Gather arbitrary picklable objects from every rank (reference uses
    cloudpickle + allgather of byte tensors, ``torch/__init__.py:609-648``
    pattern). Single-controller: every rank runs this same program, so the
    result is simply ``[obj] * size``; multi-process gathers over the
    controller."""
    basics._require_init()
    if basics.process_size() == 1:
        return [pickle.loads(pickle.dumps(obj))] * basics.size()
    from horovod_tpu.ops import hostlocal

    return hostlocal.allgather_object(obj, basics.data_axis())


# --------------------------------------------------------------------------
# broadcast


def broadcast(tensor, root_rank: int = 0, *, axis=None, name=None):
    """Broadcast root's value to all ranks (reference
    ``NCCLBroadcast``, ``nccl_operations.cc:366-396``;
    ``tensorflow/mpi_ops.py:145-174``)."""
    ax = _axis(axis)
    if not 0 <= root_rank < _axis_size(ax):
        # reference validates root across ranks and returns an ERROR response
        # (controller.cc:378-611)
        raise ValueError(
            f"broadcast root_rank {root_rank} out of range [0, {_axis_size(ax)})"
        )
    if _is_tracer(tensor):
        if not _axis_bound(ax):
            return tensor  # global value: all ranks already hold root's value
        return _inner_broadcast(tensor, root_rank, ax)
    if _hostlocal_mode(tensor):
        # multi-process: root_rank is a *process* index (the Horovod rank)
        from horovod_tpu.ops import hostlocal

        _record_eager_op("broadcast", (_as_array(tensor),), axis=ax)
        return hostlocal.broadcast(tensor, root_rank, ax)
    tensor = _as_array(tensor)
    if not _is_stacked(tensor, ax):
        # replicated: every rank already holds root's value
        return tensor
    was_bool = tensor.dtype == jnp.bool_
    if was_bool:
        tensor = tensor.astype(jnp.int8)
    fn = _eager_broadcast_fn(basics.mesh(), ax, int(root_rank))
    _record_eager_op("broadcast", (tensor,), axis=ax)
    out = jnp.squeeze(fn(tensor), axis=0)
    if was_bool:
        out = out.astype(jnp.bool_)
    return out


def _inner_broadcast(v, root, ax):
    idx = _flat_axis_index(basics.mesh(), ax)
    was_bool = v.dtype == jnp.bool_
    if was_bool:
        v = v.astype(jnp.int8)
    out = lax.psum(jnp.where(idx == root, v, jnp.zeros_like(v)), ax)
    return out.astype(jnp.bool_) if was_bool else out


def broadcast_(tensor, root_rank: int = 0, *, axis=None, name=None):
    return broadcast(tensor, root_rank, axis=axis, name=name)


def broadcast_async(tensor, root_rank: int = 0, *, axis=None, name=None):
    from horovod_tpu.core import REQUEST_BROADCAST

    h = _core_enqueue(
        name, tensor, REQUEST_BROADCAST, axis=axis, root_rank=root_rank
    )
    if h is not None:
        return h
    return _async(lambda: broadcast(tensor, root_rank, axis=axis), name)


broadcast_async_ = broadcast_async


def broadcast_object(obj, root_rank: int = 0, *, name=None):
    """Broadcast a picklable object (reference ``torch/__init__.py:609-648``)."""
    basics._require_init()
    if basics.process_size() == 1:
        return pickle.loads(pickle.dumps(obj))
    from horovod_tpu.ops import hostlocal

    return hostlocal.broadcast_object(obj, root_rank, basics.data_axis())


# --------------------------------------------------------------------------
# TPU-native extensions (beyond the 0.19.2 surface; used by
# horovod_tpu.parallel for sequence/expert parallelism)


def alltoall(tensor, *, axis=None, name=None):
    """All-to-all: rank i sends chunk j of its tensor to rank j. Not in the
    0.19.2 reference (added upstream in 0.20); first-class here because
    sequence/expert parallelism needs it. dim0 must be divisible by size."""
    ax = _axis(axis)
    if _is_tracer(tensor):
        if not _axis_bound(ax):
            raise ValueError(
                "alltoall is rank-dependent and requires a bound mesh axis; "
                "call it inside shard_map over the data axis."
            )
        k = tensor.shape[0]
        n = _axis_size(ax)
        g = tensor.reshape((n, k // n) + tensor.shape[1:])
        r = lax.all_to_all(g, ax, split_axis=0, concat_axis=0)
        return r.reshape((k,) + r.shape[2:])
    if _hostlocal_mode(tensor):
        from horovod_tpu.ops import hostlocal

        _record_eager_op("alltoall", (_as_array(tensor),), axis=ax)
        return hostlocal.alltoall(tensor, ax)
    tensor = _as_array(tensor)
    if not _is_stacked(tensor, ax):
        raise ValueError("eager alltoall requires a stacked [size, ...] array")
    fn = _eager_alltoall_fn(basics.mesh(), ax)
    _record_eager_op("alltoall", (tensor,), axis=ax)
    return fn(tensor)


def alltoall_async(tensor, *, axis=None, name=None):
    from horovod_tpu.core import REQUEST_ALLTOALL

    h = _core_enqueue(name, tensor, REQUEST_ALLTOALL, axis=axis)
    if h is not None:
        return h
    return _async(lambda: alltoall(tensor, axis=axis), name)


def handle_average_backwards_compatibility(op, average):
    """Resolve the deprecated ``average=`` kwarg against ``op=`` (reference
    ``horovod/common/util.py`` ``handle_average_backwards_compatibility``):
    exactly one may be given; ``average`` defaults to True -> Average."""
    if op is not None:
        if average is not None:
            raise ValueError(
                "The op parameter supersedes average; provide only one."
            )
        return op
    return Average if (average is None or average) else Sum


def reducescatter_async(tensor, op: ReduceOp = Average, *, axis=None,
                        name=None):
    """Async reduce-scatter returning a handle; with the native core
    attached and a `name`, rides the negotiation cycle as
    REQUEST_REDUCESCATTER (the dispatch in ``core.py`` was previously
    reachable only in principle)."""
    from horovod_tpu.core import REQUEST_REDUCESCATTER

    _check_rs_op(op)

    h = _core_enqueue(name, tensor, REQUEST_REDUCESCATTER, axis=axis, op=op)
    if h is not None:
        return h
    return _async(lambda: reducescatter(tensor, op, axis=axis), name)


def _check_rs_op(op):
    if op not in (Average, Sum):
        raise ValueError(
            f"reducescatter supports Average/Sum, got {op!r} (Adasum's "
            "pairwise projections have no scatter formulation)"
        )


def _pad_rows(tensor, n: int, dim: int = 0):
    """Zero-pad `dim` up to the next multiple of `n` (the reduce-scatter
    padding path: SPMD shapes are static, so Horovod's "first ranks get one
    extra row" uneven split cannot be expressed — the XLA-native spelling
    pads with zero rows that land in the tail ranks' shards)."""
    rows = tensor.shape[dim]
    pad = (-rows) % n
    if not pad:
        return tensor
    widths = [(0, 0)] * tensor.ndim
    widths[dim] = (0, pad)
    return jnp.pad(tensor, widths)


def reducescatter(tensor, op: ReduceOp = Average, *, axis=None, name=None):
    """Reduce-scatter along dim 0 (upstream 0.21 feature; here it is also the
    building block of hierarchical allreduce, reference
    ``nccl_operations.cc:162-354``, and of the ZeRO-1 sharded optimizer).

    On the single-controller paths (in-jit and eager) a leading dim not
    divisible by the axis size is zero-padded up to the next multiple
    before the scatter (each rank then holds ``ceil(rows/N)`` rows; the
    pad rows — all zeros — land in the tail ranks' shards). The
    multi-process host-local path still requires dim 0 divisible by the
    process count (its shard exchange is row-exact across hosts). On the
    eager path the (padded) input buffer is donated to the launch when
    ``HOROVOD_DONATE_FUSED`` is on (accelerator default) — treat the input
    as consumed, as with every Horovod collective."""
    _check_rs_op(op)
    ax = _axis(axis)
    n = _axis_size(ax)
    if _is_tracer(tensor):
        if not _axis_bound(ax):
            raise ValueError(
                "reducescatter is rank-dependent and requires a bound mesh "
                "axis; call it inside shard_map over the data axis."
            )
        tensor = _pad_rows(tensor, n)
        out = lax.psum_scatter(tensor, ax, scatter_dimension=0, tiled=True)
        return _div(out, n) if op == Average else out
    if _hostlocal_mode(tensor):
        from horovod_tpu.ops import hostlocal

        _record_eager_op("reducescatter", (_as_array(tensor),), axis=ax)
        return hostlocal.reducescatter(tensor, op, ax)
    tensor = _as_array(tensor)
    stacked = _is_stacked(tensor, ax)
    # stacked [size, rows, ...]: the per-rank tensor's dim 0 is dim 1 here
    tensor = _pad_rows(tensor, n, dim=1 if stacked else 0)
    fn = _eager_reducescatter_fn(basics.mesh(), ax, stacked)
    _record_eager_op("reducescatter", (tensor,), axis=ax)
    out = fn(tensor)
    return _div(out, n) if op == Average else out
