"""Two-level (hierarchical) collectives over a ``cross`` × ``local`` mesh.

The reference's NCCL-hierarchical allreduce splits the job along the node
boundary: NCCL reduce-scatter inside the node, MPI allreduce across nodes on
the shrunken shard, NCCL allgather back inside the node
(``common/ops/nccl_operations.cc:162-354``); a matching toggle pair exists for
allgather (``HOROVOD_HIERARCHICAL_ALLREDUCE`` / ``_ALLGATHER``,
``common/operations.cc``). On TPU the axis *placement* already encodes the
hierarchy — an outer ``cross`` axis rides DCN, the inner ``local`` axis rides
ICI — and XLA lowers a flat ``psum`` over both axes however it likes. This
module makes the two-level decomposition explicit and testable:

- in-jit building blocks (:func:`hier_allreduce`, :func:`hier_allgather`)
  that decompose exactly as the reference does: local reduce-scatter →
  cross allreduce on the 1/L-sized shard → local allgather;
- an eager entry point (:func:`hierarchical_allreduce`) compiled per
  mesh/shape, mirroring :mod:`horovod_tpu.ops.collective`'s eager kernels;
- an opt-in strategy toggle (:func:`set_hierarchical`, env
  ``HOROVOD_HIERARCHICAL_ALLREDUCE``) that :func:`collective.allreduce`
  consults when given a two-axis tuple — the knob an autotuner can drive the
  same way the reference's parameter manager drives its hierarchical flags
  (``common/parameter_manager.cc:44-81``).

Equivalence with the flat path is asserted in ``tests/test_hierarchical.py``
and exercised under multi-chip shardings in ``__graft_entry__.py``.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu import basics

#: canonical axis names for a host-hierarchy mesh: ``cross`` (inter-host,
#: DCN) is the OUTER mesh dim so hosts own contiguous device blocks and the
#: inner ``local`` axis stays on intra-host ICI.
CROSS_AXIS = "cross"
LOCAL_AXIS = "local"

_forced: Optional[bool] = None
_forced_allgather: Optional[bool] = None


def set_hierarchical(on: Optional[bool]) -> None:
    """Force the hierarchical allreduce strategy on/off (``None`` = env)."""
    global _forced
    _forced = on


def set_hierarchical_allgather(on: Optional[bool]) -> None:
    """Force the hierarchical allgather strategy on/off (``None`` = env)."""
    global _forced_allgather
    _forced_allgather = on


def _env_on(var: str) -> bool:
    return os.environ.get(var, "0").lower() in ("1", "true", "yes", "on")


def enabled() -> bool:
    """Whether two-axis allreduces decompose hierarchically.

    Explicit :func:`set_hierarchical` wins; otherwise the reference-named env
    var ``HOROVOD_HIERARCHICAL_ALLREDUCE`` (default off → flat ``psum`` over
    both axes, which XLA lowers as it sees fit).

    .. note:: consulted at TRACE time. A function already jitted keeps the
       strategy it was traced with (``jax.jit`` caches are not keyed on this
       toggle) — flip the toggle before tracing, or re-jit after flipping.
       The eager paths (:func:`hierarchical_allreduce`,
       ``collective.allreduce`` on concrete arrays, and the native core's
       launches) re-check it on every call, which is how the autotuned
       broadcast lands mid-run."""
    if _forced is not None:
        return _forced
    return _env_on("HOROVOD_HIERARCHICAL_ALLREDUCE")


def allgather_enabled() -> bool:
    """Two-axis allgather strategy toggle (reference
    ``HOROVOD_HIERARCHICAL_ALLGATHER``, ``common/operations.cc``)."""
    if _forced_allgather is not None:
        return _forced_allgather
    return _env_on("HOROVOD_HIERARCHICAL_ALLGATHER")


# --------------------------------------------------------------------------
# in-jit building blocks (call inside shard_map over both axes)


def hier_allreduce(v, *, cross_axis: str = CROSS_AXIS,
                   local_axis: str = LOCAL_AXIS, compression=None):
    """Two-level sum-allreduce: local reduce-scatter → cross allreduce →
    local allgather. Must run inside a shard_map/pmap binding both axes.

    The cross-host hop moves ``size/L`` elements per device instead of
    ``size`` — the reference's entire rationale for the NCCL+MPI split
    (``nccl_operations.cc:162-186``) — and every device ends with the full
    reduction, bit-identical in structure to the flat ``psum``.

    ``compression`` compresses ONLY the cross hop — the DCN leg, where
    bandwidth is 1-2 orders below ICI — while the local reduce-scatter and
    all-gather stay full-width. A quantized compressor
    (``Compression.int8``) runs the real int8 ring over ``cross_axis``
    (int8 + bf16 scales on DCN, f32 accumulation); an elementwise one
    (``Compression.fp16``) casts the 1/L shard for the cross ``psum``.
    """
    L = lax.psum(1, local_axis)  # static: axis size
    shape, size = v.shape, v.size
    flat = v.reshape(-1)
    pad = (-size) % L
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    piece = lax.psum_scatter(flat, local_axis, scatter_dimension=0, tiled=True)
    if compression is None:
        piece = lax.psum(piece, cross_axis)
    elif getattr(compression, "quantized", False):
        from horovod_tpu.ops.collective import (
            Sum, _quant_allreduce_bound, _quant_block,
        )

        piece = _quant_allreduce_bound(
            piece, cross_axis, op=Sum, block=_quant_block(compression))
    else:
        c, ctx = compression.compress(piece)
        piece = compression.decompress(lax.psum(c, cross_axis), ctx)
    out = lax.all_gather(piece, local_axis, axis=0, tiled=True)
    if pad:
        out = out[:size]
    return out.reshape(shape)


def hier_allgather(v, *, cross_axis: str = CROSS_AXIS,
                   local_axis: str = LOCAL_AXIS):
    """Two-level allgather along dim 0: gather inside the host (ICI), then
    across hosts (DCN). Row-major mesh order (global rank = cross·L + local)
    makes the result ordering identical to the flat gather over
    ``(cross, local)`` — asserted in tests. Reference toggle:
    ``HOROVOD_HIERARCHICAL_ALLGATHER``."""
    g = lax.all_gather(v, local_axis, axis=0, tiled=True)
    return lax.all_gather(g, cross_axis, axis=0, tiled=True)


def _stacked_pair(tensor, cross_axis: str, local_axis: str) -> bool:
    """Strict per-rank-stacked detection for the two-level eager path: the
    leading dim must be sharded over BOTH axes (``P((cross, local), ...)``)
    or neither. A half-sharded leading dim (e.g. ``P(('local',))`` replicated
    over cross) would silently reinterpret rows as per-global-rank
    contributions if treated as stacked — reject it instead."""
    from horovod_tpu.ops.collective import _is_stacked

    c = _is_stacked(tensor, cross_axis)
    l = _is_stacked(tensor, local_axis)
    if c != l:
        raise ValueError(
            "hierarchical collective: leading dim is sharded over only one "
            f"of ({cross_axis!r}, {local_axis!r}); stack per-rank values "
            f"over BOTH (PartitionSpec(({cross_axis!r}, {local_axis!r}), "
            "...)) or pass a replicated array"
        )
    return c


# --------------------------------------------------------------------------
# eager path (compiled per mesh/shape, mirroring collective.py's kernels)


@functools.lru_cache(maxsize=None)
def _eager_hier_allreduce_fn(mesh, cross_axis, local_axis, stacked):
    from horovod_tpu.ops.collective import _guarded, _smap

    in_spec = P((cross_axis, local_axis)) if stacked else P()

    def fn(v):
        if stacked:
            v = jnp.squeeze(v, axis=0)
        return hier_allreduce(v, cross_axis=cross_axis, local_axis=local_axis)

    return _guarded(jax.jit(_smap(fn, mesh, (in_spec,), P())))


@functools.lru_cache(maxsize=None)
def _eager_hier_allgather_fn(mesh, cross_axis, local_axis, stacked):
    from horovod_tpu.ops.collective import _guarded, _smap

    in_spec = P((cross_axis, local_axis)) if stacked else P()

    def fn(v):
        if stacked:
            v = jnp.squeeze(v, axis=0)
        return hier_allgather(v, cross_axis=cross_axis, local_axis=local_axis)

    return _guarded(jax.jit(_smap(fn, mesh, (in_spec,), P())))


def hierarchical_allgather(tensor, *, cross_axis: str = CROSS_AXIS,
                           local_axis: str = LOCAL_AXIS):
    """Eager two-level allgather over the current mesh (dim-0 concat in
    global rank order). ``tensor`` is replicated or stacked
    ``[cross·local, ...]``; mirrors :func:`hierarchical_allreduce`."""
    from horovod_tpu.ops.collective import _as_array, _is_stacked

    mesh = basics.mesh()
    for ax in (cross_axis, local_axis):
        if ax not in mesh.axis_names:
            raise ValueError(
                f"mesh {mesh.axis_names} has no '{ax}' axis; build it with "
                f"build_host_mesh() or axes={{'cross': H, 'local': L}}"
            )
    tensor = _as_array(tensor)
    stacked = _stacked_pair(tensor, cross_axis, local_axis)
    fn = _eager_hier_allgather_fn(mesh, cross_axis, local_axis, stacked)
    return fn(tensor)


def hierarchical_allreduce(tensor, op=None, *, cross_axis: str = CROSS_AXIS,
                           local_axis: str = LOCAL_AXIS):
    """Eager two-level allreduce over the current mesh.

    ``tensor`` is either replicated or stacked ``[cross·local, ...]`` (one
    leading row per device, sharded over ``(cross, local)``); returns the
    reduction replicated, averaged unless ``op`` is ``ReduceOp.SUM``.
    """
    from horovod_tpu.ops.collective import (
        ReduceOp, _as_array, _div, _is_stacked,
    )

    mesh = basics.mesh()
    for ax in (cross_axis, local_axis):
        if ax not in mesh.axis_names:
            raise ValueError(
                f"mesh {mesh.axis_names} has no '{ax}' axis; build it with "
                f"build_host_mesh() or axes={{'cross': H, 'local': L}}"
            )
    tensor = _as_array(tensor)
    stacked = _stacked_pair(tensor, cross_axis, local_axis)
    fn = _eager_hier_allreduce_fn(mesh, cross_axis, local_axis, stacked)
    out = fn(tensor)  # per-rank row squeezed inside the kernel
    if op is None or op == ReduceOp.AVERAGE:
        out = _div(out, mesh.shape[cross_axis] * mesh.shape[local_axis])
    return out
