"""Multi-process eager collectives on host-local values.

This is the Horovod programming model proper (reference
``horovod/torch/mpi_ops.py``: every *process* passes its own tensor and
receives the cross-process result): under multi-controller JAX each process
owns ``local_chip_count()`` chips of the global mesh, and a host-local (numpy /
single-device) array is that process's contribution.

Mapping onto the chip-level data axis: the local value is tiled over the
process's local chips and assembled into a global ``[n_chips, ...]`` array via
``multihost_utils.host_local_array_to_global_array``; a chip-level ``psum``
then yields ``local_size * (sum over processes)``, so process-level Sum
divides by ``local_chip_count`` and process-level Average by ``n_chips`` — both
exact. Broadcast/allgather slice the tiling back out. This keeps one mesh and
one collective implementation for both the SPMD in-jit path and the
process-eager path.

Device order is process-major (JAX orders ``jax.devices()`` by process
index), matching the reference's rank-major slot allocation
(``run/gloo_run.py:54-112``).
"""

from __future__ import annotations

import pickle
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import multihost_utils
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import basics


def is_global_array(x) -> bool:
    """True iff x is a jax.Array already placed on the global mesh (the SPMD
    path); host-local numpy/scalars and single-device arrays are 'mine'."""
    sharding = getattr(x, "sharding", None)
    return isinstance(sharding, NamedSharding)


def _stack_local(x, ax: str):
    """Tile this process's value over its local chips and build the global
    stacked [n_chips, ...] array sharded over `ax`."""
    mesh = basics.mesh()
    ls = basics.local_chip_count()
    local = np.repeat(np.asarray(x)[None], ls, axis=0)
    return multihost_utils.host_local_array_to_global_array(local, mesh, P(ax))


def allreduce(x, op, ax: str):
    """Process-level allreduce; returns the reduced value replicated.

    The collective runs on the *flattened* tensor: a join()ed process
    zero-backfills from response metadata that only records element counts
    (``core.py::_execute_backfilled``), so flat-by-construction contributions
    from joined ranks always shape-match the live ranks' here.
    """
    from horovod_tpu.ops import collective as C

    mesh = basics.mesh()
    x = jnp.asarray(x)
    shape = x.shape
    g = _stack_local(jnp.reshape(x, (-1,)), ax)
    fn = C._eager_allreduce_fn(mesh, ax, True, 1)
    (out,) = fn(g)
    out = jnp.squeeze(out, axis=0)
    if op == C.Sum:
        out = C._div(out, basics.local_chip_count())
    elif op == C.Average:
        out = C._div(out, C._axis_size(ax))  # product for tuple axes
    else:
        raise ValueError(f"unsupported op for host-local allreduce: {op}")
    return jnp.reshape(out, shape)


def _allgather_equal(x, ax: str):
    """Allgather of same-shaped per-process tensors (concat along dim 0)."""
    from horovod_tpu.ops import collective as C

    mesh = basics.mesh()
    ls = basics.local_chip_count()
    g = _stack_local(x, ax)
    fn = C._eager_allgather_fn(mesh, ax, True, 1)
    (out,) = fn(g)  # [n_chips, *shape]; every ls-th row is one process
    out = out[::ls]  # [n_procs, *shape]
    return out.reshape((out.shape[0] * out.shape[1],) + out.shape[2:])


def allgather(x, ax: str):
    """Process-level allgather: concat per-process tensors along dim 0.

    Leading dims may DIFFER per process (reference semantics: allgather
    negotiates per-rank first-dim sizes and computes receive displacements,
    ``MPI_Allgatherv`` in ``mpi_operations.cc``): a tiny equal-shape count
    gather first, then ragged contributions are padded to the max row count
    and sliced back out after the gather."""
    x = jnp.asarray(x)
    if x.ndim == 0:
        x = x[None]
    nproc = basics.process_size()
    counts = np.asarray(
        _allgather_equal(jnp.asarray([x.shape[0]], jnp.int32), ax)
    ).reshape(nproc)
    if (counts == counts[0]).all():
        return _allgather_equal(x, ax)
    m = int(counts.max())
    pad = jnp.zeros((m - x.shape[0],) + x.shape[1:], x.dtype)
    out = np.asarray(_allgather_equal(jnp.concatenate([x, pad], axis=0), ax))
    out = out.reshape((nproc, m) + x.shape[1:])
    return jnp.concatenate(
        [jnp.asarray(out[i, : counts[i]]) for i in range(nproc)], axis=0
    )


def broadcast(x, root_proc: int, ax: str):
    """Process-level broadcast from `root_proc` (process index)."""
    from horovod_tpu.ops import collective as C

    mesh = basics.mesh()
    nproc = basics.process_size()
    if not 0 <= root_proc < nproc:
        raise ValueError(
            f"broadcast root rank {root_proc} out of range [0, {nproc})"
        )
    g = _stack_local(x, ax)
    was_bool = g.dtype == jnp.bool_
    if was_bool:
        g = g.astype(jnp.int8)
    root_coord = root_proc * basics.local_chip_count()  # process-major device order
    fn = C._eager_broadcast_fn(mesh, ax, int(root_coord))
    out = jnp.squeeze(fn(g), axis=0)
    return out.astype(jnp.bool_) if was_bool else out


def alltoall(x, ax: str):
    """Process-level alltoall: process ``r`` receives block ``r`` of every
    process's tensor, concatenated in process order (dim 0 split into
    ``process_size`` blocks).

    ``local_chip_count == 1`` runs a chip-level ``all_to_all`` directly.
    Multi-chip processes run the chip-level ``all_to_all`` on the tiled
    array when dim 0 divides the chip count: each chip then *receives* only
    ``rows`` elements (vs ``n_chips x rows`` for an allgather), and this
    process's chips collectively hold every process's block-``r`` chunk —
    duplicated ``local_chip_count`` times on the send side by the tiling,
    deduplicated in the host-side reassembly below. Falls back to
    allgather + local slice when dim 0 does not divide the chip count. The
    bandwidth-optimal path remains the in-jit SPMD ``all_to_all``.
    """
    from horovod_tpu.ops import collective as C

    mesh = basics.mesh()
    nproc = basics.process_size()
    ls = basics.local_chip_count()
    n_chips = C._axis_size(ax)
    rows = np.asarray(x).shape[0]
    if rows % nproc != 0:
        raise ValueError(
            f"alltoall dim 0 ({rows}) must be divisible by the number of "
            f"processes ({nproc})"
        )
    if ls == 1:
        g = _stack_local(x, ax)
        fn = C._eager_alltoall_fn(mesh, ax)
        out = fn(g)
        return jnp.asarray(np.asarray(out.addressable_data(0))[0])
    if rows % n_chips == 0:
        # chip-level exchange on the tiled array: chip c receives chip-chunk
        # c of every chip's (tiled) value. Process p owns chips
        # [p*ls, (p+1)*ls) (process-major device order), whose chunks
        # p*ls..(p+1)*ls-1 concatenate to exactly process-block p; sources
        # j and j+1.. within one process carry identical tiles, so one
        # source chip per process (j = q*ls) suffices.
        chunk = rows // n_chips
        g = _stack_local(x, ax)
        fn = C._eager_alltoall_fn(mesh, ax)
        out = fn(g)
        flat_devices = list(mesh.devices.reshape(-1))
        my_shards = {
            flat_devices.index(s.device): np.asarray(s.data)[0]
            for s in out.addressable_shards
        }
        p = basics.process_rank()
        blocks = []
        for q in range(nproc):
            j = q * ls  # dedup tiled sources: one chip per source process
            for m in range(ls):
                rec = my_shards[p * ls + m]
                blocks.append(rec[j * chunk:(j + 1) * chunk])
        return jnp.asarray(np.concatenate(blocks, axis=0))
    gathered = allgather(x, ax)  # [nproc * rows, ...]
    gathered = gathered.reshape((nproc, nproc, rows // nproc) + gathered.shape[1:])
    r = basics.process_rank()
    return gathered[:, r].reshape((rows,) + gathered.shape[3:])


def reducescatter(x, op, ax: str):
    """Process-level reduce-scatter: process ``r`` receives block ``r`` of
    the cross-process reduction (dim 0 split into ``process_size`` blocks).

    Multi-chip processes use the chip-level ``psum_scatter`` when dim 0
    divides the chip count — the device order is process-major, so a
    process's chips hold exactly the contiguous chip-blocks forming its
    process block; the tiling multiplies the sum by ``local_chip_count``, divided
    back out. Otherwise it falls back to allreduce + local slice.
    """
    from horovod_tpu.ops import collective as C

    mesh = basics.mesh()
    nproc = basics.process_size()
    ls = basics.local_chip_count()
    n_chips = C._axis_size(ax)
    rows = np.asarray(x).shape[0]
    if rows % nproc != 0:
        raise ValueError(
            f"reducescatter dim 0 ({rows}) must be divisible by the number "
            f"of processes ({nproc})"
        )
    if ls == 1 or rows % n_chips == 0:
        g = _stack_local(x, ax)
        fn = C._eager_reducescatter_fn(mesh, ax, True)
        out = fn(g)
        # this process's chips hold consecutive chip-blocks; concatenated
        # they are its process-level shard (process-major device order)
        flat_devices = list(mesh.devices.reshape(-1))
        shards = sorted(
            ((flat_devices.index(s.device), np.asarray(s.data))
             for s in out.addressable_shards),
            key=lambda t: t[0],
        )
        shard = jnp.concatenate([jnp.asarray(v)[0] for _, v in shards], axis=0)
        if ls > 1:
            shard = C._div(shard, ls)  # tiling contributed ls copies
        if op == C.Average:
            shard = C._div(shard, nproc)
        return shard
    reduced = allreduce(x, C.Sum, ax)  # [rows, ...] full reduction
    block = rows // nproc
    r = basics.process_rank()
    shard = reduced[r * block:(r + 1) * block]
    if op == C.Average:
        shard = C._div(shard, nproc)
    return shard


# ----------------------------------------------------------- object shuttle


def _obj_to_padded(obj):
    blob = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    return blob


def allgather_object(obj, ax: str) -> list:
    """Gather arbitrary picklable objects from every process (reference
    pattern ``torch/__init__.py:609-648``: length-allgather + padded
    byte-tensor allgather)."""
    from horovod_tpu.ops import collective as C

    blob = _obj_to_padded(obj)
    # both gathers are equal-shaped by construction — skip the ragged
    # size negotiation allgather() would prepend
    lengths = np.asarray(_allgather_equal(np.array([len(blob)], np.int32), ax))
    max_len = int(lengths.max())
    padded = np.zeros((max_len,), np.uint8)
    padded[: len(blob)] = blob
    gathered = np.asarray(_allgather_equal(padded, ax))
    gathered = gathered.reshape(basics.process_size(), max_len)
    per_process = [
        pickle.loads(gathered[i, : int(lengths[i])].tobytes())
        for i in range(basics.process_size())
    ]
    # one entry per *chip* ("rank" = chip, so len == hvd.size() regardless of
    # process count; chips of the same process hold that process's object)
    out = []
    for obj_i in per_process:
        out.extend([obj_i] * basics.local_chip_count())
    return out


def broadcast_object(obj, root_proc: int, ax: str):
    """Broadcast a picklable object from `root_proc`."""
    blob = _obj_to_padded(obj)
    length = np.asarray(
        broadcast(np.array([len(blob)], np.int32), root_proc, ax)
    )
    n = int(length[0])
    buf = np.zeros((n,), np.uint8)
    buf[: min(len(blob), n)] = blob[:n]  # non-root values are masked anyway
    out = np.asarray(broadcast(buf, root_proc, ax))
    return pickle.loads(out.tobytes())
