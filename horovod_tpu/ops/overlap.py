"""Bucketed backward-pass gradient sync: comm/compute overlap.

The reference's defining perf trick is the background controller plus the
64 MB fusion buffer that overlaps allreduce with backprop
(``controller.cc:640-761``, ``operations.cc:550-600``): gradients are
reduced as backprop produces them, so step time approaches
``max(compute, comm)`` instead of ``compute + comm``. Every train-step
path here previously synced the whole gradient tree only after the full
backward pass. This module rebuilds the insight TPU-natively (the same
bucketing PyTorch DDP uses — Li et al., VLDB 2020):

- :class:`BucketPlan` partitions the flat per-dtype gradient packing into
  ~``HOROVOD_BUCKET_BYTES`` (default 64 MB, honoring the existing
  ``HOROVOD_FUSION_THRESHOLD`` knob) buckets in **reverse-topological
  (backprop-emission) order** — the last-declared parameters' gradients
  are produced first in the backward pass, so their bucket's collective
  can launch while the earlier layers' backward still runs.
- one collective per bucket instead of one per tree/dtype: each bucket's
  ``psum``/``psum_scatter`` depends only on ITS leaves' cotangents, so
  XLA's latency-hiding scheduler (plus the async-collective flags
  :func:`horovod_tpu.tuning.apply_xla_flags` sets) can hoist the launch
  into the backward — the data dependency, not the trace position, is
  what the scheduler honors.
- :func:`sync_hook` additionally *pins* the interleaving structurally: a
  ``custom_vjp`` hook on a layer block issues the block's bucket
  collectives inside its backward rule and threads the activation
  cotangent through :func:`barrier_after`
  (``lax.optimization_barrier``), so the remaining backward fragments
  *data-depend* on the issued collectives — no scheduler, CPU included,
  can sink them to the end of the step.

Used by ``DistributedOptimizer(overlap=True)`` (per-bucket reduce-scatter
under ZeRO-1 with a single trailing all-gather per dtype; per-bucket
quantize with error-feedback residuals keyed by bucket) and
``make_shardmap_train_step(..., overlap=True)``.
"""

from __future__ import annotations

import functools
import os
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import basics
from horovod_tpu.observability import metrics as _metrics

__all__ = [
    "DEFAULT_BUCKET_BYTES",
    "Segment",
    "Bucket",
    "BucketPlan",
    "plan_for",
    "bucket_bytes_from_env",
    "resolve_bucket_bytes",
    "barrier_enabled",
    "pack_group",
    "pack_group_rows",
    "assemble",
    "bucketed_allreduce",
    "barrier_after",
    "sync_hook",
]

#: default bucket capacity — the reference fusion buffer's 64 MB
DEFAULT_BUCKET_BYTES = 64 * 1024 * 1024

#: env knobs (documented in docs/performance.md's overlap knob table; the
#: CI guard in tests/test_overlap.py pins every HOROVOD_BUCKET_* /
#: HOROVOD_OVERLAP_* literal into that table)
BUCKET_BYTES_ENV = "HOROVOD_BUCKET_BYTES"
OVERLAP_ENV = "HOROVOD_OVERLAP"
OVERLAP_BARRIER_ENV = "HOROVOD_OVERLAP_BARRIER"


def _env_true(name: str, default: str = "0") -> bool:
    return os.environ.get(name, default).lower() in ("1", "true", "yes")


def bucket_bytes_from_env() -> int:
    """Bucket capacity in bytes: ``HOROVOD_BUCKET_BYTES`` when set, else
    the existing fusion-threshold knob ``HOROVOD_FUSION_THRESHOLD`` (the
    native core's bin size — one knob, one meaning), else 64 MB."""
    for name in (BUCKET_BYTES_ENV, "HOROVOD_FUSION_THRESHOLD"):
        v = os.environ.get(name)
        if v:
            return max(1, int(v))
    return DEFAULT_BUCKET_BYTES


def resolve_bucket_bytes(overlap=None, bucket_bytes: Optional[int] = None
                         ) -> Optional[int]:
    """Resolve the ``overlap=``/``bucket_bytes=`` kwarg pair to a bucket
    capacity, or ``None`` for the monolithic path.

    ``overlap=None`` consults ``HOROVOD_OVERLAP``; ``overlap=False``
    disables even with the env set (the explicit kwarg wins, matching
    every other knob here); ``bucket_bytes`` alone implies overlap."""
    if overlap is None:
        overlap = True if bucket_bytes is not None else _env_true(OVERLAP_ENV)
    if not overlap:
        return None
    if bucket_bytes is not None:
        return max(1, int(bucket_bytes))
    return bucket_bytes_from_env()


def barrier_enabled() -> bool:
    """``HOROVOD_OVERLAP_BARRIER`` (default on): thread
    ``lax.optimization_barrier`` tokens from each issued bucket collective
    into the remaining backward, pinning the interleaved order as a data
    dependency. Off, the schedule is left entirely to XLA's
    latency-hiding scheduler (maximum freedom, no ordering pin)."""
    return _env_true(OVERLAP_BARRIER_ENV, "1")


# --------------------------------------------------------------------------
# the plan


class Segment(NamedTuple):
    """One contiguous element range ``[start, stop)`` of raveled leaf
    ``idx`` — a bucket boundary may split a leaf, so a leaf can span
    several buckets via several segments."""

    idx: int
    start: int
    stop: int

    @property
    def size(self) -> int:
        return self.stop - self.start


class Bucket(NamedTuple):
    """One bucket: single-dtype (a collective moves one dtype), ordered
    segments, true packed length ``L`` and ``Lp`` padded to the axis
    size (ZeRO-1 reduce-scatter needs ``Lp % N == 0``; padding is zeros
    and inert through elementwise optimizers)."""

    key: str
    dtype: str
    segs: Tuple[Segment, ...]
    L: int
    Lp: int

    @property
    def idxs(self) -> Tuple[int, ...]:
        seen: List[int] = []
        for s in self.segs:
            if s.idx not in seen:
                seen.append(s.idx)
        return tuple(seen)


def _leaf_shape_dtype(leaf) -> Tuple[Tuple[int, ...], str]:
    shape = tuple(getattr(leaf, "shape", ()) or ())
    dt = getattr(leaf, "dtype", None)
    dt = jnp.dtype(dt) if dt is not None else jnp.result_type(leaf)
    return shape, str(dt)


class BucketPlan:
    """Partition of a gradient tree's leaves into reverse-emission-order
    buckets of ~``bucket_bytes`` each.

    The partition depends only on the leaf shapes/dtypes and
    ``bucket_bytes`` — NOT on the axis size ``n``, which only pads each
    bucket (``Lp``). Resharding a bucketed optimizer state across world
    sizes therefore re-derives the identical segment boundaries.
    """

    def __init__(self, buckets: Sequence[Bucket], *, n: int,
                 bucket_bytes: int):
        self.buckets: Tuple[Bucket, ...] = tuple(buckets)
        self.n = int(n)
        self.bucket_bytes = int(bucket_bytes)
        self.groups = {b.key: b for b in self.buckets}

    def __len__(self) -> int:
        return len(self.buckets)

    def describe(self) -> str:
        return "\n".join(
            f"{b.key}: L={b.L} Lp={b.Lp} segs="
            + ",".join(f"{s.idx}[{s.start}:{s.stop}]" for s in b.segs)
            for b in self.buckets
        )

    @classmethod
    def build(cls, leaves: Sequence, n: int,
              bucket_bytes: Optional[int] = None) -> "BucketPlan":
        """Build the plan from leaves (arrays or anything with
        ``.shape``/``.dtype``). Iteration runs over the leaves in
        REVERSE tree-flatten order: backprop produces the last-declared
        parameters' cotangents first, so the first bucket closed is the
        first whose gradients exist mid-backward."""
        bucket_bytes = int(bucket_bytes or bucket_bytes_from_env())
        n = max(1, int(n))
        open_segs: dict = {}    # dtype -> (segs list, bytes, elems)
        counters: dict = {}     # dtype -> next bucket ordinal
        buckets: List[Bucket] = []

        def close(dt: str) -> None:
            segs, _nbytes, elems = open_segs.pop(dt)
            if not segs:
                return
            k = counters.get(dt, 0)
            counters[dt] = k + 1
            L = elems
            buckets.append(Bucket(
                key=f"{dt}#{k}", dtype=dt, segs=tuple(segs),
                L=L, Lp=L + ((-L) % n),
            ))

        infos = [_leaf_shape_dtype(l) for l in leaves]
        for i in reversed(range(len(infos))):
            shape, dt = infos[i]
            size = int(np.prod(shape, dtype=np.int64)) if shape else 1
            if size == 0:
                continue
            itemsize = int(jnp.dtype(dt).itemsize)
            pos = 0
            while pos < size:
                segs, nbytes, elems = open_segs.setdefault(dt, ([], 0, 0))
                # at least one element of progress per iteration, so a
                # bucket_bytes below one itemsize still terminates
                room = max(1, (bucket_bytes - nbytes) // itemsize)
                take = min(size - pos, room)
                segs.append(Segment(i, pos, pos + take))
                nbytes += take * itemsize
                elems += take
                open_segs[dt] = (segs, nbytes, elems)
                pos += take
                if nbytes >= bucket_bytes:
                    close(dt)
        for dt in list(open_segs):
            close(dt)
        return cls(buckets, n=n, bucket_bytes=bucket_bytes)


@functools.lru_cache(maxsize=256)
def _cached_plan(sig: tuple, n: int, bucket_bytes: int) -> BucketPlan:
    return BucketPlan.build(
        [jax.ShapeDtypeStruct(shape, jnp.dtype(dt)) for shape, dt in sig],
        n, bucket_bytes)


def plan_for(leaves: Sequence, n: int,
             bucket_bytes: Optional[int] = None) -> BucketPlan:
    """Cached :meth:`BucketPlan.build` keyed on the (shape, dtype)
    signature — the eager path rebuilds the plan every step, and the
    partition is pure in the signature."""
    bucket_bytes = int(bucket_bytes or bucket_bytes_from_env())
    sig = tuple(_leaf_shape_dtype(l) for l in leaves)
    return _cached_plan(sig, max(1, int(n)), bucket_bytes)


# --------------------------------------------------------------------------
# pack / unpack


def pack_group(leaves, bucket: Bucket):
    """Flatten + concatenate one bucket's segments, zero-padded to Lp."""
    parts = [
        jnp.ravel(jnp.asarray(leaves[s.idx]))[s.start:s.stop]
        for s in bucket.segs
    ]
    flat = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    if bucket.Lp > bucket.L:
        flat = jnp.concatenate(
            [flat, jnp.zeros((bucket.Lp - bucket.L,), flat.dtype)])
    return flat


def pack_group_rows(leaves, bucket: Bucket, stacked_flags, n: int):
    """``[N, Lp]`` matrix of per-rank flat contributions for one bucket:
    stacked ``[N, ...]`` leaves supply their own rows, replicated leaves
    tile (the eager-path analog of :func:`pack_group`)."""
    rows = []
    for s in bucket.segs:
        l = jnp.asarray(leaves[s.idx])
        if stacked_flags[s.idx]:
            rows.append(l.reshape(n, -1)[:, s.start:s.stop])
        else:
            rows.append(jnp.broadcast_to(
                jnp.ravel(l)[None, s.start:s.stop], (n, s.size)))
    m = rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=1)
    if bucket.Lp > bucket.L:
        m = jnp.concatenate(
            [m, jnp.zeros((n, bucket.Lp - bucket.L), m.dtype)], axis=1)
    return m


def assemble(flats: dict, groups: dict, shapes: Sequence[Tuple[int, ...]],
             dtypes: Sequence) -> list:
    """Reassemble leaves from per-bucket flat buffers. ``flats[key]`` is
    the bucket's reduced flat buffer (length >= L; padding ignored);
    a leaf split across buckets is stitched from its segments in element
    order. Leaves no bucket covers (zero-size) come back as zeros."""
    pieces: dict = {}
    for key, b in groups.items():
        flat = flats[key]
        off = 0
        for s in b.segs:
            pieces.setdefault(s.idx, []).append((s.start, flat[off:off + s.size]))
            off += s.size
    out = []
    for i, shape in enumerate(shapes):
        ps = sorted(pieces.get(i, ()), key=lambda t: t[0])
        if not ps:
            out.append(jnp.zeros(shape, jnp.dtype(dtypes[i])))
            continue
        flat = (
            ps[0][1] if len(ps) == 1
            else jnp.concatenate([p for _, p in ps])
        )
        out.append(flat.reshape(shape))
    return out


# --------------------------------------------------------------------------
# bucketed tree sync (the non-sharded / allreduce mode)


def _record_buckets(mode: str, k: int) -> None:
    if not _metrics.enabled():
        return
    _metrics.gauge(
        "grad_sync_buckets",
        help="gradient-sync collectives (buckets) issued per step",
        mode=mode,
    ).set(k)


def bucketed_allreduce(grads, op=None, *, axis=None, compression=None,
                       bucket_bytes: Optional[int] = None,
                       plan: Optional[BucketPlan] = None,
                       predivide: float = 1.0,
                       residual: Optional[dict] = None,
                       roundtrip=None):
    """Allreduce a gradient tree through reverse-emission-order buckets:
    one flat collective per bucket instead of one per leaf, each
    depending only on its own leaves' cotangents — the overlappable
    schedule. Trajectory-identical to the per-leaf path for ``none`` and
    ``fp16`` wire formats (packing is a permutation; the elementwise cast
    and the cross-rank sum commute with it); blockwise int8 scales are
    layout-dependent, so the int8 wire tracks within one quantization
    step per element (error feedback keeps it convergence-safe).

    With ``residual`` (a dict keyed by bucket key — the error-feedback
    state layout ``DistributedOptimizer(overlap=True)`` carries), returns
    ``(reduced_tree, new_residual)``; otherwise ``(reduced_tree, None)``.
    ``roundtrip`` models what one bucket's wire transfer preserves
    (default: the compressor's compress→decompress roundtrip).
    """
    from horovod_tpu.compression import Compression
    from horovod_tpu.ops import collective as _C

    op = _C.Average if op is None else op
    if op not in (_C.Average, _C.Sum):
        raise ValueError(
            "bucketed overlap supports op=Average/Sum (Adasum's pairwise "
            "projections are per-tensor scalars; bucket packing would mix "
            "them)"
        )
    compression = Compression.none if compression is None else compression
    if getattr(compression, "factorized", False):
        raise ValueError(
            "factorized compression (PowerSGD) syncs per-leaf rank-r "
            "factors; bucket-level overlap does not apply — drop "
            "overlap= or use the int8/fp16 wire"
        )
    ax = _C._axis(axis)
    n = _C._axis_size(ax)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    traced = any(_C._is_tracer(l) for l in leaves)
    stacked_flags = [
        (not traced) and _C._is_stacked(l, ax) for l in leaves
    ]
    shapes = [
        tuple(l.shape[1:]) if st else tuple(getattr(l, "shape", ()))
        for l, st in zip(leaves, stacked_flags)
    ]
    dtypes = [_leaf_shape_dtype(l)[1] for l in leaves]

    if plan is None:
        # n=1: the allreduce wire needs no shard padding (the quantized
        # ring pads internally), so L == Lp and the packing is exact
        plan = plan_for(
            [jax.ShapeDtypeStruct(s, jnp.dtype(d))
             for s, d in zip(shapes, dtypes)], 1, bucket_bytes)

    if roundtrip is None:
        def roundtrip(v):
            c, ctx = compression.compress(v)
            return compression.decompress(c, ctx)

    if basics.is_initialized():
        # byte-model accounting, priced per BUCKET through the
        # compressor's wire_bytes hook (the int8 floor applies to the
        # packed bucket, exactly what the wire below does)
        from horovod_tpu import optim as _optim

        _optim._record_sync_bytes("allreduce", n, sum(
            _optim._wire_bytes_leaf(
                (b.L,), jnp.dtype(b.dtype), compression)
            for b in plan.buckets
        ))

    reduced_flats = {}
    new_res: Optional[dict] = {} if residual is not None else None
    for key, b in plan.groups.items():
        if any(stacked_flags[i] for i in b.idxs):
            flat = pack_group_rows(leaves, b, stacked_flags, n)   # [N, L]
            flat = jax.device_put(
                flat, NamedSharding(basics.mesh(), P(ax)))
        else:
            flat = pack_group(leaves, b)                          # [L]
        if residual is not None:
            corrected = flat + residual[key]
            new_res[key] = (corrected - roundtrip(corrected)).astype(
                jnp.dtype(b.dtype))
            flat = corrected
        if op == _C.Average and predivide != 1.0:
            out = _C.allreduce(
                flat / predivide, _C.Sum, axis=ax, compression=compression,
            ) * (predivide / n)
        else:
            out = _C.allreduce(flat, op, axis=ax, compression=compression)
        reduced_flats[key] = out[:b.L]
    _record_buckets("allreduce", len(plan.groups))
    # eager stacked inputs reduce to the replicated per-rank shape — the
    # same contract allreduce() itself has
    out_leaves = assemble(reduced_flats, plan.groups, shapes, dtypes)
    return jax.tree_util.tree_unflatten(treedef, out_leaves), new_res


# --------------------------------------------------------------------------
# interleaving pins: barrier threading + the custom_vjp block hook


def barrier_after(x, dep):
    """Thread an ``optimization_barrier`` token derived from ``dep``
    (typically an issued bucket collective's output) into ``x`` (the
    activation cotangent the remaining backward consumes): every
    topological order — XLA's schedulers included — must now place the
    collective before the later backward fragments. One tiny (1-element)
    token per bucket; no math changes."""
    dep_leaves = [
        l for l in jax.tree_util.tree_leaves(dep)
        if hasattr(l, "dtype") and getattr(l, "size", 0)
    ]
    if not dep_leaves:
        return x
    tok = jnp.ravel(dep_leaves[0])[:1]
    flat, tdef = jax.tree_util.tree_flatten(x)
    if not flat:
        return x
    out = lax.optimization_barrier(tuple(flat) + (tok,))
    return jax.tree_util.tree_unflatten(tdef, list(out[:-1]))


def chain_barriers(values: list) -> list:
    """Pin issue order across a sequence of independent collectives'
    outputs: value k is barrier-tied to value k-1, so every schedule —
    XLA's latency-hiding scheduler included — issues them in list order.
    The ZeRO-3 gather-on-use leg chains its per-bucket parameter
    all-gathers this way: the forward consumes bucket k while bucket
    k+1's gather is still in flight, instead of all gathers racing (and
    all gathered buffers being live) at step start — the
    :func:`sync_hook`/:func:`barrier_after` trick run in the forward
    direction."""
    if len(values) <= 1:
        return list(values)
    out = [values[0]]
    for v in values[1:]:
        out.append(barrier_after(v, out[-1]))
    return out


def sync_hook(block_fn, sync_fn, *, barrier: Optional[bool] = None):
    """Wrap ``block_fn(params, x) -> y`` so its backward rule issues the
    block's gradient sync *inside* the backward pass — the ``custom_vjp``
    spelling of the reference's "reduce while backprop still runs".

    ``sync_fn(param_grads) -> synced_grads`` is typically a
    :func:`bucketed_allreduce` closure. With ``barrier`` (default: the
    ``HOROVOD_OVERLAP_BARRIER`` knob) the activation cotangent is
    barrier-tied to the issued collective, pinning bucket k's sync
    *between* block k's and block k-1's backward fragments in every
    schedule. ``jax.grad`` of a model composed of hooked blocks returns
    gradients that are ALREADY synced — pair with a plain optimizer, not
    ``DistributedOptimizer`` (which would reduce a second time)."""

    @jax.custom_vjp
    def blk(p, x):
        return block_fn(p, x)

    def fwd(p, x):
        y, vjp = jax.vjp(block_fn, p, x)
        return y, vjp

    def bwd(vjp, g):
        gp, gx = vjp(g)
        gp = sync_fn(gp)
        use_barrier = barrier_enabled() if barrier is None else barrier
        if use_barrier:
            gx = barrier_after(gx, gp)
        return gp, gx

    blk.defvjp(fwd, bwd)
    return blk
