"""Blockwise (flash) attention: the single-chip building block of the
long-context stack (:mod:`horovod_tpu.parallel.ring_attention`).

No counterpart exists in the reference — Horovod 0.19.2 shards only the batch
axis (SURVEY.md §5.7) — so this module is TPU-native capability: an online-
softmax attention whose working set stays in VMEM-sized tiles feeding the MXU,
written as a Pallas kernel (grid ``[batch*heads, q_blocks, k_blocks]``,
accumulators in VMEM scratch) with a mathematically identical ``lax.scan``
implementation used off-TPU.

The backward pass is the standard flash backward: the forward saves only
``out`` and the log-sum-exp rows (O(T) extra memory, not the O(T²) score
matrix); the backward recomputes each block's probabilities from (q, k, lse)
and accumulates dq/dk/dv blockwise. The same block primitive
(:func:`_block_bwd`) powers ring attention's distributed backward.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30
#: lse stand-in for fully-masked rows: exp(s - BIG) == 0 for any real score
LSE_MASKED = 1e30


def _block_sizes(t_q: int, t_k: int, block_q: int, block_k: int):
    bq = min(block_q, t_q)
    bk = min(block_k, t_k)
    while t_q % bq:
        bq //= 2
    while t_k % bk:
        bk //= 2
    return max(bq, 1), max(bk, 1)


def _causal_mask(q_ids, k_ids):
    return q_ids[:, None] >= k_ids[None, :]


def lse_from_state(m, l):
    """log-sum-exp rows from online-softmax state; fully-masked rows get
    ``LSE_MASKED`` so recomputed probabilities vanish."""
    return jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), LSE_MASKED)


# --------------------------------------------------------------------------
# scan implementation (CPU / reference) — forward state


def _attention_scan(q, k, v, *, causal: bool, sm_scale: float,
                    q_offset, kv_offset, block_k: int):
    """Online-softmax attention over K/V blocks with a lax.scan.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]. ``q_offset``/``kv_offset`` are the
    global sequence positions of element 0 (used by ring attention to mask
    causally across devices); they may be traced values.

    Returns online-softmax state ``(m, l, acc)`` with m/l: [B, H, Tq],
    acc: [B, H, Tq, D].
    """
    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    _, bk = _block_sizes(t_q, t_k, t_q, block_k)
    n_k = t_k // bk

    qf = q.astype(jnp.float32) * sm_scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    # [B, H, Tq, D] so the matmul contracts the trailing dim on the MXU
    qf = qf.transpose(0, 2, 1, 3)
    kf = kf.transpose(0, 2, 1, 3).reshape(b, h, n_k, bk, d)
    vf = vf.transpose(0, 2, 1, 3).reshape(b, h, n_k, bk, d)

    q_ids = q_offset + jnp.arange(t_q)

    def step(carry, blk):
        m, l, acc = carry
        k_blk, v_blk, j = blk
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk)  # [B,H,Tq,bk]
        if causal:
            k_ids = kv_offset + j * bk + jnp.arange(bk)
            s = jnp.where(_causal_mask(q_ids, k_ids)[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((b, h, t_q), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_q), jnp.float32)
    acc0 = jnp.zeros((b, h, t_q, d), jnp.float32)
    (m, l, acc), _ = lax.scan(
        step, (m0, l0, acc0),
        (kf.transpose(2, 0, 1, 3, 4), vf.transpose(2, 0, 1, 3, 4),
         jnp.arange(n_k)),
    )
    return m, l, acc


def _finalize(m, l, acc, dtype):
    # fully-masked rows (ring attention with kv entirely in the causal
    # future) have l == 0; emit zeros, not NaNs
    safe_l = jnp.where(l > 0, l, 1.0)
    out = acc / safe_l[..., None]
    out = jnp.where((l > 0)[..., None], out, 0.0)
    return out.transpose(0, 2, 1, 3).astype(dtype)  # [B, Tq, H, D]


# --------------------------------------------------------------------------
# shared block backward primitive


def _block_bwd(q, k_blk, v_blk, dout, delta, lse, *, causal: bool,
               sm_scale: float, q_offset, kv_offset):
    """Gradient contributions of one K/V block, recomputing p from lse.

    q/dout: [B, Tq, H, D]; k_blk/v_blk: [B, Tk, H, D];
    delta/lse: [B, H, Tq] (delta = rowsum(dout * out)).
    Returns (dq_contrib [B,Tq,H,D], dk_blk, dv_blk [B,Tk,H,D]) in float32.
    """
    qf = q.astype(jnp.float32)
    kf = k_blk.astype(jnp.float32)
    vf = v_blk.astype(jnp.float32)
    dof = dout.astype(jnp.float32)

    s = jnp.einsum("bqhd,bkhd->bhqk", qf, kf) * sm_scale
    if causal:
        t_q, t_k = q.shape[1], k_blk.shape[1]
        q_ids = q_offset + jnp.arange(t_q)
        k_ids = kv_offset + jnp.arange(t_k)
        s = jnp.where(_causal_mask(q_ids, k_ids)[None, None], s, NEG_INF)
    p = jnp.exp(s - lse[..., None])                      # [B,H,Tq,Tk]
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
    dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vf)
    ds = p * (dp - delta[..., None])
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, kf) * sm_scale
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf) * sm_scale
    return dq, dk, dv


def _delta(out, dout):
    """delta = rowsum(dout * out): [B, Tq, H, D] -> [B, H, Tq]."""
    return jnp.einsum(
        "bqhd,bqhd->bhq",
        out.astype(jnp.float32), dout.astype(jnp.float32))


# --------------------------------------------------------------------------
# pallas kernel (TPU hot path) — emits out AND lse


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                      m_scratch, l_scratch, acc_scratch,
                      *, sm_scale: float, causal: bool, block_q: int,
                      block_k: int):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    # All online-softmax state is kept 2-D ([bq, 1] keepdims columns):
    # Mosaic's TPU lowering wants >=2-D vectors, and (bq, 1) broadcasts
    # cleanly against both s [bq, bk] and acc [bq, D].
    def _compute():
        q = q_ref[0].astype(jnp.float32) * sm_scale     # [bq, D]
        k = k_ref[0].astype(jnp.float32)                # [bk, D]
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bq, bk]
        if causal:
            q_ids = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_ids = kj * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, NEG_INF)
        m_prev = m_scratch[:]                            # [bq, 1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)                  # [bq, 1]
        l_new = l_scratch[:] * alpha + p.sum(axis=-1, keepdims=True)
        acc_scratch[:] = (
            acc_scratch[:] * alpha
            + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        )
        m_scratch[:] = m_new
        l_scratch[:] = l_new

    if causal:
        # whole block strictly in the future -> skip
        @pl.when(kj * block_k <= qi * block_q + (block_q - 1))
        def _():
            _compute()
    else:
        _compute()

    @pl.when(kj == n_k - 1)
    def _write():
        m, l = m_scratch[:], l_scratch[:]                # [bq, 1]
        safe_l = jnp.where(l > 0, l, 1.0)
        out = acc_scratch[:] / safe_l
        o_ref[0] = jnp.where(l > 0, out, 0.0).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(
            l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), LSE_MASKED)


def _flash_fwd_pallas(q, k, v, *, causal: bool, sm_scale: float,
                      block_q: int, block_k: int, interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, t_q, h, d = q.shape
    t_k = k.shape[1]
    h_kv = k.shape[2]
    g = h // h_kv
    bq, bk = _block_sizes(t_q, t_k, block_q, block_k)

    # [B*H, T, D] layout: one grid row per (batch, head). K/V keep their
    # H_kv rows; GQA maps each query head's grid row onto its kv head in
    # the BlockSpec index map — zero-copy, no H-wide K/V buffer exists.
    qr = q.transpose(0, 2, 1, 3).reshape(b * h, t_q, d)
    kr = k.transpose(0, 2, 1, 3).reshape(b * h_kv, t_k, d)
    vr = v.transpose(0, 2, 1, 3).reshape(b * h_kv, t_k, d)

    def kv_row(bh):
        # grid row bh = batch*h + head  ->  kv row = batch*h_kv + head//g
        return (bh // h) * h_kv + (bh % h) // g

    kernel = functools.partial(
        _flash_fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=bq, block_k=bk,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, t_q // bq, t_k // bk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, kj: (kv_row(bh), kj, 0)),
            pl.BlockSpec((1, bk, d), lambda bh, qi, kj: (kv_row(bh), kj, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, qi, kj: (bh, qi, 0)),
            # trailing singleton keeps the lse block 2-D per grid row:
            # (bq, 1) satisfies Mosaic's tiling rule (dim -2 divisible by
            # 8, dim -1 equal to the array's), which a (1, bq) block of a
            # rank-2 [B*H, Tq] array does not
            pl.BlockSpec((1, bq, 1), lambda bh, qi, kj: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, 1), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    out = out.reshape(b, h, t_q, d).transpose(0, 2, 1, 3)
    lse = lse.reshape(b, h, t_q)
    return out, lse


# --------------------------------------------------------------------------
# public op with flash (blockwise-recompute) backward


def gqa_group(q, k) -> int:
    """Query-group size for GQA/MQA (1 = standard multi-head)."""
    h, h_kv = q.shape[2], k.shape[2]
    if h % h_kv != 0:
        raise ValueError(
            f"query heads ({h}) must be a multiple of kv heads ({h_kv})"
        )
    return h // h_kv


def rep_group(x, g: int):
    """Broadcast K/V heads over query groups (jit fuses the broadcast;
    repeat lays the g copies of each kv head adjacently)."""
    return jnp.repeat(x, g, axis=2) if g > 1 else x


def reduce_group(dx, g: int):
    """Transpose of :func:`rep_group` for gradients: sum each kv head's
    adjacent query-group copies. Expects a 4-D [B, T, H, D] block (heads on
    axis 2, matching :func:`rep_group`)."""
    if g == 1:
        return dx
    b, t, h, d = dx.shape
    return dx.reshape(b, t, h // g, g, d).sum(axis=3)


def _fwd_impl(q, k, v, causal, sm_scale, block_sizes):
    block_q, block_k, use_pallas, interpret = block_sizes
    if use_pallas:
        # GQA handled zero-copy inside the kernel's kv index map
        return _flash_fwd_pallas(
            q, k, v, causal=causal, sm_scale=sm_scale,
            block_q=block_q, block_k=block_k, interpret=interpret)
    g = gqa_group(q, k)
    m, l, acc = _attention_scan(
        q, rep_group(k, g), rep_group(v, g), causal=causal,
        sm_scale=sm_scale,
        q_offset=0, kv_offset=0, block_k=block_k)
    return _finalize(m, l, acc, q.dtype), lse_from_state(m, l)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, sm_scale, block_sizes):
    return _fwd_impl(q, k, v, causal, sm_scale, block_sizes)[0]


def _flash_fwd(q, k, v, causal, sm_scale, block_sizes):
    out, lse = _fwd_impl(q, k, v, causal, sm_scale, block_sizes)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_sizes, res, g):
    """O(T) extra-memory backward: scan K/V blocks, recomputing p from lse
    (saves no score matrix — the flash-attention trade). Residual K/V stay
    H_kv-wide under GQA; each block is broadcast per step and its gradient
    group-summed back (repeat's transpose — adjacent-copy layout)."""
    q, k, v, out, lse = res
    block_k = block_sizes[1]
    b, t_k, h_kv, d = k.shape
    h = q.shape[2]
    grp = h // h_kv
    _, bk = _block_sizes(q.shape[1], t_k, q.shape[1], block_k)
    n_k = t_k // bk
    delta = _delta(out, g)

    k_blocks = k.reshape(b, n_k, bk, h_kv, d).transpose(1, 0, 2, 3, 4)
    v_blocks = v.reshape(b, n_k, bk, h_kv, d).transpose(1, 0, 2, 3, 4)

    def step(dq, blk):
        k_blk, v_blk, j = blk
        dq_c, dk_b, dv_b = _block_bwd(
            q, rep_group(k_blk, grp), rep_group(v_blk, grp), g, delta,
            lse, causal=causal,
            sm_scale=sm_scale, q_offset=0, kv_offset=j * bk)
        return dq + dq_c, (reduce_group(dk_b, grp), reduce_group(dv_b, grp))

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dq, (dk_blocks, dv_blocks) = lax.scan(
        step, dq0, (k_blocks, v_blocks, jnp.arange(n_k)))
    dk = dk_blocks.transpose(1, 0, 2, 3, 4).reshape(b, t_k, h_kv, d)
    dv = dv_blocks.transpose(1, 0, 2, 3, 4).reshape(b, t_k, h_kv, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def decode_attention(q, k_cache, v_cache, start_pos):
    """Attention of a new chunk q ``[B, T, H, D]`` (query t sits at global
    position ``start_pos[b] + t``) against a kv cache ``[B, L, H_kv, D]``,
    causally masked per row. T=1 is the decode step; T=prompt_len (or a
    prefill chunk) is the prefill. GQA-aware. Cache positions beyond a
    row's frontier are masked to ``-1e30`` — ``exp`` underflows them to an
    exact 0, so garbage (or page-pool padding) past the frontier
    contributes nothing.

    This is the single decode-attention primitive: the contiguous-cache
    path (:class:`horovod_tpu.models.transformer.TransformerBlock` with
    ``decode=True``) calls it directly, and the serving engine's paged
    cache reaches it through :func:`paged_decode_attention`."""
    if k_cache.shape[2] != q.shape[2]:
        k_cache, v_cache = repeat_kv_heads(q, k_cache, v_cache)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache) * q.shape[-1] ** -0.5
    t, l = q.shape[1], k_cache.shape[1]
    qpos = start_pos[:, None] + jnp.arange(t)[None, :]           # [B, T]
    valid = jnp.arange(l)[None, None, :] <= qpos[:, :, None]     # [B, T, L]
    s = jnp.where(valid[:, None, :, :], s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v_cache)


def paged_decode_attention(q, k_pages, v_pages, page_table, start_pos, *,
                           page_size: int):
    """Decode attention against a **paged** KV cache (vLLM-style).

    ``k_pages``/``v_pages``: the shared page pool ``[P, page_size, H_kv,
    D]`` — fixed-size pages owned by a free-list allocator, so any batch
    composition shares one preallocated buffer. ``page_table``: ``[B,
    pages_per_seq]`` int32 page ids per sequence slot, position-ordered
    (token at global position p lives in page ``page_table[b, p //
    page_size]`` at offset ``p % page_size``). ``q``: ``[B, T, H, D]``
    with query t at ``start_pos[b] + t``.

    The gather re-linearizes each slot's pages into ``[B, pages_per_seq *
    page_size, H_kv, D]`` and defers to :func:`decode_attention`; slots
    past a row's frontier (pool padding, recycled pages) are causally
    masked there, so the pool's contents beyond ``start_pos + T`` are
    never observable. Those slots are additionally **zeroed** before the
    matmuls: the causal mask zeroes their softmax weight, but a recycled
    page can hold non-finite garbage from a poisoned weight generation,
    and IEEE ``0 × NaN = NaN`` would leak it through the ``p @ v``
    contraction (zeroing is exact for finite garbage too — a masked
    position contributes ``0 × 0`` either way, so parity with the
    contiguous path is unchanged). On TPU the gather is a cheap HBM-local
    take (the future Pallas variant fuses it into the attention kernel);
    the semantics here are the contract both share.
    """
    b = q.shape[0]
    k_cache = k_pages[page_table].reshape(
        b, -1, k_pages.shape[2], k_pages.shape[3])
    v_cache = v_pages[page_table].reshape(
        b, -1, v_pages.shape[2], v_pages.shape[3])
    frontier = start_pos + q.shape[1]  # exclusive per-row high-water mark
    live = jnp.arange(k_cache.shape[1])[None, :] < frontier[:, None]
    k_cache = jnp.where(live[..., None, None], k_cache, 0)
    v_cache = jnp.where(live[..., None, None], v_cache, 0)
    return decode_attention(q, k_cache, v_cache, start_pos)


def tp_paged_decode_attention(q, k_pages, v_pages, page_table, start_pos, *,
                              page_size: int, axis: str = "tp", mesh=None):
    """:func:`paged_decode_attention` sharded over a tensor-parallel axis.

    Attention is independent per head, so head-sharding the query and the
    page pool (``q`` on dim 2, ``k_pages``/``v_pages`` on dim 2) makes the
    paged decode embarrassingly parallel: each rank runs the plain kernel
    on its head block and the results concatenate — no collectives, hence
    **token-identical** to the single-chip path. ``page_table`` and
    ``start_pos`` are replicated (every rank walks the same pages).

    Inside a shard_map region over ``axis`` the inputs are already the
    local head shards and this validates + defers. Outside one it wraps
    itself in a shard_map over ``mesh`` (default: the active global mesh)
    with specs ``P(None, None, axis, None)`` for q and the page pools.
    Head counts must divide by the axis size — the serving engine checks
    this once at construction.
    """
    from horovod_tpu.ops.collective import _axis_bound, _axis_size, _smap

    if _axis_bound(axis):
        return paged_decode_attention(
            q, k_pages, v_pages, page_table, start_pos, page_size=page_size)
    if mesh is None:
        from horovod_tpu import basics

        mesh = basics.mesh()
    n = mesh.shape[axis]
    if q.shape[2] % n or k_pages.shape[2] % n:
        raise ValueError(
            f"heads={q.shape[2]} / kv_heads={k_pages.shape[2]} not "
            f"divisible by tp axis size {n}")
    from jax.sharding import PartitionSpec as P

    hsharded = P(None, None, axis, None)
    fn = functools.partial(
        paged_decode_attention, page_size=page_size)
    return _smap(fn, mesh,
                 (hsharded, hsharded, hsharded, P(), P()),
                 hsharded)(q, k_pages, v_pages, page_table, start_pos)


def repeat_kv_heads(q, k, v):
    """Broadcast K/V heads over query groups for GQA/MQA: ``q`` has H
    heads, ``k``/``v`` have H_kv with ``H % H_kv == 0``. Under jit the
    repeat is a broadcast XLA folds into the attention matmuls, so no
    H-wide K/V is materialized in HBM."""
    g = gqa_group(q, k)
    return rep_group(k, g), rep_group(v, g)


def flash_attention(q, k, v, *, causal: bool = False,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    use_pallas: Optional[bool] = None,
                    interpret: bool = False):
    """Memory-efficient attention. ``q``: [B, Tq, H, D]; ``k``/``v``:
    [B, Tk, H_kv, D] with ``H % H_kv == 0`` — grouped-query attention
    (H_kv < H) broadcasts each K/V head over its query group; MQA is
    ``H_kv == 1``. Returns [B, Tq, H, D].

    ``use_pallas`` defaults to True on TPU backends (the VMEM-tiled kernel)
    and False elsewhere (the scan path). Both paths share the blockwise
    lse-recompute backward. GQA is zero-copy end-to-end: the Pallas kernel
    maps each query head's grid row onto its kv head (no H-wide K/V buffer
    exists), residuals save the H_kv-wide K/V, and the scan path's
    per-block broadcast fuses under jit.
    """
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError("q/k/v must be [batch, seq, heads, head_dim]")
    if k.shape != v.shape:
        raise ValueError(f"k/v shape mismatch: {k.shape} vs {v.shape}")
    gqa_group(q, k)  # validate H % H_kv == 0
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    return _flash(q, k, v, causal, sm_scale,
                  (block_q, block_k, use_pallas, interpret))
