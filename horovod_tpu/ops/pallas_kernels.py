"""Pallas kernels for the int8 wire-format hot path, Adasum, and the
fused ZeRO-1 Adam shard update.

The int8 wire (PR 5) saved bytes but paid in HBM round-trips: the HLO
path materializes the quantize's abs/max/scale/cast intermediates, the
post-``all_to_all`` ``[N, sp]`` f32 dequantized matrix, and the reduced
shard between accumulate and requantize — each a full trip through HBM
around a purely memory-bound epilogue. PR 10's bucketing made the unit
of work one ~64 MB bucket chunked into VMEM-sized tiles, so the whole
epilogue now runs on-chip:

- :func:`quantize_blockwise` — max-abs scale per block + int8 cast in
  ONE VMEM pass (the multi-op HLO sequence in
  :func:`horovod_tpu.compression.quantize_blockwise` collapsed);
  :func:`quantize_roundtrip` additionally emits the dequantized wire
  image in the same pass, so error feedback's residual and the
  ``all_to_all`` payload share a single quantize (the HLO path
  quantizes the corrected buffer twice).
- :func:`dequant_accumulate` / :func:`dequant_accumulate_requantize` —
  consume the post-``all_to_all`` int8 chunks + bf16 scales and emit
  the f32 sum shard (reduce-scatter epilogue) or the requantized shard
  (allreduce epilogue) without materializing the ``[N, sp]`` f32
  dequant matrix or round-tripping the reduced shard.
- :func:`adasum_pair_combine` / :func:`adasum_segment_combine` — the
  Adasum combine's three reductions (``a·b``, ``|a|²``, ``|b|²``) out
  of ONE fused read of both operands (the role of the reference's
  ``FusedPairwiseReduceWithComm``), then one blend pass; used by the
  VHDD butterfly at every halving level, grouped path included.
- :func:`fused_adam_update` — Adam moment update + bias correction +
  parameter step in one kernel over the per-bucket ``[N, shard_k]``
  buffers of ``optim._zero_update`` (via :func:`horovod_tpu.optim.
  fused_adam`). The optional ``requant_block`` epilogue additionally
  emits the blockwise-int8 wire image of the update shard in the same
  pass — the hook for a future quantized update-gather leg; today the
  gather stays f32 (the collective schedule is pinned invariant), so
  only the tests exercise it.

Collectives are NEVER issued from a kernel: Pallas replaces the
elementwise HLO *around* ``all_to_all``/``all_gather``/``ppermute``,
so the collective schedule — and the PR-8 fingerprint matrix — is
invariant under ``HOROVOD_PALLAS``.

``HOROVOD_PALLAS`` semantics (read at trace time, so tests can flip it
per-case; the compiled eager-kernel caches key on it):

- ``auto`` (default/unset) — kernels on TPU backends only.
- ``1`` — kernels everywhere; non-TPU backends run them via Pallas
  ``interpret=True``, which executes the same kernel body as jax ops.
  That is the equivalence harness: CPU tier-1 pins the kernels
  bit-identical (quantize) / within pinned tolerances (Adasum) against
  the discrete HLO path without TPU hardware. Interpret mode is a
  correctness surface, NOT a performance mode.
- ``0`` — discrete HLO everywhere (the pre-PR-12 path, bit-for-bit).

Backend resolution for ``auto`` reuses
:func:`horovod_tpu.tuning._target_platform` when no backend exists yet,
so consulting the knob never initializes a backend before
``hvd.tuning.apply_xla_flags`` has run.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = [
    "PALLAS_ENV",
    "enabled",
    "interpret",
    "cache_key",
    "quantize_blockwise",
    "quantize_roundtrip",
    "dequant_accumulate",
    "dequant_accumulate_requantize",
    "adasum_pair_combine",
    "adasum_segment_combine",
    "fused_adam_update",
]

#: env knob: auto (TPU only) | 1 (everywhere, interpret off-TPU) | 0 (off)
#: (documented in docs/performance.md's Pallas knob table)
PALLAS_ENV = "HOROVOD_PALLAS"

#: elements per grid step for flat-vector kernels: one (8, 128) f32 VMEM
#: tile — small enough that a whole (N, chunk) dequant-accumulate block
#: stays resident beside its scales, large enough to amortize the grid
_CHUNK = 1024

#: sublane rows per grid step of the blockwise quantize (8 × block
#: elements per tile, the f32 tile height)
_QROWS = 8

_LANES = 128


def _mode() -> str:
    v = os.environ.get(PALLAS_ENV, "auto").strip().lower()
    if v in ("", "auto"):
        return "auto"
    if v in ("1", "true", "yes", "on"):
        return "1"
    if v in ("0", "false", "no", "off"):
        return "0"
    raise ValueError(
        f"{PALLAS_ENV}={v!r}: expected auto|1|0"
    )


def _platform() -> str:
    """The backend the kernels would compile for — the live backend when
    one exists, else the same resolution ``tuning.apply_xla_flags`` uses
    (consulting the knob must never initialize a backend early)."""
    from horovod_tpu import tuning

    if tuning.backend_initialized():
        return jax.default_backend()
    return tuning._target_platform(os.environ)


def enabled() -> bool:
    """Are the Pallas kernels armed for the next trace? Read from the
    environment at trace time — flipping ``HOROVOD_PALLAS`` between
    steps retraces correctly (the eager-kernel caches key on
    :func:`cache_key`)."""
    m = _mode()
    if m == "0":
        return False
    if m == "1":
        return True
    return _platform() == "tpu"


def interpret() -> bool:
    """Run kernels through the Pallas interpreter? True off-TPU under
    ``HOROVOD_PALLAS=1`` — the CPU equivalence harness."""
    return enabled() and _platform() != "tpu"


def cache_key():
    """(enabled, interpret) — mixed into every compiled eager-kernel
    cache key whose traced body consults the knob, so flipping
    ``HOROVOD_PALLAS`` can never replay a stale compiled program."""
    if _mode() == "0":
        return (False, False)
    return (enabled(), interpret())


def _pl():
    from jax.experimental import pallas as pl

    return pl


def _pad_rows(m, rows: int):
    """Zero-pad the leading axis of a 2-D array to a multiple of ``rows``."""
    pad = (-m.shape[0]) % rows
    if pad:
        m = jnp.concatenate(
            [m, jnp.zeros((pad,) + m.shape[1:], m.dtype)])
    return m


def _pad_tail(flat, multiple: int):
    pad = (-flat.shape[0]) % multiple
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


# --------------------------------------------------------------------------
# blockwise int8 quantize (+ fused wire roundtrip)


def _quantize_kernel(x_ref, q_ref, s_ref, *, roundtrip, d_ref=None):
    """One VMEM pass over (rows, block): max-abs → bf16 scale → int8
    cast, mirroring ``compression.quantize_blockwise`` expression for
    expression so the interpret-mode output is BIT-identical to the HLO
    path (pinned by tests/test_pallas.py)."""
    m = x_ref[...]
    amax = jnp.max(jnp.abs(m), axis=1, keepdims=True)
    sc = (amax / 127.0).astype(jnp.bfloat16)
    s_ref[...] = sc
    sf = sc.astype(m.dtype)
    safe = jnp.where(sf > 0, sf, jnp.ones_like(sf))
    q = jnp.where(sf > 0, m / safe, jnp.zeros_like(m))
    qi = jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8)
    q_ref[...] = qi
    if roundtrip:
        d_ref[...] = qi.astype(m.dtype) * sf


def _quantize_call(flat, block: int, roundtrip: bool):
    pl = _pl()
    L = flat.shape[0]
    nb = L // block
    m = _pad_rows(flat.reshape(nb, block), _QROWS)
    nbp = m.shape[0]
    out_shape = [
        jax.ShapeDtypeStruct((nbp, block), jnp.int8),
        jax.ShapeDtypeStruct((nbp, 1), jnp.bfloat16),
    ]
    out_specs = [
        pl.BlockSpec((_QROWS, block), lambda i: (i, 0)),
        pl.BlockSpec((_QROWS, 1), lambda i: (i, 0)),
    ]
    if roundtrip:
        out_shape.append(jax.ShapeDtypeStruct((nbp, block), flat.dtype))
        out_specs.append(pl.BlockSpec((_QROWS, block), lambda i: (i, 0)))
    kernel = (
        (lambda x, q, s, d: _quantize_kernel(x, q, s, roundtrip=True,
                                             d_ref=d))
        if roundtrip else
        (lambda x, q, s: _quantize_kernel(x, q, s, roundtrip=False))
    )
    out = pl.pallas_call(
        kernel,
        grid=(nbp // _QROWS,),
        in_specs=[pl.BlockSpec((_QROWS, block), lambda i: (i, 0))],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret(),
    )(m)
    q, s = out[0], out[1]
    q = q[:nb].reshape(-1)
    s = s[:nb].reshape(-1)
    if roundtrip:
        return q, s, out[2][:nb].reshape(-1)
    return q, s


def quantize_blockwise(flat, block: int):
    """Fused blockwise int8 quantize of a flat float vector whose length
    is a multiple of ``block``. Returns ``(q int8 [L], scales bf16
    [L/block])`` — bit-identical to the discrete HLO
    ``compression.quantize_blockwise`` (interpret mode pins it)."""
    return _quantize_call(flat, block, roundtrip=False)


def quantize_roundtrip(flat, block: int):
    """Like :func:`quantize_blockwise` but ALSO emits the dequantized
    wire image in the same VMEM pass: ``(q, scales, deq [L])``. One read
    of the corrected gradient buffer serves both the ``all_to_all``
    payload and the error-feedback residual — the HLO path pays two full
    quantize passes for the same pair."""
    return _quantize_call(flat, block, roundtrip=True)


# --------------------------------------------------------------------------
# post-all_to_all epilogues: dequant-accumulate(-requantize)


def _chunk_cols(sp: int, block: int) -> int:
    """Per-grid-step column count: a multiple of ``block`` capped near
    :data:`_CHUNK` (the whole (N, chunk) int8 block + scales must sit in
    VMEM beside the f32 accumulator)."""
    cap = max(_CHUNK // block, 1)
    nb = sp // block
    return min(nb, cap) * block


def _deq_acc_kernel(q_ref, s_ref, o_ref, *, block):
    q = q_ref[...]                                    # (n, chunk) int8
    s = s_ref[...]                                    # (n, cpb) bf16
    n, chunk = q.shape
    d = q.astype(o_ref.dtype).reshape(n, chunk // block, block) \
        * s.astype(o_ref.dtype)[:, :, None]
    o_ref[...] = jnp.sum(d, axis=0).reshape(1, chunk)


def _requant_rows(acc, q_ref, s_ref):
    """Blockwise requantize of the accumulated (cpb, block) rows —
    the same expressions as :func:`_quantize_kernel`."""
    amax = jnp.max(jnp.abs(acc), axis=1, keepdims=True)
    sc = (amax / 127.0).astype(jnp.bfloat16)
    s_ref[...] = sc
    sf = sc.astype(acc.dtype)
    safe = jnp.where(sf > 0, sf, jnp.ones_like(sf))
    q = jnp.where(sf > 0, acc / safe, jnp.zeros_like(acc))
    q_ref[...] = jnp.clip(jnp.round(q), -127, 127).astype(jnp.int8)


def _deq_acc_requant_kernel(q_ref, s_ref, q2_ref, s2_ref, *, block,
                            divisor, dtype):
    q = q_ref[...]
    s = s_ref[...]
    n, chunk = q.shape
    d = q.astype(dtype).reshape(n, chunk // block, block) \
        * s.astype(dtype)[:, :, None]
    acc = jnp.sum(d, axis=0)                           # (cpb, block)
    if divisor is not None:
        acc = acc / jnp.asarray(divisor, dtype=acc.dtype)
    _requant_rows(acc, q2_ref, s2_ref)


def _pad_cols(m, cols: int):
    pad = (-m.shape[1]) % cols
    if pad:
        m = jnp.concatenate(
            [m, jnp.zeros((m.shape[0], pad), m.dtype)], axis=1)
    return m


def dequant_accumulate(qr, scr, dtype, block: int):
    """Fused reduce-scatter epilogue: the post-``all_to_all`` int8
    chunks ``qr [N, sp]`` + bf16 scales ``scr [N, sp/block]`` →
    dequantize, ACCUMULATE over the N senders in ``dtype`` (f32
    widening), emit the summed shard ``[sp]`` — without materializing
    the ``[N, sp]`` dequantized matrix in HBM. Accumulation order
    matches the HLO ``deq.sum(axis=0)`` exactly (interpret mode is
    bit-identical)."""
    pl = _pl()
    n, sp = qr.shape
    chunk = _chunk_cols(sp, block)
    qp = _pad_cols(qr, chunk)
    sp_p = qp.shape[1]
    scp = _pad_cols(scr, chunk // block)
    cpb = chunk // block
    out = pl.pallas_call(
        functools.partial(_deq_acc_kernel, block=block),
        grid=(sp_p // chunk,),
        in_specs=[
            pl.BlockSpec((n, chunk), lambda j: (0, j)),
            pl.BlockSpec((n, cpb), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, chunk), lambda j: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((sp_p // chunk, chunk),
                                       jnp.dtype(dtype)),
        interpret=interpret(),
    )(qp, scp)
    return out.reshape(-1)[:sp]


def dequant_accumulate_requantize(qr, scr, dtype, block: int,
                                  divisor=None):
    """Fused allreduce epilogue: dequantize + accumulate (+ divide by
    ``divisor`` for Average) + blockwise REQUANTIZE in one pass — the
    reduced shard feeds the int8 all-gather leg without a round trip
    through HBM between accumulate and requantize. Returns ``(q2 int8
    [sp], scales2 bf16 [sp/block])``, bit-identical to the discrete
    sum → div → ``quantize_blockwise`` sequence. ``sp`` must be a
    multiple of ``block`` (the allreduce pads to ``N·block``)."""
    pl = _pl()
    n, sp = qr.shape
    chunk = _chunk_cols(sp, block)
    qp = _pad_cols(qr, chunk)
    sp_p = qp.shape[1]
    scp = _pad_cols(scr, chunk // block)
    cpb = chunk // block
    q2, s2 = pl.pallas_call(
        functools.partial(
            _deq_acc_requant_kernel, block=block, divisor=divisor,
            dtype=jnp.dtype(dtype)),
        grid=(sp_p // chunk,),
        in_specs=[
            pl.BlockSpec((n, chunk), lambda j: (0, j)),
            pl.BlockSpec((n, cpb), lambda j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((cpb, block), lambda j: (j, 0)),
            pl.BlockSpec((cpb, 1), lambda j: (j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((sp_p // block, block), jnp.int8),
            jax.ShapeDtypeStruct((sp_p // block, 1), jnp.bfloat16),
        ],
        interpret=interpret(),
    )(qp, scp)
    nb = sp // block
    return q2[:nb].reshape(-1), s2[:nb].reshape(-1)


def _deq_rows_kernel(q_ref, s_ref, o_ref, *, block):
    q = q_ref[...]                                    # (n, chunk) int8
    s = s_ref[...]                                    # (n, cpb) bf16
    n, chunk = q.shape
    o_ref[...] = (
        q.astype(o_ref.dtype).reshape(n, chunk // block, block)
        * s.astype(o_ref.dtype)[:, :, None]
    ).reshape(n, chunk)


def dequantize_rows(qr, scr, dtype, block: int):
    """Fused all-gather epilogue: gathered int8 rows ``qr [N, sp]`` + bf16
    scales ``scr [N, sp/block]`` → per-row dequantized ``[N, sp]`` in
    ``dtype`` — NO accumulation (every row is a different rank's
    parameter shard; contrast :func:`dequant_accumulate`, the
    reduce-scatter epilogue that sums the senders). One VMEM pass per
    column chunk, bit-identical to the discrete HLO
    ``compression.dequantize_rows`` (interpret mode pins it). The ZeRO-3
    int8 parameter gather (``collective.quantized_all_gather``) runs this
    right after its ``all_gather`` pair."""
    pl = _pl()
    n, sp = qr.shape
    chunk = _chunk_cols(sp, block)
    qp = _pad_cols(qr, chunk)
    sp_p = qp.shape[1]
    scp = _pad_cols(scr, chunk // block)
    cpb = chunk // block
    out = pl.pallas_call(
        functools.partial(_deq_rows_kernel, block=block),
        grid=(sp_p // chunk,),
        in_specs=[
            pl.BlockSpec((n, chunk), lambda j: (0, j)),
            pl.BlockSpec((n, cpb), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((n, chunk), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((n, sp_p), jnp.dtype(dtype)),
        interpret=interpret(),
    )(qp, scp)
    return out[:, :sp]


# --------------------------------------------------------------------------
# Adasum pairwise combine (single-tensor + segmented group form)


def _pair_reduce_kernel(a_ref, b_ref, o_ref):
    """Per-chunk lane-wise partials of ``a·b``, ``|a|²``, ``|b|²`` out
    of ONE read of both operands, accumulated across the grid into one
    (8, 128) block (rows 0..2 carry the three reductions)."""
    pl = _pl()
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    a = a_ref[...].astype(jnp.float32).reshape(-1, _LANES)
    b = b_ref[...].astype(jnp.float32).reshape(-1, _LANES)
    upd = jnp.concatenate([
        jnp.sum(a * b, axis=0)[None],
        jnp.sum(a * a, axis=0)[None],
        jnp.sum(b * b, axis=0)[None],
        jnp.zeros((5, _LANES), jnp.float32),
    ], axis=0)
    o_ref[...] = o_ref[...] + upd


def _blend_kernel(a_ref, b_ref, ca_ref, cb_ref, o_ref):
    ca = ca_ref[0, 0]
    cb = cb_ref[0, 0]
    o_ref[...] = (ca * a_ref[...].astype(jnp.float32)
                  + cb * b_ref[...].astype(jnp.float32))


def _as_chunks(flat, chunk: int):
    return _pad_tail(flat, chunk).reshape(-1, chunk)


def adasum_pair_combine(a, b):
    """One Adasum pairwise combine (``ops/adasum.py::_pair_combine``)
    as two fused VMEM passes: pass 1 reads ``a``/``b`` ONCE for all
    three scalar reductions (the discrete path reads each operand three
    times), pass 2 applies the scaled blend. The chunked partial
    reduction changes the f32 summation order vs ``jnp.vdot``, so
    equivalence is pinned to tolerance, not bits."""
    pl = _pl()
    shape, dtype = a.shape, a.dtype
    af = a.reshape(-1)
    bf = b.reshape(-1)
    L = af.shape[0]
    a2 = _as_chunks(af, _CHUNK)
    b2 = _as_chunks(bf, _CHUNK)
    nc = a2.shape[0]
    part = pl.pallas_call(
        _pair_reduce_kernel,
        grid=(nc,),
        in_specs=[
            pl.BlockSpec((1, _CHUNK), lambda i: (i, 0)),
            pl.BlockSpec((1, _CHUNK), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, _LANES), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, _LANES), jnp.float32),
        interpret=interpret(),
    )(a2, b2)
    dot = jnp.sum(part[0])
    na = jnp.sum(part[1])
    nb = jnp.sum(part[2])
    ca = jnp.where(na == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(na, 1e-30)))
    cb = jnp.where(nb == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(nb, 1e-30)))
    out = pl.pallas_call(
        _blend_kernel,
        grid=(nc,),
        in_specs=[
            pl.BlockSpec((1, _CHUNK), lambda i: (i, 0)),
            pl.BlockSpec((1, _CHUNK), lambda i: (i, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _CHUNK), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nc, _CHUNK), jnp.float32),
        interpret=interpret(),
    )(a2, b2, ca.reshape(1, 1), cb.reshape(1, 1))
    return out.reshape(-1)[:L].reshape(shape).astype(dtype)


def _seg_reduce_kernel(a_ref, b_ref, seg_ref, o_ref):
    """Segmented variant of :func:`_pair_reduce_kernel`: the three
    products contract against an in-register one-hot segment matrix on
    the MXU, yielding per-SEGMENT partials — all tensors of a fused
    Adasum group reduced in one read of the group buffer."""
    pl = _pl()
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        o_ref[...] = jnp.zeros_like(o_ref)

    a = a_ref[...].astype(jnp.float32)                 # (1, chunk)
    b = b_ref[...].astype(jnp.float32)
    seg = seg_ref[...]                                 # (1, chunk) int32
    nsp = o_ref.shape[1]
    onehot = (
        lax.broadcasted_iota(jnp.int32, (nsp, a.shape[1]), 0) == seg
    ).astype(jnp.float32)
    prods = jnp.concatenate([
        a * b, a * a, b * b,
        jnp.zeros((5, a.shape[1]), jnp.float32),
    ], axis=0)                                         # (8, chunk)
    part = lax.dot_general(
        prods, onehot, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # (8, nsp)
    o_ref[...] = o_ref[...] + part


def _seg_blend_kernel(a_ref, b_ref, ca_ref, cb_ref, o_ref):
    o_ref[...] = ca_ref[...] * a_ref[...] + cb_ref[...] * b_ref[...]


def adasum_segment_combine(a, b, seg_ids, n_segments: int):
    """Per-tensor Adasum combine over a concatenated flat f32 group
    buffer (``ops/adasum.py::_segment_combine``): per-segment
    ``dot``/``na``/``nb`` partials come out of ONE fused read of
    ``a``/``b`` (pass 1), the per-segment blend out of a second
    (pass 2). The flat layout — and therefore the butterfly's
    ``ppermute`` signature — is untouched; padding happens inside the
    kernel wrappers only."""
    pl = _pl()
    L = a.shape[0]
    a2 = _as_chunks(a, _CHUNK)
    b2 = _as_chunks(b, _CHUNK)
    # ghost id n_segments marks the zero-pad tail; it matches no one-hot
    # row (nsp > n_segments) or contributes only to a sliced-off row
    seg_p = jnp.concatenate([
        seg_ids.astype(jnp.int32),
        jnp.full(((-L) % _CHUNK,), n_segments, jnp.int32),
    ]) if L % _CHUNK else seg_ids.astype(jnp.int32)
    s2 = seg_p.reshape(-1, _CHUNK)
    nc = a2.shape[0]
    nsp = -(-max(n_segments + 1, 2) // _LANES) * _LANES
    part = pl.pallas_call(
        _seg_reduce_kernel,
        grid=(nc,),
        in_specs=[
            pl.BlockSpec((1, _CHUNK), lambda i: (i, 0)),
            pl.BlockSpec((1, _CHUNK), lambda i: (i, 0)),
            pl.BlockSpec((1, _CHUNK), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, nsp), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, nsp), jnp.float32),
        interpret=interpret(),
    )(a2, b2, s2)
    dot = part[0, :n_segments]
    na = part[1, :n_segments]
    nb = part[2, :n_segments]
    ca = jnp.where(na == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(na, 1e-30)))
    cb = jnp.where(nb == 0, 0.0, 1.0 - dot / (2.0 * jnp.maximum(nb, 1e-30)))
    # per-element coefficients: one gather (the same gather the discrete
    # path's ca[seg_ids] performs), fed chunk-wise into the blend pass
    ca_e = jnp.concatenate([ca, jnp.zeros((1,), jnp.float32)])[seg_p]
    cb_e = jnp.concatenate([cb, jnp.zeros((1,), jnp.float32)])[seg_p]
    out = pl.pallas_call(
        _seg_blend_kernel,
        grid=(nc,),
        in_specs=[
            pl.BlockSpec((1, _CHUNK), lambda i: (i, 0)),
            pl.BlockSpec((1, _CHUNK), lambda i: (i, 0)),
            pl.BlockSpec((1, _CHUNK), lambda i: (i, 0)),
            pl.BlockSpec((1, _CHUNK), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, _CHUNK), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((nc, _CHUNK), jnp.float32),
        interpret=interpret(),
    )(a2, b2, ca_e.reshape(-1, _CHUNK), cb_e.reshape(-1, _CHUNK))
    return out.reshape(-1)[:L]


# --------------------------------------------------------------------------
# fused Adam shard update (ZeRO-1 per-bucket [N, shard_k] buffers)


def _adam_kernel(g_ref, mu_ref, nu_ref, c_ref, u_ref, mu2_ref, nu2_ref,
                 *, b1, b2, eps, eps_root, neg_lr, requant, block,
                 q_ref=None, s_ref=None):
    """Adam moment update + bias correction + parameter step in one VMEM
    pass, expression-for-expression the optax ``scale_by_adam`` +
    ``scale(-lr)`` chain so interpret mode is bit-identical to the
    discrete path. ``c_ref`` carries the two traced bias-correction
    scalars (they depend on the step count). With ``requant`` the update
    chunk is additionally blockwise-int8 quantized in the same pass —
    the wire image of the update shard when compression is on."""
    g = g_ref[...]
    mu = mu_ref[...]
    nu = nu_ref[...]
    b1c = c_ref[0, 0]
    b2c = c_ref[0, 1]
    mu2 = (1 - b1) * g + b1 * mu
    nu2 = (1 - b2) * (g * g) + b2 * nu
    mu2_ref[...] = mu2
    nu2_ref[...] = nu2
    u = neg_lr * ((mu2 / b1c) / (jnp.sqrt(nu2 / b2c + eps_root) + eps))
    u_ref[...] = u
    if requant:
        _requant_rows(u.reshape(-1, block), q_ref, s_ref)


def fused_adam_update(g, mu, nu, b1c, b2c, *, lr, b1, b2, eps,
                      eps_root=0.0, requant_block=None):
    """One fused Adam step over a flat shard: returns ``(update, mu',
    nu')`` — bit-identical to optax's ``scale_by_adam`` →
    ``scale(-lr)`` chain — and, with ``requant_block``, additionally
    ``(q, scales)``: the blockwise-int8 wire image of the update shard
    emitted by the same pass. No production path consumes the epilogue
    yet — the ZeRO-1 update gather stays f32 so the pinned collective
    schedule cannot move; it is the (tested) hook for a future int8
    gather leg. ``b1c``/``b2c`` are the traced bias corrections
    ``1 - b**count`` (they ride a tiny (1, 2) buffer into the kernel).

    Works on any 1-D shard (zero-padded to the chunk internally) and
    under ``jax.vmap`` — the form ``optim._zero_update`` applies over
    the per-bucket ``[N, shard_k]`` state buffers."""
    pl = _pl()
    L = g.shape[0]
    chunk = _CHUNK if requant_block is None else \
        max(_CHUNK // requant_block, 1) * requant_block
    g2 = _as_chunks(g, chunk)
    mu2 = _as_chunks(mu, chunk)
    nu2 = _as_chunks(nu, chunk)
    nc = g2.shape[0]
    c = jnp.stack([b1c, b2c]).astype(g.dtype).reshape(1, 2)
    out_specs = [
        pl.BlockSpec((1, chunk), lambda i: (i, 0)),
        pl.BlockSpec((1, chunk), lambda i: (i, 0)),
        pl.BlockSpec((1, chunk), lambda i: (i, 0)),
    ]
    out_shape = [jax.ShapeDtypeStruct((nc, chunk), g.dtype)] * 3
    if requant_block is not None:
        cpb = chunk // requant_block
        out_specs += [
            pl.BlockSpec((cpb, requant_block), lambda i: (i, 0)),
            pl.BlockSpec((cpb, 1), lambda i: (i, 0)),
        ]
        out_shape += [
            jax.ShapeDtypeStruct((nc * cpb, requant_block), jnp.int8),
            jax.ShapeDtypeStruct((nc * cpb, 1), jnp.bfloat16),
        ]

    def kernel(g_r, mu_r, nu_r, c_r, u_r, m2_r, n2_r, *extra):
        _adam_kernel(
            g_r, mu_r, nu_r, c_r, u_r, m2_r, n2_r,
            b1=b1, b2=b2, eps=eps, eps_root=eps_root, neg_lr=-lr,
            requant=requant_block is not None,
            block=requant_block or 0,
            q_ref=extra[0] if extra else None,
            s_ref=extra[1] if extra else None,
        )

    out = pl.pallas_call(
        kernel,
        grid=(nc,),
        in_specs=[
            pl.BlockSpec((1, chunk), lambda i: (i, 0)),
            pl.BlockSpec((1, chunk), lambda i: (i, 0)),
            pl.BlockSpec((1, chunk), lambda i: (i, 0)),
            pl.BlockSpec((1, 2), lambda i: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret(),
    )(g2, mu2, nu2, c)
    u, mo, no = (o.reshape(-1)[:L] for o in out[:3])
    if requant_block is None:
        return u, mo, no
    lq = -(-L // requant_block) * requant_block
    q = out[3].reshape(-1)[:lq]
    s = out[4].reshape(-1)[:lq // requant_block]
    return u, mo, no, (q, s)
