"""XLA flag tuning for comm/compute overlap.

The bucketed gradient sync (:mod:`horovod_tpu.ops.overlap`) makes each
bucket's collective *schedulable* inside the backward pass — whether it
actually overlaps is XLA's call. On TPU two compiler features do the
work: **async collective fusion** (collectives split into start/done
pairs that run on the DMA engines while the TensorCore keeps computing)
and the **latency-hiding scheduler** (hoists the starts as early as
their operands allow and sinks the dones as late as their consumers
allow). Both are controlled by ``XLA_FLAGS``, which XLA reads ONCE at
backend initialization — so the knobs must land in the environment
before the first ``jax`` device touch.

:func:`apply_xla_flags` appends the preset idempotently and never
clobbers a flag the user already set (their value wins, even when it
disagrees with the preset). ``HOROVOD_XLA_FLAGS_PRESET=<preset>`` makes
``hvd.init`` apply it automatically before backend init. TPU-prefixed
flags are a hard parse error on non-TPU jaxlibs (``Unknown flags in
XLA_FLAGS`` is *fatal*), so application is gated on the resolved target
platform — on a CPU host the call is a recorded no-op, never a crash.
"""

from __future__ import annotations

import importlib.util
import logging
import os
import sys
import warnings
from typing import List, Optional, Tuple

__all__ = [
    "PRESETS",
    "PRESET_ENV",
    "apply_xla_flags",
    "maybe_apply_from_env",
    "backend_initialized",
]

log = logging.getLogger("horovod_tpu")

#: env knob: name of the preset ``hvd.init`` applies before backend init
#: (documented in docs/performance.md's overlap knob table)
PRESET_ENV = "HOROVOD_XLA_FLAGS_PRESET"

#: preset name -> tuple of (flag, platform) pairs. ``platform`` names the
#: backend the flag exists on; flags for other platforms are skipped (a
#: TPU-only flag in XLA_FLAGS is FATAL on a CPU jaxlib).
#: the comm/compute-overlap flag set: async start/done collectives + the
#: latency-hiding scheduler that pins the overlapped schedule
_OVERLAP_FLAGS = (
    ("--xla_tpu_enable_async_collective_fusion=true", "tpu"),
    ("--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
     "tpu"),
    ("--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
     "tpu"),
    ("--xla_tpu_enable_latency_hiding_scheduler=true", "tpu"),
)

PRESETS = {
    "overlap": _OVERLAP_FLAGS,
    # the HOROVOD_PALLAS companion: a Pallas kernel is an opaque custom
    # call to XLA's scheduler — without async collectives + the
    # latency-hiding scheduler, a custom call adjacent to a collective
    # SERIALIZES against it, giving back the overlap PR 10 bought. The
    # flag set is therefore exactly the overlap set (no Pallas-specific
    # XLA flags exist to arm); the separate name records intent and
    # keeps the knob table honest. Backend resolution is shared the
    # other way too: pallas_kernels' `auto` mode resolves the target
    # platform through this module's `_target_platform`, so consulting
    # HOROVOD_PALLAS never initializes a backend before these flags land.
    "pallas": _OVERLAP_FLAGS,
    # explicit opt-out spelling for HOROVOD_XLA_FLAGS_PRESET
    "none": (),
}


def backend_initialized() -> bool:
    """Best-effort: has a jax backend already been created (meaning
    XLA_FLAGS edits no longer take effect in this process)?"""
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import xla_bridge

        return bool(getattr(xla_bridge, "_backends", None))
    except (ImportError, AttributeError):
        return False


def _target_platform(env) -> str:
    """The platform the next backend init will target: the explicit
    ``JAX_PLATFORMS``/``JAX_PLATFORM_NAME`` pin when present, else
    ``tpu`` iff a TPU runtime (libtpu) is importable."""
    pins = env.get("JAX_PLATFORMS") or env.get("JAX_PLATFORM_NAME") or ""
    if pins:
        return pins.split(",")[0].strip().lower()
    try:
        has_tpu = importlib.util.find_spec("libtpu") is not None
    except (ImportError, ValueError):
        has_tpu = False
    return "tpu" if has_tpu else "cpu"


def _flag_name(flag: str) -> str:
    return flag.split("=", 1)[0]


def apply_xla_flags(preset: Optional[str] = None, *, env=None,
                    platform: Optional[str] = None,
                    warn_if_late: bool = True
                    ) -> Tuple[List[str], List[str]]:
    """Append the preset's flags to ``XLA_FLAGS`` idempotently.

    Returns ``(added, skipped)``: flags appended now, and flags withheld
    because the user already set that flag name (their value wins) or
    the flag's platform does not match the resolved target. Calling
    twice adds nothing the second time. With ``warn_if_late`` a warning
    fires when a flag is added after a backend already initialized —
    the edit then only helps subprocesses.
    """
    env = os.environ if env is None else env
    if preset is None:
        preset = env.get(PRESET_ENV) or "overlap"
    if preset not in PRESETS:
        raise ValueError(
            f"unknown XLA flags preset {preset!r}; known: "
            f"{sorted(PRESETS)}"
        )
    platform = (platform or _target_platform(env)).lower()
    current = env.get("XLA_FLAGS", "")
    present = {_flag_name(t) for t in current.split() if t}
    added: List[str] = []
    skipped: List[str] = []
    for flag, flag_platform in PRESETS[preset]:
        if _flag_name(flag) in present:
            skipped.append(flag)      # user-set value wins, always
        elif flag_platform != platform:
            skipped.append(flag)      # TPU-only flag on a CPU jaxlib is
            # a fatal parse error, not a no-op — withhold it
        else:
            added.append(flag)
    if added:
        env["XLA_FLAGS"] = " ".join(([current] if current else []) + added)
        if warn_if_late and env is os.environ and backend_initialized():
            warnings.warn(
                "horovod_tpu.tuning.apply_xla_flags ran after a jax "
                "backend initialized; XLA_FLAGS is read once at backend "
                "init, so the overlap flags only affect subprocesses. "
                "Set HOROVOD_XLA_FLAGS_PRESET=overlap (or call "
                "apply_xla_flags) before the first device touch.",
                RuntimeWarning,
                stacklevel=2,
            )
    if skipped:
        log.debug("tuning: withheld XLA flags %s (user-set or platform "
                  "mismatch for %r)", skipped, platform)
    return added, skipped


def maybe_apply_from_env(env=None) -> Tuple[List[str], List[str]]:
    """Apply the ``HOROVOD_XLA_FLAGS_PRESET`` preset when the knob is
    set; no-op otherwise. ``hvd.init`` calls this before its first
    backend touch, so the env knob alone is enough to arm the overlap
    flags on every entry point (launcher children included — the env
    rides through)."""
    env = os.environ if env is None else env
    if not env.get(PRESET_ENV):
        return [], []
    return apply_xla_flags(env.get(PRESET_ENV), env=env)
