"""Mesh construction and parallelism strategies (DP/TP/PP/SP/EP).

The reference framework is data-parallel only (SURVEY.md §2.7); the mesh layer
here is deliberately more general so the same collective surface extends to
tensor/pipeline/sequence/expert axes, the TPU-idiomatic way
(``jax.sharding.Mesh`` + ``shard_map``/``pjit``).
"""

from horovod_tpu.parallel.mesh import (  # noqa: F401
    DATA_AXIS,
    MODEL_AXIS,
    PIPELINE_AXIS,
    SEQUENCE_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    TP_AXIS,
    CROSS_AXIS,
    LOCAL_AXIS,
    MeshConfig,
    build_mesh,
    build_host_mesh,
)
from horovod_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ulysses_attention,
    zigzag_permutation,
    zigzag_ring_attention,
)
from horovod_tpu.parallel.pipeline import (  # noqa: F401
    make_interleaved_stage_params,
    make_stage_params,
    pipeline_apply,
    pipeline_apply_interleaved,
)
from horovod_tpu.parallel.moe import (  # noqa: F401
    expert_parallel_moe,
    top1_dispatch,
    top2_dispatch,
)
