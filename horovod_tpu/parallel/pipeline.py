"""Pipeline parallelism over the ``pipe`` mesh axis.

No reference counterpart (Horovod 0.19.2 is data-parallel only — SURVEY.md
§2.7); this is the TPU-native extension filling the ``pipe`` axis the mesh
layer reserves. GPipe-style schedule expressed as a ``lax.scan`` inside
``shard_map``:

- each pipe-mesh position holds ONE stage's parameters (pytree stacked on a
  leading ``[n_stages, ...]`` axis, sharded over ``pipe``);
- microbatches enter at stage 0; every tick each stage applies itself to its
  current activation and hands the result to the next stage via
  ``lax.ppermute`` (a single ICI hop — neighbors on the torus);
- after ``n_micro + n_stages - 1`` ticks the last stage has produced every
  microbatch's output. The scan is differentiable: reverse-mode turns the
  forward shift into the backward shift automatically, giving the 1F1B-ish
  backward schedule without hand-writing it.

The bubble fraction is the usual ``(S-1)/(M+S-1)``; raise ``n_micro`` to
amortize. Collective cost per tick is one neighbor ppermute of a microbatch
activation — bandwidth-optimal for ICI.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.mesh import PIPELINE_AXIS


def pipeline_apply(stage_fn: Callable, stage_params, x_micro, *,
                   axis_name: str = PIPELINE_AXIS):
    """Run microbatches through the stage pipeline.

    Inside ``shard_map`` over ``axis_name``:

    Args:
      stage_fn: ``(params_for_one_stage, activation) -> activation``; applied
        by every device to its local stage.
      stage_params: local stage's params (the caller shards a
        ``[n_stages, ...]``-stacked tree over ``axis_name``; shard_map hands
        each device its ``[1, ...]`` slice — pass it with the leading axis
        squeezed via ``jax.tree.map(lambda p: p[0], ...)``).
      x_micro: ``[n_micro, mb, ...]`` microbatched input, replicated across
        the pipe axis (only stage 0 reads it).

    Returns:
      ``[n_micro, mb, ...]`` outputs, valid on the LAST stage and zero
      elsewhere; ``psum`` over ``axis_name`` (or read the last-stage shard)
      yields the result everywhere.
    """
    n_stages = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    mb_shape = x_micro.shape[1:]
    n_ticks = n_micro + n_stages - 1

    shift = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        acts = carry  # activation entering this stage this tick
        # stage 0 ingests microbatch t (clamped; masked out when t >= n_micro)
        feed = x_micro[jnp.minimum(t, n_micro - 1)]
        acts = jnp.where(idx == 0, feed, acts)
        out = stage_fn(stage_params, acts)
        # last stage emits; everyone shifts to the next stage
        nxt = lax.ppermute(out, axis_name, shift)
        return nxt, out

    init = jnp.zeros(mb_shape, x_micro.dtype)
    _, outs = lax.scan(tick, init, jnp.arange(n_ticks))

    # outs: [n_ticks, mb, ...]; the last stage produced microbatch m at tick
    # m + n_stages - 1. Gather those, zero elsewhere so a psum finalizes.
    take = outs[n_stages - 1:]
    is_last = (idx == n_stages - 1)
    return jnp.where(is_last, take, jnp.zeros_like(take))


def make_stage_params(params_list):
    """Stack per-stage param pytrees into one ``[n_stages, ...]`` tree
    (shard it over the pipe axis with ``P('pipe', ...)`` specs)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *params_list
    )
