"""Pipeline parallelism over the ``pipe`` mesh axis.

No reference counterpart (Horovod 0.19.2 is data-parallel only — SURVEY.md
§2.7); this is the TPU-native extension filling the ``pipe`` axis the mesh
layer reserves. GPipe-style schedule expressed as a ``lax.scan`` inside
``shard_map``:

- each pipe-mesh position holds ONE stage's parameters (pytree stacked on a
  leading ``[n_stages, ...]`` axis, sharded over ``pipe``);
- microbatches enter at stage 0; every tick each stage applies itself to its
  current activation and hands the result to the next stage via
  ``lax.ppermute`` (a single ICI hop — neighbors on the torus);
- after ``n_micro + n_stages - 1`` ticks the last stage has produced every
  microbatch's output. The scan is differentiable: reverse-mode turns the
  forward shift into the backward shift automatically, giving the 1F1B-ish
  backward schedule without hand-writing it.

The bubble fraction is the usual ``(S-1)/(M+S-1)``; raise ``n_micro`` to
amortize. Collective cost per tick is one neighbor ppermute of a microbatch
activation — bandwidth-optimal for ICI.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.mesh import PIPELINE_AXIS


def pipeline_apply(stage_fn: Callable, stage_params, x_micro, *,
                   axis_name: str = PIPELINE_AXIS):
    """Run microbatches through the stage pipeline.

    Inside ``shard_map`` over ``axis_name``:

    Args:
      stage_fn: ``(params_for_one_stage, activation) -> activation``; applied
        by every device to its local stage.
      stage_params: local stage's params (the caller shards a
        ``[n_stages, ...]``-stacked tree over ``axis_name``; shard_map hands
        each device its ``[1, ...]`` slice — pass it with the leading axis
        squeezed via ``jax.tree.map(lambda p: p[0], ...)``).
      x_micro: ``[n_micro, mb, ...]`` microbatched input, replicated across
        the pipe axis (only stage 0 reads it).

    Returns:
      ``[n_micro, mb, ...]`` outputs, valid on the LAST stage and zero
      elsewhere; ``psum`` over ``axis_name`` (or read the last-stage shard)
      yields the result everywhere.
    """
    n_stages = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    mb_shape = x_micro.shape[1:]
    n_ticks = n_micro + n_stages - 1

    shift = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        acts = carry  # activation entering this stage this tick
        # stage 0 ingests microbatch t (clamped; masked out when t >= n_micro)
        feed = x_micro[jnp.minimum(t, n_micro - 1)]
        acts = jnp.where(idx == 0, feed, acts)
        out = stage_fn(stage_params, acts)
        # last stage emits; everyone shifts to the next stage
        nxt = lax.ppermute(out, axis_name, shift)
        return nxt, out

    init = jnp.zeros(mb_shape, x_micro.dtype)
    _, outs = lax.scan(tick, init, jnp.arange(n_ticks))

    # outs: [n_ticks, mb, ...]; the last stage produced microbatch m at tick
    # m + n_stages - 1. Gather those, zero elsewhere so a psum finalizes.
    take = outs[n_stages - 1:]
    is_last = (idx == n_stages - 1)
    return jnp.where(is_last, take, jnp.zeros_like(take))


def make_stage_params(params_list):
    """Stack per-stage param pytrees into one ``[n_stages, ...]`` tree
    (shard it over the pipe axis with ``P('pipe', ...)`` specs)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *params_list
    )


def make_interleaved_stage_params(params_list, n_devices: int):
    """Stack ``L = n_devices * v`` per-stage pytrees for the interleaved
    schedule: stage ``k`` lives on device ``k % n_devices`` at wrap level
    ``k // n_devices`` (megatron-style round-robin layout). Returns a
    ``[n_devices, v, ...]`` tree — shard dim 0 over the pipe axis; each
    device then holds its ``[v, ...]`` local stack."""
    L = len(params_list)
    if L % n_devices != 0:
        raise ValueError(
            f"interleaved pipeline needs stages ({L}) divisible by devices "
            f"({n_devices})"
        )
    v = L // n_devices
    by_device = [
        [params_list[w * n_devices + d] for w in range(v)]
        for d in range(n_devices)
    ]
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves).reshape(
            (n_devices, v) + leaves[0].shape
        ),
        *[p for dev in by_device for p in dev],
    )


def pipeline_apply_interleaved(stage_fn: Callable, stage_params, x_micro, *,
                               axis_name: str = PIPELINE_AXIS):
    """Interleaved (circular) pipeline: each device holds ``v`` non-adjacent
    stages and activations loop ``v`` times around the ring.

    With ``L = S*v`` total stages on ``S`` devices, the bubble fraction is
    ``(S-1)/(M*v + S-1)`` — vs ``(S-1)/(M + S-1)`` *of v×-longer ticks* for
    the same layers stacked depth-first on a GPipe schedule (the
    megatron-style interleaving win). Every tick is still exactly one
    neighbor ``ppermute``, so the collective cost per tick is unchanged.

    Scheduling is drain-first: each device holds ONE in-flight activation
    (a register is sufficient — a device receives at most one activation per
    tick and always consumes a valid one the same tick, so occupancy never
    exceeds 1) and prefers wrapped work over injecting a fresh microbatch,
    which reproduces the optimal ``M*v + S - 1`` make-span greedily without
    a precomputed timetable. The whole schedule is one ``lax.scan``, so
    reverse-mode autodiff yields the mirrored backward schedule for free.

    Args:
      stage_fn: ``(params_for_one_stage, activation) -> activation``.
      stage_params: this device's ``[v, ...]`` stacked local stages (from
        :func:`make_interleaved_stage_params` sharded over ``axis_name`` and
        squeezed of the device axis).
      x_micro: ``[n_micro, mb, ...]`` microbatches, replicated over the axis.

    Returns:
      ``[n_micro, mb, ...]`` outputs, valid on the last device and zero
      elsewhere (``psum`` over ``axis_name`` finalizes, as with
      :func:`pipeline_apply`).
    """
    S = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    v = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    L = S * v
    M = x_micro.shape[0]
    mb_shape = x_micro.shape[1:]
    n_ticks = M * v + L  # ≥ greedy make-span (M*v + S - 1), slack is idle

    shift = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, _):
        slot, meta, injected = carry  # meta: [3] int32 = (valid, wrap, mb)
        held = meta[0] > 0
        can_inject = (idx == 0) & (~held) & (injected < M)
        feed = x_micro[jnp.minimum(injected, M - 1)]
        act = jnp.where(held, slot, feed)
        w = jnp.where(held, meta[1], 0)
        mb = jnp.where(held, meta[2], injected)
        active = held | can_inject

        params_w = jax.tree_util.tree_map(
            lambda p: lax.dynamic_index_in_dim(p, w, 0, keepdims=False),
            stage_params,
        )
        out = stage_fn(params_w, act)

        gstage = w * S + idx
        final = active & (gstage == L - 1)
        send_valid = active & ~final
        send_w = w + jnp.where(idx == S - 1, 1, 0)
        recv_act = lax.ppermute(out, axis_name, shift)
        recv_meta = lax.ppermute(
            jnp.stack(
                [send_valid.astype(jnp.int32), send_w, mb]
            ).astype(jnp.int32),
            axis_name,
            shift,
        )

        # a valid slot is always consumed this tick, so the next slot is
        # simply whatever arrived (or empty)
        rv = recv_meta[0] > 0
        next_slot = jnp.where(rv, recv_act, jnp.zeros_like(recv_act))
        next_meta = jnp.where(rv, recv_meta, jnp.zeros((3,), jnp.int32))
        injected2 = injected + can_inject.astype(jnp.int32)
        return (next_slot, next_meta, injected2), (out, mb, final)

    init = (
        jnp.zeros(mb_shape, x_micro.dtype),
        jnp.zeros((3,), jnp.int32),
        jnp.asarray(0, jnp.int32),
    )
    _, (outs, mbs, finals) = lax.scan(tick, init, None, length=n_ticks)

    # scatter completed microbatches into position; non-final ticks add zeros
    mask = finals.reshape((n_ticks,) + (1,) * len(mb_shape))
    contrib = jnp.where(mask, outs, jnp.zeros_like(outs))
    return (
        jnp.zeros((M,) + mb_shape, x_micro.dtype).at[mbs].add(contrib)
    )
