"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

Long-context capability has no counterpart in the reference (Horovod 0.19.2
shards only the batch axis — SURVEY.md §5.7); this is the TPU-native
extension the mesh layer (:mod:`horovod_tpu.parallel.mesh`) reserves the
``seq`` axis for. Two strategies:

- :func:`ring_attention` — blockwise attention with K/V blocks rotating
  around the ring via ``lax.ppermute`` (Liu et al., "Ring Attention with
  Blockwise Transformers"). Each device holds ``T/n`` of the sequence; per
  ring step it attends its local queries against the visiting K/V block and
  folds the result into online-softmax accumulators. ICI neighbor exchange
  overlaps with the block matmuls (XLA schedules the ppermute concurrently
  with compute), so the collective cost hides behind the MXU work.
- :func:`ulysses_attention` — DeepSpeed-Ulysses-style all-to-all: re-shard
  from sequence-sharded to head-sharded with ``lax.all_to_all``, run plain
  (flash) attention on full-length sequences per head group, and all-to-all
  back. Cheaper at moderate context (2 all-to-alls vs n-1 permutes) but
  requires ``heads % axis_size == 0``.

Both are pure functions of per-shard values, designed to be called inside
``shard_map``/``pjit`` over a mesh built by
:func:`horovod_tpu.parallel.mesh.build_mesh`.

**Backward** is hand-written (``jax.custom_vjp``) as a second ring pass: the
forward saves only the output and the log-sum-exp rows (O(T/n) per device);
the backward re-rotates K/V around the ring together with their gradient
accumulators, recomputing each block's probabilities from lse
(:func:`horovod_tpu.ops.flash_attention._block_bwd`). Autodiff through the
forward scan would instead checkpoint every visiting block's score matrix —
O(T²/n) per device — which is exactly what ring attention exists to avoid.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.ops.flash_attention import (
    NEG_INF,
    _attention_scan,
    _block_bwd,
    _delta,
    _finalize,
    gqa_group,
    lse_from_state,
    reduce_group,
    rep_group,
)
from horovod_tpu.parallel.mesh import SEQUENCE_AXIS


def _ring_perm(n):
    return [(i, (i + 1) % n) for i in range(n)]


def _merge_state(state, new):
    """Merge two online-softmax partial states; a fully-masked partial has
    m == NEG_INF and is suppressed by a zero weight."""
    m, l, acc = state
    m2, l2, acc2 = new
    m_new = jnp.maximum(m, m2)
    a1 = jnp.exp(m - m_new)
    a2 = jnp.where(m2 > NEG_INF / 2, jnp.exp(m2 - m_new), 0.0)
    return (
        m_new,
        l * a1 + l2 * a2,
        acc * a1[..., None] + acc2 * a2[..., None],
    )


def _ring_fwd_impl(q, k, v, axis_name, causal, sm_scale, block_k):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    t_kv = k.shape[1]
    # GQA: the ring rotates the SMALL (H_kv-head) K/V bundle — the
    # per-fold repeat is a broadcast XLA fuses into the block matmuls, so
    # the ppermute bytes shrink by the group factor
    g = gqa_group(q, k)
    q_offset = my * t_q
    perm = _ring_perm(n)

    def fold(state, kv_src, k_blk, v_blk):
        def merge(state):
            if causal:
                new = _attention_scan(
                    q, rep_group(k_blk, g), rep_group(v_blk, g), causal=True,
                    sm_scale=sm_scale,
                    q_offset=q_offset, kv_offset=kv_src * t_kv,
                    block_k=block_k)
            else:
                new = _attention_scan(
                    q, rep_group(k_blk, g), rep_group(v_blk, g), causal=False,
                    sm_scale=sm_scale,
                    q_offset=0, kv_offset=0, block_k=block_k)
            return _merge_state(state, new)

        if not causal:
            return merge(state)
        # skip the FLOPs of blocks entirely in the causal future (the ring's
        # built-in imbalance: early-position devices skip most steps)
        visible = kv_src * t_kv <= q_offset + t_q - 1
        return lax.cond(visible, merge, lambda s: s, state)

    def ring_step(carry, _):
        state, k_blk, v_blk, src = carry
        state = fold(state, src, k_blk, v_blk)
        # rotate: each device hands its current block to the next neighbor,
        # so after n-1 steps every device has seen every block (ICI ring)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = lax.ppermute(src, axis_name, perm)
        return (state, k_blk, v_blk, src), None

    m0 = jnp.full((b, h, t_q), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_q), jnp.float32)
    acc0 = jnp.zeros((b, h, t_q, d), jnp.float32)
    (state, _, _, _), _ = lax.scan(
        ring_step, ((m0, l0, acc0), k, v, my), None, length=n)
    m, l, acc = state
    return _finalize(m, l, acc, q.dtype), lse_from_state(m, l)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring(q, k, v, axis_name, causal, sm_scale, block_k):
    return _ring_fwd_impl(q, k, v, axis_name, causal, sm_scale, block_k)[0]


def _ring_fwd(q, k, v, axis_name, causal, sm_scale, block_k):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, causal, sm_scale, block_k)
    return out, (q, k, v, out, lse)


def _ring_bwd(axis_name, causal, sm_scale, block_k, res, g):
    """Second ring pass: rotate (k, v, dk, dv) bundles; every device adds its
    local contribution to the visiting block's gradients; after n rotations
    the accumulated dk/dv are home. dq accumulates locally. Fully-future
    causal blocks contribute exactly zero (p recomputed from lse vanishes)."""
    q, k, v, out, lse = res
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    t_q, t_kv = q.shape[1], k.shape[1]
    grp = gqa_group(q, k)
    q_offset = my * t_q
    perm = _ring_perm(n)
    delta = _delta(out, g)

    def ring_step(carry, _):
        dq, k_blk, v_blk, dk, dv, src = carry

        def contrib(_):
            dq_c, dk_c, dv_c = _block_bwd(
                q, rep_group(k_blk, grp), rep_group(v_blk, grp), g, delta,
                lse, causal=causal,
                sm_scale=sm_scale,
                q_offset=q_offset,
                kv_offset=src * t_kv if causal else 0,
            )
            # GQA: fold each query group's contribution back onto its kv
            # head so the rotating dk/dv bundles stay H_kv-wide
            return dq_c, reduce_group(dk_c, grp), reduce_group(dv_c, grp)

        def zeros(_):
            return (jnp.zeros(q.shape, jnp.float32),
                    jnp.zeros(k.shape, jnp.float32),
                    jnp.zeros(k.shape, jnp.float32))

        if causal:
            visible = src * t_kv <= q_offset + t_q - 1
            dq_c, dk_c, dv_c = lax.cond(visible, contrib, zeros, None)
        else:
            dq_c, dk_c, dv_c = contrib(None)
        dq = dq + dq_c
        dk = dk + dk_c
        dv = dv + dv_c
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        src = lax.ppermute(src, axis_name, perm)
        return (dq, k_blk, v_blk, dk, dv, src), None

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dkv0 = jnp.zeros(k.shape, jnp.float32)
    (dq, _, _, dk, dv, _), _ = lax.scan(
        ring_step, (dq0, k, v, dkv0, dkv0, my), None, length=n)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring.defvjp(_ring_fwd, _ring_bwd)


def ring_attention(q, k, v, *, axis_name: str = SEQUENCE_AXIS,
                   causal: bool = False, sm_scale: Optional[float] = None,
                   block_k: int = 256):
    """Attention over a sequence sharded on ``axis_name``.

    Call inside ``shard_map``: ``q``/``k``/``v`` are the local shards
    ``[B, T_local, H, D]`` of a global ``[B, T, H, D]`` sequence laid out
    contiguously by mesh position (shard i holds positions
    ``[i*T_local, (i+1)*T_local)``). K/V may carry fewer (GQA/MQA) heads
    with ``H % H_kv == 0`` — the ring then rotates the H_kv-wide bundle
    (ppermute bytes shrink by the group factor) and broadcasts per fold.
    Returns the local output shard.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _ring(q, k, v, axis_name, causal, sm_scale, block_k)


# --------------------------------------------------------------- zigzag ring


def zigzag_permutation(t: int, n: int):
    """Token permutation for the zigzag (load-balanced causal) layout.

    The sequence is cut into ``2n`` chunks; device ``i`` holds chunks
    ``i`` and ``2n-1-i`` (one early + one late), so every device does the
    same causal work per ring step — the plain contiguous layout leaves
    device 0 skipping almost every visiting block while device n-1 computes
    them all, and the ring's ppermute barrier makes everyone wait for the
    busiest device.

    Returns ``perm`` (np.ndarray) such that ``x[perm]`` is the zigzag
    order: shard ``i`` of the permuted sequence (length ``t/n``) is device
    i's local chunk pair. Invert with ``np.argsort(perm)``.
    """
    import numpy as np

    if t % (2 * n) != 0:
        raise ValueError(
            f"zigzag layout needs sequence length ({t}) divisible by "
            f"2 * axis size ({2 * n})"
        )
    tc = t // (2 * n)
    chunks = np.arange(t).reshape(2 * n, tc)
    order = []
    for i in range(n):
        order.append(chunks[i])
        order.append(chunks[2 * n - 1 - i])
    return np.concatenate(order)


def _zz_offsets(src, tc, n):
    """Global offsets of the two chunks device `src` holds."""
    return src * tc, (2 * n - 1 - src) * tc


def _zz_fwd_impl(q, k, v, axis_name, sm_scale, block_k):
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    tc = t_local // 2
    g = gqa_group(q, k)  # ring rotates the H_kv-wide bundle (see _ring)
    perm = _ring_perm(n)

    qa, qb = q[:, :tc], q[:, tc:]
    my_a, my_b = _zz_offsets(my, tc, n)

    def fold_pair(state, q_sub, q_off, kv_sub, kv_off):
        def merge(s):
            new = _attention_scan(
                q_sub, rep_group(kv_sub[0], g), rep_group(kv_sub[1], g),
                causal=True,
                sm_scale=sm_scale, q_offset=q_off, kv_offset=kv_off,
                block_k=block_k)
            return _merge_state(s, new)

        visible = kv_off <= q_off + tc - 1
        return lax.cond(visible, merge, lambda s: s, state)

    def ring_step(carry, _):
        (sa, sb), k_blk, v_blk, src = carry
        src_a, src_b = _zz_offsets(src, tc, n)
        kva = (k_blk[:, :tc], v_blk[:, :tc])
        kvb = (k_blk[:, tc:], v_blk[:, tc:])
        sa = fold_pair(sa, qa, my_a, kva, src_a)
        sa = fold_pair(sa, qa, my_a, kvb, src_b)
        sb = fold_pair(sb, qb, my_b, kva, src_a)
        sb = fold_pair(sb, qb, my_b, kvb, src_b)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = lax.ppermute(src, axis_name, perm)
        return ((sa, sb), k_blk, v_blk, src), None

    def init_state():
        return (
            jnp.full((b, h, tc), NEG_INF, jnp.float32),
            jnp.zeros((b, h, tc), jnp.float32),
            jnp.zeros((b, h, tc, d), jnp.float32),
        )

    ((sa, sb), _, _, _), _ = lax.scan(
        ring_step, ((init_state(), init_state()), k, v, my), None, length=n)
    out = jnp.concatenate(
        [_finalize(*sa, q.dtype), _finalize(*sb, q.dtype)], axis=1)
    lse = jnp.concatenate(
        [lse_from_state(sa[0], sa[1]), lse_from_state(sb[0], sb[1])],
        axis=2)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _zigzag(q, k, v, axis_name, sm_scale, block_k):
    return _zz_fwd_impl(q, k, v, axis_name, sm_scale, block_k)[0]


def _zigzag_fwd(q, k, v, axis_name, sm_scale, block_k):
    out, lse = _zz_fwd_impl(q, k, v, axis_name, sm_scale, block_k)
    return out, (q, k, v, out, lse)


def _zigzag_bwd(axis_name, sm_scale, block_k, res, g):
    """Second ring pass, per chunk pair: rotate (k, v, dk, dv) bundles and
    add each of the four (q chunk x visiting kv chunk) contributions."""
    q, k, v, out, lse = res
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t_local, h, d = q.shape
    tc = t_local // 2
    grp = gqa_group(q, k)
    perm = _ring_perm(n)
    delta = _delta(out, g)
    my_a, my_b = _zz_offsets(my, tc, n)
    subs = (  # (q chunk, dout chunk, delta rows, lse rows, global offset)
        (q[:, :tc], g[:, :tc], delta[:, :, :tc], lse[:, :, :tc], my_a),
        (q[:, tc:], g[:, tc:], delta[:, :, tc:], lse[:, :, tc:], my_b),
    )

    def ring_step(carry, _):
        dq, k_blk, v_blk, dk, dv, src = carry
        src_offs = _zz_offsets(src, tc, n)
        kv_subs = ((k_blk[:, :tc], v_blk[:, :tc]),
                   (k_blk[:, tc:], v_blk[:, tc:]))
        dq_parts = []
        # per-half accumulators, concatenated once (mirrors the forward's
        # static k_blk[:, :tc] / [:, tc:] split)
        dk_halves = [dk[:, :tc], dk[:, tc:]]
        dv_halves = [dv[:, :tc], dv[:, tc:]]
        for q_sub, g_sub, d_sub, l_sub, q_off in subs:
            dq_sub = jnp.zeros(q_sub.shape, jnp.float32)
            for ki, kv_off in enumerate(src_offs):
                k_sub, v_sub = kv_subs[ki]

                def contrib(_, q_sub=q_sub, g_sub=g_sub, d_sub=d_sub,
                            l_sub=l_sub, q_off=q_off, k_sub=k_sub,
                            v_sub=v_sub, kv_off=kv_off):
                    dq_c, dk_c, dv_c = _block_bwd(
                        q_sub, rep_group(k_sub, grp), rep_group(v_sub, grp),
                        g_sub, d_sub, l_sub,
                        causal=True, sm_scale=sm_scale,
                        q_offset=q_off, kv_offset=kv_off)
                    return (dq_c, reduce_group(dk_c, grp),
                            reduce_group(dv_c, grp))

                def zeros(_, q_sub=q_sub, k_sub=k_sub):
                    z = jnp.zeros(k_sub.shape, jnp.float32)
                    return jnp.zeros(q_sub.shape, jnp.float32), z, z

                visible = kv_off <= q_off + tc - 1
                dq_c, dk_c, dv_c = lax.cond(visible, contrib, zeros, None)
                dq_sub = dq_sub + dq_c
                dk_halves[ki] = dk_halves[ki] + dk_c
                dv_halves[ki] = dv_halves[ki] + dv_c
            dq_parts.append(dq_sub)
        dq = dq + jnp.concatenate(dq_parts, axis=1)
        dk_new = jnp.concatenate(dk_halves, axis=1)
        dv_new = jnp.concatenate(dv_halves, axis=1)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        dk_new = lax.ppermute(dk_new, axis_name, perm)
        dv_new = lax.ppermute(dv_new, axis_name, perm)
        src = lax.ppermute(src, axis_name, perm)
        return (dq, k_blk, v_blk, dk_new, dv_new, src), None

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dkv0 = jnp.zeros(k.shape, jnp.float32)
    (dq, _, _, dk, dv, _), _ = lax.scan(
        ring_step, (dq0, k, v, dkv0, dkv0, my), None, length=n)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_zigzag.defvjp(_zigzag_fwd, _zigzag_bwd)


def zigzag_ring_attention(q, k, v, *, axis_name: str = SEQUENCE_AXIS,
                          sm_scale: Optional[float] = None,
                          block_k: int = 256):
    """Load-balanced CAUSAL ring attention over the zigzag layout.

    Same ring exchange as :func:`ring_attention` (one neighbor ppermute of
    the K/V bundle per step), but the sequence is laid out by
    :func:`zigzag_permutation`: each device holds one early + one late
    chunk, so causal work is equal per device per step instead of device 0
    idling while device n-1 computes every visiting block (the ring's
    ppermute barrier otherwise makes every step as slow as the busiest
    device — up to ~2x causal step time at large n).

    Call inside ``shard_map``; ``q``/``k``/``v`` are local shards
    ``[B, 2*Tc, H, D]`` of the PERMUTED sequence (``x[zigzag_permutation(T,
    n)]`` sharded contiguously). The output comes back in the same zigzag
    layout; invert with ``np.argsort(perm)``. Non-causal attention has no
    imbalance to fix — use :func:`ring_attention`.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if q.shape[1] % 2:
        raise ValueError(
            "zigzag_ring_attention expects local length 2*Tc (one early + "
            "one late chunk per device); got odd local length "
            f"{q.shape[1]}"
        )
    return _zigzag(q, k, v, axis_name, sm_scale, block_k)


def ulysses_attention(q, k, v, *, axis_name: str = SEQUENCE_AXIS,
                      causal: bool = False, sm_scale: Optional[float] = None,
                      attention_fn=None):
    """All-to-all sequence parallelism (DeepSpeed Ulysses): trade the
    sequence sharding for a head sharding, attend full-length, trade back.

    Inside ``shard_map`` with ``q``/``k``/``v`` local shards
    ``[B, T_local, H, D]``; requires ``H % axis_size == 0``. K/V may carry
    fewer (GQA/MQA) heads: the exchange then moves the smallest shardable
    head count, so a custom ``attention_fn`` must itself accept K/V with
    fewer heads than Q (the default flash path does); pass pre-repeated
    K/V if yours cannot.
    """
    n = lax.axis_size(axis_name)
    h, h_kv = q.shape[2], k.shape[2]
    if h % n:
        raise ValueError(
            f"ulysses_attention needs heads ({h}) divisible by the "
            f"'{axis_name}' axis size ({n}); use ring_attention instead"
        )
    gqa_group(q, k)  # validate divisibility
    if h_kv % n != 0:
        # no head sharding exists at h_kv (e.g. MQA with h_kv < n): repeat
        # only up to lcm(h_kv, n) — the smallest head count that both
        # splits over the axis and divides h (h is a common multiple of
        # h_kv and n, so the lcm divides h) — not all the way to H
        import math

        target = h_kv * n // math.gcd(h_kv, n)
        factor = target // h_kv
        k, v = rep_group(k, factor), rep_group(v, factor)
    # the K/V all-to-alls exchange the smallest shardable head count; the
    # local flash call broadcasts the remaining group per block
    if attention_fn is None:
        from horovod_tpu.ops.flash_attention import flash_attention

        attention_fn = flash_attention

    def seq_to_heads(x):
        # [B, T/n, H, D] -> [B, T, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = attention_fn(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return heads_to_seq(out)
