"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

Long-context capability has no counterpart in the reference (Horovod 0.19.2
shards only the batch axis — SURVEY.md §5.7); this is the TPU-native
extension the mesh layer (:mod:`horovod_tpu.parallel.mesh`) reserves the
``seq`` axis for. Two strategies:

- :func:`ring_attention` — blockwise attention with K/V blocks rotating
  around the ring via ``lax.ppermute`` (Liu et al., "Ring Attention with
  Blockwise Transformers"). Each device holds ``T/n`` of the sequence; per
  ring step it attends its local queries against the visiting K/V block and
  folds the result into online-softmax accumulators. ICI neighbor exchange
  overlaps with the block matmuls (XLA schedules the ppermute concurrently
  with compute), so the collective cost hides behind the MXU work.
- :func:`ulysses_attention` — DeepSpeed-Ulysses-style all-to-all: re-shard
  from sequence-sharded to head-sharded with ``lax.all_to_all``, run plain
  (flash) attention on full-length sequences per head group, and all-to-all
  back. Cheaper at moderate context (2 all-to-alls vs n-1 permutes) but
  requires ``heads % axis_size == 0``.

Both are pure functions of per-shard values, designed to be called inside
``shard_map``/``pjit`` over a mesh built by
:func:`horovod_tpu.parallel.mesh.build_mesh`, and both are differentiable
(ring backward rotates gradients the opposite direction via transposed
ppermute, which JAX derives automatically from the scan).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.ops.flash_attention import (
    NEG_INF,
    _attention_scan,
    _finalize,
)
from horovod_tpu.parallel.mesh import SEQUENCE_AXIS


def ring_attention(q, k, v, *, axis_name: str = SEQUENCE_AXIS,
                   causal: bool = False, sm_scale: Optional[float] = None,
                   block_k: int = 256):
    """Attention over a sequence sharded on ``axis_name``.

    Call inside ``shard_map``: ``q``/``k``/``v`` are the local shards
    ``[B, T_local, H, D]`` of a global ``[B, T, H, D]`` sequence laid out
    contiguously by mesh position (shard i holds positions
    ``[i*T_local, (i+1)*T_local)``). Returns the local output shard.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t_q, h, d = q.shape
    t_kv = k.shape[1]

    q_offset = my * t_q

    def fold(carry, kv_src, kv):
        """Fold the K/V block owned by device `kv_src` into (m, l, acc)."""
        m, l, acc = carry
        k_blk, v_blk = kv
        if causal:
            kv_offset = kv_src * t_kv
            # skip blocks fully in the causal future without materializing
            # the scores: all-masked blocks keep the carry unchanged
            block_visible = kv_offset <= q_offset + t_q - 1
            m2, l2, acc2 = _attention_scan(
                q, k_blk, v_blk, causal=True, sm_scale=sm_scale,
                q_offset=q_offset, kv_offset=kv_offset, block_k=block_k)
        else:
            block_visible = True
            m2, l2, acc2 = _attention_scan(
                q, k_blk, v_blk, causal=False, sm_scale=sm_scale,
                q_offset=0, kv_offset=0, block_k=block_k)
        # merge two online-softmax partial states
        m_new = jnp.maximum(m, m2)
        a1 = jnp.exp(m - m_new)
        a2 = jnp.where(m2 > NEG_INF / 2, jnp.exp(m2 - m_new), 0.0)
        l_new = l * a1 + l2 * a2
        acc_new = acc * a1[..., None] + acc2 * a2[..., None]
        if causal:
            keep = block_visible
            m_new = jnp.where(keep, m_new, m)
            l_new = jnp.where(keep, l_new, l)
            acc_new = jnp.where(keep, acc_new, acc)
        return m_new, l_new, acc_new

    perm = [(i, (i + 1) % n) for i in range(n)]

    def ring_step(carry, _):
        state, (k_blk, v_blk), src = carry
        state = fold(state, src, (k_blk, v_blk))
        # rotate: each device hands its current block to the next neighbor,
        # so after n-1 steps every device has seen every block (ICI ring)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = lax.ppermute(src, axis_name, perm)
        return (state, (k_blk, v_blk), src), None

    m0 = jnp.full((b, h, t_q), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, t_q), jnp.float32)
    acc0 = jnp.zeros((b, h, t_q, d), jnp.float32)
    init = ((m0, l0, acc0), (k, v), my)
    (state, _, _), _ = lax.scan(ring_step, init, None, length=n)
    m, l, acc = state
    return _finalize(m, l, acc, q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str = SEQUENCE_AXIS,
                      causal: bool = False, sm_scale: Optional[float] = None,
                      attention_fn=None):
    """All-to-all sequence parallelism (DeepSpeed Ulysses): trade the
    sequence sharding for a head sharding, attend full-length, trade back.

    Inside ``shard_map`` with ``q``/``k``/``v`` local shards
    ``[B, T_local, H, D]``; requires ``H % axis_size == 0``.
    """
    n = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % n:
        raise ValueError(
            f"ulysses_attention needs heads ({h}) divisible by the "
            f"'{axis_name}' axis size ({n}); use ring_attention instead"
        )
    if attention_fn is None:
        from horovod_tpu.ops.flash_attention import flash_attention

        attention_fn = flash_attention

    def seq_to_heads(x):
        # [B, T/n, H, D] -> [B, T, H/n, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = attention_fn(qh, kh, vh, causal=causal, sm_scale=sm_scale)
    return heads_to_seq(out)
