"""Expert parallelism (Mixture-of-Experts) over the ``expert`` mesh axis.

No reference counterpart (SURVEY.md §2.7); TPU-native extension in the
GShard/Switch formulation, which is the shape XLA lowers best: routing as
one-hot einsum dispatch (dense matmuls on the MXU, no gather/scatter), token
exchange as a single ``lax.all_to_all`` per direction riding ICI.

Top-1 (Switch) routing with a static capacity factor: each token picks its
highest-gate expert; tokens beyond an expert's capacity are dropped (output
falls back to zero for them — the standard Switch behavior). Dispatch and
combine are the transpose of each other, so the layer is differentiable end
to end, router included (straight-through on the gate value).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from horovod_tpu.parallel.mesh import EXPERT_AXIS


def _build_dispatch(onehot, pos, gate, capacity):
    """[T,E,C] 0/1 dispatch + gate-weighted combine for one routing choice:
    token t lands in expert e's buffer slot pos[t] when it fits."""
    keep = pos < capacity
    pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                            dtype=jnp.float32)               # [T, C]
    d = onehot[:, :, None] * pos_oh[:, None, :] * keep[:, None, None]
    return d, d * gate[:, None, None]


def top1_dispatch(gates_logits, capacity: int):
    """Switch-style top-1 routing tensors.

    Args:
      gates_logits: ``[T, E]`` router logits for T local tokens, E experts.
      capacity: per-expert buffer slots C.

    Returns:
      (dispatch ``[T, E, C]`` 0/1, combine ``[T, E, C]`` gate-weighted,
       aux_loss scalar — the Switch load-balancing loss).
    """
    t, e = gates_logits.shape
    gates = jax.nn.softmax(gates_logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)                  # [T]
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [T, E]

    # position of each token within its expert's buffer (0-based; masked to
    # the selected expert BEFORE summing so other columns contribute nothing)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot        # [T, E]
    pos_in_expert = pos.sum(axis=-1)                         # [T]
    gate_val = (gates * onehot).sum(axis=-1)                 # [T]
    dispatch, combine = _build_dispatch(
        onehot, pos_in_expert, gate_val, capacity)

    # load-balancing aux loss (Switch Transformer eq. 4)
    density = onehot.mean(axis=0)
    density_proxy = gates.mean(axis=0)
    aux = (density * density_proxy).sum() * e
    return dispatch, combine, aux


def top2_dispatch(gates_logits, capacity: int):
    """GShard-style top-2 routing tensors (the GShard default; top-1 is the
    Switch simplification).

    Each token goes to its two highest-gate experts with combine weights
    renormalized over the pair. Buffer positions for second choices come
    after ALL first choices of that expert, so under pressure second
    choices drop first (the GShard policy). Same return shape/contract as
    :func:`top1_dispatch`.
    """
    t, e = gates_logits.shape
    gates = jax.nn.softmax(gates_logits.astype(jnp.float32), axis=-1)
    idx1 = jnp.argmax(gates, axis=-1)                        # [T]
    oh1 = jax.nn.one_hot(idx1, e, dtype=jnp.float32)
    gates2 = gates * (1.0 - oh1)                             # mask choice 1
    idx2 = jnp.argmax(gates2, axis=-1)
    oh2 = jax.nn.one_hot(idx2, e, dtype=jnp.float32)

    g1 = (gates * oh1).sum(axis=-1)
    g2 = (gates * oh2).sum(axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    pos1 = ((jnp.cumsum(oh1, axis=0) - 1.0) * oh1).sum(axis=-1)   # [T]
    count1 = oh1.sum(axis=0)                                 # [E]
    pos2_e = (jnp.cumsum(oh2, axis=0) - 1.0) * oh2 + count1[None, :] * oh2
    pos2 = pos2_e.sum(axis=-1)                               # [T]

    d1, c1 = _build_dispatch(oh1, pos1, g1, capacity)
    d2, c2 = _build_dispatch(oh2, pos2, g2, capacity)

    # aux loss on FIRST choices (GShard eq: fraction routed x mean gate)
    density = oh1.mean(axis=0)
    density_proxy = gates.mean(axis=0)
    aux = (density * density_proxy).sum() * e
    return d1 + d2, c1 + c2, aux


def expert_parallel_moe(router_params, expert_params, x, expert_fn: Callable,
                        *, axis_name: str = EXPERT_AXIS,
                        capacity_factor: float = 2.0,
                        routing: str = "top1"):
    """Apply an expert-parallel MoE FFN inside ``shard_map``.

    Args:
      router_params: ``[D, E_total]`` router weight (replicated).
      expert_params: this shard's experts' params, leading dim
        ``E_local = E_total / axis_size``.
      x: local tokens ``[T, D]`` (the caller's batch/seq shard).
      expert_fn: ``(one_expert_params, tokens [C', D]) -> [C', D]``, vmapped
        over local experts.
      capacity_factor: C = ceil(T / E_total * factor).
      routing: ``"top1"`` (Switch) or ``"top2"`` (GShard default).

    Returns:
      (output ``[T, D]``, aux_loss scalar)
    """
    n = lax.axis_size(axis_name)
    t, d = x.shape
    e_local = jax.tree_util.tree_leaves(expert_params)[0].shape[0]
    e_total = e_local * n
    capacity = max(int(-(-t * capacity_factor // e_total)), 1)  # ceil, static

    try:
        dispatch_fn = {"top1": top1_dispatch, "top2": top2_dispatch}[routing]
    except KeyError:
        raise ValueError(
            f"routing must be 'top1' or 'top2', got {routing!r}"
        ) from None

    logits = x.astype(jnp.float32) @ router_params   # [T, E_total]
    dispatch, combine, aux = dispatch_fn(logits, capacity)

    # dispatch MY tokens into per-expert buffers: [E_total, C, D], ordered so
    # block [k*E_local, (k+1)*E_local) belongs to shard k's experts
    expert_in = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    # exchange: shard k receives ITS experts' buffers from every shard,
    # stacked on the capacity axis -> [E_local, n*C, D]
    expert_in = lax.all_to_all(expert_in, axis_name, split_axis=0,
                               concat_axis=1, tiled=True)

    out = jax.vmap(expert_fn)(expert_params, expert_in)      # [E_local, n*C, D]

    # inverse exchange: every shard gets back its C slots from each expert
    # -> [E_total, C, D] in the same global-expert order as dispatch
    out = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=0,
                         tiled=True)
    y = jnp.einsum("tec,ecd->td", combine, out)
    # aux loss averaged over shards (each shard routed its own tokens)
    aux = lax.pmean(aux, axis_name)
    return y.astype(x.dtype), aux
