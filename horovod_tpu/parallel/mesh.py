"""Device-mesh construction.

Horovod's communicator topology is GLOBAL / LOCAL (per node) / CROSS (same
local_rank across nodes) built by MPI comm-split or triple Gloo rendezvous
(reference ``horovod/common/common.h:111-115``, ``gloo_context.cc:143-156``).
The TPU-native equivalent is a named ``jax.sharding.Mesh``: LOCAL maps to the
intra-host slice of an axis (ICI, no network), CROSS to the inter-host slice
(DCN), and GLOBAL to the full axis. XLA's collective lowering picks
ICI vs DCN per axis automatically, so we only need axis *names* here.

Canonical axis names (only ``data`` exists in the reference's capability
surface; the rest are TPU-native extension axes used by
``horovod_tpu.parallel``):
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np
import jax

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPELINE_AXIS = "pipe"
SEQUENCE_AXIS = "seq"
EXPERT_AXIS = "expert"
#: parameter-sharding (FSDP / ZeRO-3) axis: parameters live reduce-scattered
#: over it and are re-gathered on use (:func:`horovod_tpu.optim.
#: fsdp_pack_params` + ``DistributedOptimizer(shard_params=True)``).
FSDP_AXIS = "fsdp"
#: tensor-parallel axis: Megatron column/row matmul splits
#: (:func:`horovod_tpu.models.transformer.tp_block_apply`) and head-sharded
#: decode attention (:func:`horovod_tpu.ops.flash_attention.
#: tp_paged_decode_attention`).
TP_AXIS = "tp"
#: host-hierarchy axes (Horovod CROSS/LOCAL communicators,
#: ``common/common.h:111-115``): ``cross`` = inter-host (DCN), ``local`` =
#: intra-host (ICI). Used by :mod:`horovod_tpu.ops.hierarchical`.
CROSS_AXIS = "cross"
LOCAL_AXIS = "local"

#: default axis order when building multi-axis meshes; data outermost so that
#: DP shards ride DCN across hosts while model/seq axes stay on intra-host ICI
#: (the bandwidth hierarchy argument from the scaling playbook). ``fsdp``
#: sits right inside ``data`` (its per-bucket all-gathers are the fattest
#: recurring transfers, so they get the better links), ``tp`` innermost
#: (one psum per block pair — latency-bound, wants pure ICI).
AXIS_ORDER = (DATA_AXIS, FSDP_AXIS, EXPERT_AXIS, PIPELINE_AXIS,
              SEQUENCE_AXIS, MODEL_AXIS, TP_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative ``("data", "fsdp", "tp")`` mesh spec.

    The canonical 3-D hybrid layout: pure DP replicas outermost, parameter
    shards (ZeRO-3) in the middle, tensor-parallel innermost. Axis lengths
    multiply to the device count (one may be ``-1`` to fill), and unused
    axes stay at length 1 — a ``MeshConfig((8, 1, 1))`` IS the Horovod
    topology. ``build()`` lowers through :func:`build_mesh`, so the
    :data:`AXIS_ORDER` outer-to-inner discipline (DP over DCN, TP over
    ICI) and device-order preservation apply unchanged::

        mesh = MeshConfig((2, 2, 2)).build()   # 8 chips: DP x FSDP x TP
    """

    axis_lengths: Tuple[int, ...]
    axis_names: Tuple[str, ...] = (DATA_AXIS, FSDP_AXIS, TP_AXIS)

    def __post_init__(self):
        if len(self.axis_lengths) != len(self.axis_names):
            raise ValueError(
                f"axis_lengths {self.axis_lengths} and axis_names "
                f"{self.axis_names} must have equal rank"
            )
        for name, length in zip(self.axis_names, self.axis_lengths):
            if length != -1 and length <= 0:
                raise ValueError(
                    f"axis {name!r} must have positive length (or -1 to "
                    f"fill), got {length}"
                )

    @property
    def axes(self) -> dict:
        return dict(zip(self.axis_names, self.axis_lengths))

    def build(self, devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
        return build_mesh(axes=self.axes, devices=devices)


def build_mesh(
    axes: Optional[dict] = None,
    devices: Optional[Sequence] = None,
) -> jax.sharding.Mesh:
    """Build the global mesh.

    Args:
      axes: mapping axis-name -> size; at most one size may be ``-1`` (fills
        with remaining devices). Default ``{"data": -1}``: a 1-D DP mesh over
        every chip — the Horovod topology.
      devices: device subset (defaults to ``jax.devices()``). Order is
        preserved: JAX returns TPU devices in physical-torus-friendly order, so
        a contiguous reshape keeps neighboring mesh coordinates on neighboring
        ICI links.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    if axes is None:
        axes = {DATA_AXIS: -1}

    names = [a for a in AXIS_ORDER if a in axes]
    names += [a for a in axes if a not in names]  # user-custom axes last
    sizes = [axes[a] for a in names]

    n_wild = sum(1 for s in sizes if s == -1)
    if n_wild > 1:
        raise ValueError(f"at most one axis size may be -1, got {axes}")
    fixed = int(np.prod([s for s in sizes if s != -1])) if sizes else 1
    if n_wild == 1:
        if n % fixed != 0:
            raise ValueError(
                f"device count {n} not divisible by fixed axes product {fixed}"
            )
        sizes = [n // fixed if s == -1 else s for s in sizes]
    elif fixed != n:
        raise ValueError(f"axes product {fixed} != device count {n}")

    dev_array = np.asarray(devices).reshape(sizes)
    return jax.sharding.Mesh(dev_array, tuple(names))


def build_host_mesh(local: Optional[int] = None,
                    devices: Optional[Sequence] = None) -> jax.sharding.Mesh:
    """Build the ``(cross, local)`` host-hierarchy mesh.

    ``local`` defaults to the chips this process can see per host
    (``jax.local_device_count()``). ``cross`` (outer, so each host owns a
    contiguous device block) fills with the remaining devices. The Horovod
    analog is the LOCAL comm-split by hostname + CROSS split by local rank
    (reference ``gloo_context.cc:143-156``)."""
    if local is None:
        local = jax.local_device_count()
    return build_mesh(axes={CROSS_AXIS: -1, LOCAL_AXIS: local},
                      devices=devices)
